"""Categorical one-hot vectorizers: PickList / text pivot / MultiPickList.

TPU-native ports of the reference one-hot family
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
OpOneHotVectorizer.scala and its OpSetVectorizer / OpTextPivotVectorizer
subclasses). Semantics preserved:

- fit counts category occurrences per input feature, keeps the top-K
  (TransmogrifierDefaults.TopK = 20) with count >= min_support (= 10),
- transform pivots each value into [cat_1 .. cat_K, OTHER, NULL] columns;
  unseen/overflow categories light the OTHER column, empties the NULL one,
- vector metadata records each category as an ``indicator_value`` grouped
  by the parent feature, which is what SanityChecker's Cramér's V and
  group-aware pruning key off.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import SequenceEstimator, SequenceModel
from ..types import OPSet, OPVector, Text
from .vector_utils import (NULL_INDICATOR, OTHER_INDICATOR,
                           VectorColumnMetadata, vector_output)

__all__ = ["OneHotVectorizer", "OneHotVectorizerModel",
           "MultiPickListVectorizer", "MultiPickListVectorizerModel"]


def _top_categories(counts: dict, top_k: int, min_support: int) -> List[str]:
    items = [(c, v) for c, v in counts.items() if v >= min_support]
    # count desc, then lexical for determinism (reference sorts by count)
    items.sort(key=lambda cv: (-cv[1], cv[0]))
    return [c for c, _ in items[:top_k]]


def _pivot_block(values_per_row: List[Optional[Sequence[str]]],
                 cats: List[str], track_nulls: bool) -> np.ndarray:
    """values_per_row: None = missing, else iterable of category strings."""
    n = len(values_per_row)
    width = len(cats) + 1 + (1 if track_nulls else 0)
    block = np.zeros((n, width), dtype=np.float64)
    index = {c: i for i, c in enumerate(cats)}
    other_col = len(cats)
    null_col = len(cats) + 1
    for i, vals in enumerate(values_per_row):
        if vals is None or len(vals) == 0:
            if track_nulls:
                block[i, null_col] = 1.0
            continue
        for v in vals:
            j = index.get(v)
            if j is None:
                block[i, other_col] = 1.0
            else:
                block[i, j] = 1.0
    return block


def _pivot_metas(feature, cats: List[str], track_nulls: bool
                 ) -> List[VectorColumnMetadata]:
    metas = [VectorColumnMetadata(
        parent_feature_name=feature.name,
        parent_feature_type=feature.ftype.__name__,
        grouping=feature.name, indicator_value=c) for c in cats]
    metas.append(VectorColumnMetadata(
        parent_feature_name=feature.name,
        parent_feature_type=feature.ftype.__name__,
        grouping=feature.name, indicator_value=OTHER_INDICATOR))
    if track_nulls:
        metas.append(VectorColumnMetadata(
            parent_feature_name=feature.name,
            parent_feature_type=feature.ftype.__name__,
            grouping=feature.name, indicator_value=NULL_INDICATOR))
    return metas


class OneHotVectorizerModel(SequenceModel):
    input_types = (Text,)
    output_type = OPVector

    def __init__(self, categories: List[List[str]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="pivotText", uid=uid)
        self.categories = [list(c) for c in categories]
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, cats in zip(self.input_features, cols, self.categories):
            rows = [None if v is None else (v,) for v in col.data]
            blocks.append(_pivot_block(rows, cats, self.track_nulls))
            metas.extend(_pivot_metas(f, cats, self.track_nulls))
        return vector_output(self.get_output().name, blocks, metas)

    # -- compiled-serving lowering (serving/plan.py): the trained
    # category->index lookup runs on host, the one-hot expansion on
    # device. Index layout: [0..K-1] categories, K = OTHER, K+1 = NULL
    # (or -1 = all-zero row when nulls are untracked).
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        cats = self.categories[i]
        index = {c: j for j, c in enumerate(cats)}
        other = len(cats)
        null = other + 1 if self.track_nulls else -1
        get = index.get
        # one C-allocated pass (np.fromiter) — this encoder is the
        # train-prepare hot loop for wide categorical data
        return np.fromiter(
            (null if v is None else get(v, other) for v in col.data),
            dtype=np.int32, count=col.n_rows)

    def transform_arrays(self, arrays):
        import jax
        import jax.numpy as jnp
        blocks = []
        for idx, cats in zip(arrays, self.categories):
            width = len(cats) + 1 + (1 if self.track_nulls else 0)
            blocks.append(jax.nn.one_hot(idx, width))
        return jnp.concatenate(blocks, axis=1)


class OneHotVectorizer(SequenceEstimator):
    """Top-K one-hot pivot for categorical text features
    (reference OpOneHotVectorizer.scala / OpTextPivotVectorizer)."""

    input_types = (Text,)
    output_type = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="pivotText", uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    def fit_columns(self, cols: List[FeatureColumn]) -> OneHotVectorizerModel:
        categories = []
        for col in cols:
            counts: dict = {}
            for v in col.data:
                if v is not None:
                    counts[v] = counts.get(v, 0) + 1
            categories.append(
                _top_categories(counts, self.top_k, self.min_support))
        return OneHotVectorizerModel(categories=categories,
                                     track_nulls=self.track_nulls)


class MultiPickListVectorizerModel(SequenceModel):
    input_types = (OPSet,)
    output_type = OPVector

    def __init__(self, categories: List[List[str]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="pivotSet", uid=uid)
        self.categories = [list(c) for c in categories]
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, cats in zip(self.input_features, cols, self.categories):
            rows = [None if v is None else tuple(v) for v in col.data]
            blocks.append(_pivot_block(rows, cats, self.track_nulls))
            metas.extend(_pivot_metas(f, cats, self.track_nulls))
        return vector_output(self.get_output().name, blocks, metas)

    # -- compiled-serving lowering: set membership is inherently a host
    # dict walk, so the encoder emits the multi-hot block directly
    # (EXACTLY _pivot_block, so parity is structural) and the kernel is
    # the concat that fuses it into the downstream program.
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        rows = [None if v is None else tuple(v) for v in col.data]
        return _pivot_block(rows, self.categories[i], self.track_nulls)

    def transform_arrays(self, arrays):
        import jax.numpy as jnp
        return jnp.concatenate(arrays, axis=1)


class MultiPickListVectorizer(SequenceEstimator):
    """Top-K multi-hot pivot for set features
    (reference OpSetVectorizer in OpOneHotVectorizer.scala)."""

    input_types = (OPSet,)
    output_type = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="pivotSet", uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> MultiPickListVectorizerModel:
        categories = []
        for col in cols:
            counts: dict = {}
            for vals in col.data:
                if vals:
                    for v in vals:
                        counts[v] = counts.get(v, 0) + 1
            categories.append(
                _top_categories(counts, self.top_k, self.min_support))
        return MultiPickListVectorizerModel(categories=categories,
                                            track_nulls=self.track_nulls)
