"""VectorsCombiner: concatenate OPVector features into one.

TPU-native port of core/src/main/scala/com/salesforce/op/stages/impl/
feature/VectorsCombiner.scala:51,85 — concatenates vector columns and
flattens their metadata. Columnar execution makes this a single
``np.concatenate``; the reference needed a Spark SequenceEstimator pass.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import SequenceTransformer
from ..types import OPVector
from ..utils.vector_meta import VectorColumnMetadata, VectorMetadata

__all__ = ["VectorsCombiner"]


class VectorsCombiner(SequenceTransformer):
    input_types = (OPVector,)
    output_type = OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="combineVector", uid=uid)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        mats, metas = [], []
        out_name = self.get_output().name
        for f, col in zip(self.input_features, cols):
            if col.kind != "vector":
                raise TypeError(
                    f"VectorsCombiner input {f.name!r} is not a vector")
            mats.append(col.data)
            meta = col.metadata
            if meta is None or meta.size != col.data.shape[1]:
                # raw vectors (no vectorizer provenance) get anonymous
                # per-column records so flatten stays index-consistent
                meta = VectorMetadata(name=f.name, columns=tuple(
                    VectorColumnMetadata(parent_feature_name=f.name,
                                         parent_feature_type="OPVector")
                    for _ in range(col.data.shape[1])))
            metas.append(meta)
        mat = (np.concatenate(mats, axis=1) if mats
               else np.zeros((0, 0), dtype=np.float64))
        return FeatureColumn.vector(
            mat, VectorMetadata.flatten(out_name, metas))

    def transform_arrays(self, arrays):
        # the fusion seam of the compiled plan: every vectorizer kernel
        # feeds this one concat, handing XLA the whole feature matrix
        import jax.numpy as jnp
        return jnp.concatenate(arrays, axis=1)
