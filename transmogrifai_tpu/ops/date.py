"""Date/time vectorization: unit-circle projection.

TPU-native port of the reference DateToUnitCircleTransformer
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
DateToUnitCircleTransformer.scala): a timestamp's periodic component
(hour of day, day of week, ...) is mapped to (sin, cos) on the unit
circle so midnight and 23:59 are close in feature space. Timestamps are
epoch milliseconds UTC, as in the reference (joda DateTimeUtils).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import SequenceTransformer
from ..types import Date, OPVector
from .vector_utils import VectorColumnMetadata, vector_output

__all__ = ["DateToUnitCircleVectorizer", "DateListVectorizer",
           "TIME_PERIODS", "DateListPivot"]

_MS_PER_HOUR = 3600 * 1000
_MS_PER_DAY = 24 * _MS_PER_HOUR

#: period -> (extractor of phase in [0, 1), period name)
TIME_PERIODS = {
    "HourOfDay": lambda ms: (ms % _MS_PER_DAY) / _MS_PER_DAY,
    # epoch day 0 (1970-01-01) was a Thursday = ISO day-of-week 4
    "DayOfWeek": lambda ms: (((ms // _MS_PER_DAY) + 3) % 7) / 7.0,
    "DayOfMonth": lambda ms: _day_of_month_phase(ms),
    "MonthOfYear": lambda ms: _month_phase(ms),
}


def _civil_from_ms(ms: np.ndarray):
    days = ms // _MS_PER_DAY
    # days-from-civil inverse (Howard Hinnant's algorithm), vectorized
    z = days + 719468
    era = np.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    return m.astype(np.int64), d.astype(np.int64)


def _day_of_month_phase(ms: np.ndarray) -> np.ndarray:
    _, d = _civil_from_ms(ms)
    return (d - 1) / 31.0


def _month_phase(ms: np.ndarray) -> np.ndarray:
    m, _ = _civil_from_ms(ms)
    return (m - 1) / 12.0


def _unit_circle_kernel(arrays):
    """(sin, cos) projection of host-encoded [0, 1) phases, NaN =
    missing -> (0, 0); one (n,) or (n, k) phase array per input, 2 (or
    2k, interleaved sin/cos per key) output columns each."""
    import jax.numpy as jnp
    blocks = []
    for p in arrays:
        ok = ~jnp.isnan(p)
        ang = 2.0 * jnp.pi * jnp.where(ok, p, 0.0)
        zero = jnp.zeros_like(ang)
        sin = jnp.where(ok, jnp.sin(ang), zero)
        cos = jnp.where(ok, jnp.cos(ang), zero)
        block = jnp.stack([sin, cos], axis=-1)
        blocks.append(block.reshape(block.shape[0], -1))
    return jnp.concatenate(blocks, axis=1)


class DateListPivot:
    """(reference DateListPivot enum in DateListVectorizer.scala)"""
    SINCE_FIRST = "SinceFirst"
    SINCE_LAST = "SinceLast"
    MODE_DAY = "ModeDay"
    MODE_MONTH = "ModeMonth"
    MODE_HOUR = "ModeHour"
    ALL = (SINCE_FIRST, SINCE_LAST, MODE_DAY, MODE_MONTH, MODE_HOUR)


_DAYS = ["Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun"]
_MONTHS = ["Jan", "Feb", "Mar", "Apr", "May", "Jun", "Jul", "Aug", "Sep",
           "Oct", "Nov", "Dec"]


class DateListVectorizer(SequenceTransformer):
    """DateList -> pivoted columns (reference DateListVectorizer.scala):
    SinceFirst/SinceLast = days between the earliest/latest date and
    ``reference_date_ms``; ModeDay/ModeMonth/ModeHour = one-hot of the
    most frequent day-of-week / month / hour across the list."""

    from ..types import DateList as _DateList
    input_types = (_DateList,)
    output_type = OPVector

    def __init__(self, pivot: str = DateListPivot.SINCE_FIRST,
                 reference_date_ms: int = 1_500_000_000_000,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="dateListPivot", uid=uid)
        if pivot not in DateListPivot.ALL:
            raise ValueError(f"Unknown pivot {pivot!r}")
        self.pivot = pivot
        self.reference_date_ms = reference_date_ms
        self.track_nulls = track_nulls

    def _one_hot(self, picks, n_levels, labels, f):
        n = len(picks)
        block = np.zeros((n, n_levels))
        isnull = np.zeros(n)
        for i, p in enumerate(picks):
            if p is None:
                isnull[i] = 1.0
            else:
                block[i, p] = 1.0
        blocks = [block]
        metas = [VectorColumnMetadata(
            parent_feature_name=f.name,
            parent_feature_type=f.ftype.__name__, grouping=f.name,
            indicator_value=lab) for lab in labels]
        if self.track_nulls:
            blocks.append(isnull)
            from .vector_utils import NULL_INDICATOR
            metas.append(VectorColumnMetadata(
                parent_feature_name=f.name,
                parent_feature_type=f.ftype.__name__, grouping=f.name,
                indicator_value=NULL_INDICATOR))
        return blocks, metas

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col in zip(self.input_features, cols):
            lists = [sorted(v) if v else None for v in col.data]
            if self.pivot in (DateListPivot.SINCE_FIRST,
                              DateListPivot.SINCE_LAST):
                pick = 0 if self.pivot == DateListPivot.SINCE_FIRST else -1
                days = np.zeros(len(lists))
                isnull = np.zeros(len(lists))
                for i, v in enumerate(lists):
                    if v is None:
                        isnull[i] = 1.0
                    else:
                        days[i] = (self.reference_date_ms - v[pick]) \
                            / _MS_PER_DAY
                blocks.append(days)
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__,
                    descriptor_value=self.pivot))
                if self.track_nulls:
                    from .vector_utils import NULL_INDICATOR
                    blocks.append(isnull)
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        indicator_value=NULL_INDICATOR))
                continue
            picks = []
            for v in lists:
                if v is None:
                    picks.append(None)
                    continue
                ms = np.asarray(v, dtype=np.int64)
                if self.pivot == DateListPivot.MODE_DAY:
                    units = ((ms // _MS_PER_DAY) + 3) % 7
                elif self.pivot == DateListPivot.MODE_MONTH:
                    units, _ = _civil_from_ms(ms)
                    units = units - 1
                else:  # MODE_HOUR
                    units = (ms % _MS_PER_DAY) // _MS_PER_HOUR
                vals, counts = np.unique(units, return_counts=True)
                picks.append(int(vals[np.argmax(counts)]))
            if self.pivot == DateListPivot.MODE_DAY:
                b, m = self._one_hot(picks, 7, _DAYS, f)
            elif self.pivot == DateListPivot.MODE_MONTH:
                b, m = self._one_hot(picks, 12, _MONTHS, f)
            else:
                b, m = self._one_hot(picks, 24,
                                     [f"{h:02d}h" for h in range(24)], f)
            blocks.extend(b)
            metas.extend(m)
        return vector_output(self.get_output().name, blocks, metas)


class DateToUnitCircleVectorizer(SequenceTransformer):
    """Date(s) -> [sin, cos] per time period, null-safe (missing -> 0,0)."""

    input_types = (Date,)
    output_type = OPVector

    def __init__(self, time_period: str = "HourOfDay",
                 uid: Optional[str] = None):
        super().__init__(operation_name="toUnitCircle", uid=uid)
        if time_period not in TIME_PERIODS:
            raise ValueError(
                f"Unknown time period {time_period!r}; "
                f"choose from {sorted(TIME_PERIODS)}")
        self.time_period = time_period

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        phase_fn = TIME_PERIODS[self.time_period]
        blocks, metas = [], []
        for f, col in zip(self.input_features, cols):
            vals = np.asarray(col.data, dtype=np.float64)
            ok = ~np.isnan(vals)
            ms = np.where(ok, vals, 0.0).astype(np.int64)
            phase = 2.0 * np.pi * np.asarray(phase_fn(ms), dtype=np.float64)
            block = np.zeros((len(vals), 2), dtype=np.float64)
            block[:, 0] = np.where(ok, np.sin(phase), 0.0)
            block[:, 1] = np.where(ok, np.cos(phase), 0.0)
            blocks.append(block)
            for trig in ("sin", "cos"):
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__,
                    descriptor_value=f"{trig}({self.time_period})"))
        return vector_output(self.get_output().name, blocks, metas)

    # -- compiled-serving lowering: the calendar arithmetic needs int64
    # epoch math (f32 on device would lose ~1e5 ms of precision on
    # current timestamps), so the encoder computes the [0, 1) phase on
    # host in the SAME numpy code as transform_columns; the device
    # kernel is the trig projection, which fuses.
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        phase_fn = TIME_PERIODS[self.time_period]
        vals = np.asarray(col.data, dtype=np.float64)
        ok = ~np.isnan(vals)
        ms = np.where(ok, vals, 0.0).astype(np.int64)
        phase = np.asarray(phase_fn(ms), dtype=np.float64)
        return np.where(ok, phase, np.nan)

    def transform_arrays(self, arrays):
        return _unit_circle_kernel(arrays)
