"""Date/time vectorization: unit-circle projection.

TPU-native port of the reference DateToUnitCircleTransformer
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
DateToUnitCircleTransformer.scala): a timestamp's periodic component
(hour of day, day of week, ...) is mapped to (sin, cos) on the unit
circle so midnight and 23:59 are close in feature space. Timestamps are
epoch milliseconds UTC, as in the reference (joda DateTimeUtils).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import SequenceTransformer
from ..types import Date, OPVector
from .vector_utils import VectorColumnMetadata, vector_output

__all__ = ["DateToUnitCircleVectorizer", "TIME_PERIODS"]

_MS_PER_HOUR = 3600 * 1000
_MS_PER_DAY = 24 * _MS_PER_HOUR

#: period -> (extractor of phase in [0, 1), period name)
TIME_PERIODS = {
    "HourOfDay": lambda ms: (ms % _MS_PER_DAY) / _MS_PER_DAY,
    # epoch day 0 (1970-01-01) was a Thursday = ISO day-of-week 4
    "DayOfWeek": lambda ms: (((ms // _MS_PER_DAY) + 3) % 7) / 7.0,
    "DayOfMonth": lambda ms: _day_of_month_phase(ms),
    "MonthOfYear": lambda ms: _month_phase(ms),
}


def _civil_from_ms(ms: np.ndarray):
    days = ms // _MS_PER_DAY
    # days-from-civil inverse (Howard Hinnant's algorithm), vectorized
    z = days + 719468
    era = np.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = np.where(mp < 10, mp + 3, mp - 9)
    return m.astype(np.int64), d.astype(np.int64)


def _day_of_month_phase(ms: np.ndarray) -> np.ndarray:
    _, d = _civil_from_ms(ms)
    return (d - 1) / 31.0


def _month_phase(ms: np.ndarray) -> np.ndarray:
    m, _ = _civil_from_ms(ms)
    return (m - 1) / 12.0


class DateToUnitCircleVectorizer(SequenceTransformer):
    """Date(s) -> [sin, cos] per time period, null-safe (missing -> 0,0)."""

    input_types = (Date,)
    output_type = OPVector

    def __init__(self, time_period: str = "HourOfDay",
                 uid: Optional[str] = None):
        super().__init__(operation_name="toUnitCircle", uid=uid)
        if time_period not in TIME_PERIODS:
            raise ValueError(
                f"Unknown time period {time_period!r}; "
                f"choose from {sorted(TIME_PERIODS)}")
        self.time_period = time_period

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        phase_fn = TIME_PERIODS[self.time_period]
        blocks, metas = [], []
        for f, col in zip(self.input_features, cols):
            vals = np.asarray(col.data, dtype=np.float64)
            ok = ~np.isnan(vals)
            ms = np.where(ok, vals, 0.0).astype(np.int64)
            phase = 2.0 * np.pi * np.asarray(phase_fn(ms), dtype=np.float64)
            block = np.zeros((len(vals), 2), dtype=np.float64)
            block[:, 0] = np.where(ok, np.sin(phase), 0.0)
            block[:, 1] = np.where(ok, np.cos(phase), 0.0)
            blocks.append(block)
            for trig in ("sin", "cos"):
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__,
                    descriptor_value=f"{trig}({self.time_period})"))
        return vector_output(self.get_output().name, blocks, metas)
