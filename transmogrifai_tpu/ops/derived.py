"""Derived-value transformers: parsing, validation, similarity, surgery.

TPU-native ports of the reference derived-value family
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
{PhoneNumberParser.scala, EmailParser via RichTextFeature,
MimeTypeDetector.scala, LangDetector.scala, NGramSimilarity.scala,
TextLenTransformer.scala, ToOccurTransformer.scala,
DropIndicesByTransformer.scala, AliasTransformer.scala}). The
JVM-library backends (libphonenumber, Tika, Optimaize, Lucene) become
small host-side pure-Python equivalents — these run pre-device in the
columnar pipeline, exactly like the reference runs them pre-vectorizer.
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import BinaryTransformer, UnaryTransformer
from ..types import (Base64, Binary, Email, Integral, OPSet, OPVector,
                     Phone, PickList, Real, RealNN, Text, TextList)
from ..utils.vector_meta import VectorMetadata

__all__ = ["PhoneNumberParser", "EmailToPickList", "UrlToPickList",
           "MimeTypeDetector", "LangDetector", "TextLenTransformer",
           "NGramSimilarity", "JaccardSimilarity", "ToOccurTransformer",
           "DropIndicesByTransformer"]


class PhoneNumberParser(UnaryTransformer):
    """Phone validity check (reference PhoneNumberParser.scala; the
    libphonenumber backend becomes a structural digit check)."""

    input_types = (Phone,)
    output_type = Binary

    def __init__(self, region: str = "US", uid: Optional[str] = None):
        super().__init__(operation_name="phoneValid", uid=uid)
        self.region = region

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        out = np.empty(cols[0].n_rows, dtype=object)
        for i, v in enumerate(cols[0].data):
            if v is None:
                out[i] = None
                continue
            digits = re.sub(r"\D", "", str(v))
            n = len(digits)
            out[i] = (7 <= n <= 15) and not digits.startswith("0") \
                if self.region == "US" else 7 <= n <= 15
        return FeatureColumn.from_values(Binary, list(out))


class EmailToPickList(UnaryTransformer):
    """Email -> domain (or prefix) categorical
    (reference RichTextFeature email pivot via EmailParser)."""

    input_types = (Email,)
    output_type = PickList

    def __init__(self, part: str = "domain", uid: Optional[str] = None):
        super().__init__(operation_name="emailPart", uid=uid)
        if part not in ("domain", "prefix"):
            raise ValueError("part must be 'domain' or 'prefix'")
        self.part = part

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = []
        for v in cols[0].data:
            boxed = Email(v)
            vals.append(boxed.domain if self.part == "domain"
                        else boxed.prefix)
        return FeatureColumn.from_values(PickList, vals)


class UrlToPickList(UnaryTransformer):
    """URL -> protocol/domain categorical (reference RichTextFeature
    urlVectorize)."""

    input_types = (Text,)
    output_type = PickList

    def __init__(self, part: str = "domain", uid: Optional[str] = None):
        super().__init__(operation_name="urlPart", uid=uid)
        if part not in ("domain", "protocol"):
            raise ValueError("part must be 'domain' or 'protocol'")
        self.part = part

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        from ..types import URL
        vals = []
        for v in cols[0].data:
            boxed = URL(v)
            vals.append(boxed.domain if self.part == "domain"
                        else boxed.protocol)
        return FeatureColumn.from_values(PickList, vals)


_MAGIC = [
    (b"%PDF", "application/pdf"),
    (b"\x89PNG", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF8", "image/gif"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"<?xml", "application/xml"),
    (b"{", "application/json"),
]


class MimeTypeDetector(UnaryTransformer):
    """Base64 -> MIME type via magic bytes (reference
    MimeTypeDetector.scala; Tika becomes a signature table)."""

    input_types = (Base64,)
    output_type = PickList

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="mimeType", uid=uid)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = []
        for v in cols[0].data:
            data = Base64(v).as_bytes() if v is not None else None
            if not data:
                vals.append(None)
                continue
            mime = next((m for sig, m in _MAGIC
                         if data.startswith(sig)), None)
            if mime is None:
                try:
                    data.decode("utf-8")
                    mime = "text/plain"
                except UnicodeDecodeError:
                    mime = "application/octet-stream"
            vals.append(mime)
        return FeatureColumn.from_values(PickList, vals)


class LangDetector(UnaryTransformer):
    """Language detection via Unicode-script routing + Cavnar–Trenkle
    character n-gram profiles (utils/text_lang.py) — same model family
    as the reference's Optimaize detector (LangDetector.scala,
    core/build.gradle). Handles non-Latin scripts (CJK, Cyrillic,
    Arabic, ...) that the r3 stopword-vote could not."""

    input_types = (Text,)
    output_type = PickList

    def __init__(self, default_lang: str = "unknown",
                 min_confidence: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(operation_name="langDetect", uid=uid)
        self.default_lang = default_lang
        self.min_confidence = min_confidence

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        from ..utils.text_lang import detect_language
        vals = []
        for v in cols[0].data:
            if not v:
                vals.append(None)
                continue
            lang, conf = detect_language(str(v), default=self.default_lang)
            vals.append(lang if conf >= self.min_confidence
                        else self.default_lang)
        return FeatureColumn.from_values(PickList, vals)


class TextLenTransformer(UnaryTransformer):
    """Text length (reference TextLenTransformer.scala); None -> 0."""

    input_types = (Text,)
    output_type = Integral

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="textLen", uid=uid)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = [len(v) if v is not None else 0 for v in cols[0].data]
        return FeatureColumn.from_values(Integral, vals)


def _ngrams(s: str, n: int) -> set:
    s = re.sub(r"\s+", " ", s.strip().lower())
    if len(s) < n:
        return {s} if s else set()
    return {s[i:i + n] for i in range(len(s) - n + 1)}


class NGramSimilarity(BinaryTransformer):
    """Character n-gram Jaccard similarity of two texts
    (reference NGramSimilarity.scala via Lucene; empty inputs -> 0)."""

    input_types = (Text, Text)
    output_type = RealNN

    def __init__(self, n: int = 3, uid: Optional[str] = None):
        super().__init__(operation_name="ngramSim", uid=uid)
        self.n = n

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        out = np.zeros(cols[0].n_rows, dtype=np.float64)
        for i, (a, b) in enumerate(zip(cols[0].data, cols[1].data)):
            if not a or not b:
                continue
            ga, gb = _ngrams(a, self.n), _ngrams(b, self.n)
            union = len(ga | gb)
            out[i] = len(ga & gb) / union if union else 0.0
        return FeatureColumn(ftype=RealNN, data=out)


class JaccardSimilarity(BinaryTransformer):
    """Jaccard similarity of two set features (reference
    JaccardSimilarity.scala; both-empty -> 1.0 as in the reference)."""

    input_types = (OPSet, OPSet)
    output_type = RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="jaccardSim", uid=uid)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        out = np.zeros(cols[0].n_rows, dtype=np.float64)
        for i, (a, b) in enumerate(zip(cols[0].data, cols[1].data)):
            sa = set(a) if a else set()
            sb = set(b) if b else set()
            if not sa and not sb:
                out[i] = 1.0
                continue
            union = len(sa | sb)
            out[i] = len(sa & sb) / union if union else 0.0
        return FeatureColumn(ftype=RealNN, data=out)


class ToOccurTransformer(UnaryTransformer):
    """Any feature -> 1.0 if present/truthy else 0.0
    (reference ToOccurTransformer.scala)."""

    input_types = (None,)
    output_type = RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="toOccur", uid=uid)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        col = cols[0]
        missing = col.is_missing()
        return FeatureColumn(ftype=RealNN,
                             data=(~missing).astype(np.float64))


class DropIndicesByTransformer(UnaryTransformer):
    """Drop vector columns whose metadata matches a predicate
    (reference DropIndicesByTransformer.scala). The predicate takes a
    VectorColumnMetadata; only importable functions survive save/load."""

    input_types = (OPVector,)
    output_type = OPVector

    def __init__(self, match_fn: Callable = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="dropIndicesBy", uid=uid)
        if match_fn is None:
            raise ValueError("DropIndicesByTransformer requires match_fn")
        self.match_fn = match_fn

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vec = cols[0]
        meta = vec.metadata
        if meta is None or meta.size != vec.data.shape[1]:
            raise ValueError(
                "DropIndicesByTransformer requires vector metadata")
        keep = [c.index for c in meta.columns if not self.match_fn(c)]
        return FeatureColumn.vector(
            np.asarray(vec.data, dtype=np.float64)[:, keep],
            meta.select(keep, name=self.get_output().name))


class CollectionTransformer(UnaryTransformer):
    """Lift a scalar unary transformer over a collection feature
    (reference OPCollectionTransformer.scala: OPMap/OPList/OPSet
    variants wrapping any Text/Numeric transformer): map VALUES / list /
    set ELEMENTS are boxed into the inner stage's input type, pushed
    through its ``transform_value``, and unboxed back into the same
    collection shape."""

    from ..types import OPCollection as _OPC, OPMap as _OPM
    input_types = (object,)   # concrete collection type set at set_input
    output_type = None

    def __init__(self, inner, output_type=None, uid: Optional[str] = None):
        super().__init__(
            operation_name=f"collection_{inner.operation_name}"
            if hasattr(inner, "operation_name") else "collection",
            uid=uid)
        self.inner = inner
        self._out_override = output_type

    def set_input(self, *features):
        out = super().set_input(*features)
        self.output_type = self._out_override or features[0].ftype
        if not getattr(self.inner, "input_features", ()):
            # wire the inner stage to a synthetic element-typed feature
            # so its row path has an input to describe
            from ..features.builder import FeatureBuilder
            dummy = FeatureBuilder.of(
                f"{features[0].name}_element",
                self.inner.input_types[0]).extract(
                lambda r: None).as_predictor()
            self.inner.set_input(dummy)
        return out

    def _apply_scalar(self, v):
        inner_in = self.inner.input_types[0]
        boxed = self.inner.transform_value(inner_in(v))
        return boxed.value if hasattr(boxed, "value") else boxed

    def transform_value(self, value):
        from ..types import OPMap, OPList, OPSet
        raw = value.value if hasattr(value, "value") else value
        ftype = self.output_type
        if raw is None:
            return ftype(None)
        if issubclass(ftype, OPMap):
            return ftype({k: self._apply_scalar(v)
                          for k, v in raw.items()})
        if issubclass(ftype, OPSet):
            return ftype({self._apply_scalar(v) for v in raw})
        return ftype(tuple(self._apply_scalar(v) for v in raw))

    def transform_columns(self, cols):
        from ..features.columns import FeatureColumn
        return FeatureColumn.from_values(
            self.output_type,
            [self.transform_value(v) for v in cols[0].data])
