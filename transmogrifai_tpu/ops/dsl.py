"""Feature DSL enrichments: arithmetic, map, normalize, pivot.

TPU-native port of the reference DSL implicit classes
(core/src/main/scala/com/salesforce/op/dsl/{RichNumericFeature.scala,
RichTextFeature.scala, RichFeature.scala}): ``sibSp + parCh + 1``,
``age.fillMissingWithMean().zNormalize()``, ``sex.pivot()``,
``feature.map(fn)``. The arithmetic/normalization transformers run
columnar (NaN propagates missing values exactly like the reference's
empty-Option propagation on numeric binary ops).

Wired onto :class:`~transmogrifai_tpu.features.feature.Feature` as dunder
operators and methods (see features/feature.py).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Type

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import (BinaryTransformer, UnaryEstimator, UnaryModel,
                           UnaryTransformer)
from ..types import FeatureType, OPNumeric, Real, RealNN

__all__ = ["NumericBinaryTransformer", "NumericScalarTransformer",
           "FillMissingWithMean", "FillMissingWithMeanModel",
           "StandardScaler", "StandardScalerModel"]

_OPS = {
    "add": np.add, "sub": np.subtract, "mul": np.multiply,
    "div": np.divide,
}


def _arith_kernel(op: str, a, b):
    """jnp analogue of the numpy arithmetic incl. the inf -> NaN
    missing-propagation rule (serving/plan.py lowering)."""
    import jax.numpy as jnp
    fns = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    out = fns[op](a, b)
    return jnp.where(jnp.isinf(out), jnp.nan, out)


class NumericBinaryTransformer(BinaryTransformer):
    """Elementwise arithmetic of two numeric features; missing (NaN) in
    either operand propagates (reference RichNumericFeature ``/``, ``*``,
    ``+``, ``-`` semantics: empty if either side is empty)."""

    input_types = (OPNumeric, OPNumeric)
    output_type = Real

    def __init__(self, op: str = "add", uid: Optional[str] = None):
        super().__init__(operation_name=op, uid=uid)
        if op not in _OPS:
            raise ValueError(f"Unknown op {op!r}")
        self.op = op

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        a = np.asarray(cols[0].data, dtype=np.float64)
        b = np.asarray(cols[1].data, dtype=np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = _OPS[self.op](a, b)
        out = np.where(np.isinf(out), np.nan, out)
        return FeatureColumn(ftype=Real, data=out)

    def transform_arrays(self, arrays):
        return _arith_kernel(self.op, arrays[0], arrays[1])


class NumericScalarTransformer(UnaryTransformer):
    """Feature <op> scalar (reference RichNumericFeature scalar ops)."""

    input_types = (OPNumeric,)
    output_type = Real

    def __init__(self, op: str = "add", scalar: float = 0.0,
                 swapped: bool = False, uid: Optional[str] = None):
        super().__init__(operation_name=f"{op}Scalar", uid=uid)
        if op not in _OPS:
            raise ValueError(f"Unknown op {op!r}")
        self.op = op
        self.scalar = float(scalar)
        self.swapped = swapped

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        a = np.asarray(cols[0].data, dtype=np.float64)
        args = (self.scalar, a) if self.swapped else (a, self.scalar)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = _OPS[self.op](*args)
        out = np.where(np.isinf(out), np.nan, out)
        return FeatureColumn(ftype=Real, data=out)

    def transform_arrays(self, arrays):
        a = arrays[0]
        x, y = (self.scalar, a) if self.swapped else (a, self.scalar)
        return _arith_kernel(self.op, x, y)


class AliasTransformer(UnaryTransformer):
    """Identity stage that renames its input feature
    (reference core/.../feature/AliasTransformer.scala)."""

    input_types = (None,)

    def __init__(self, alias: str, output_type: Type[FeatureType] = Real,
                 uid: Optional[str] = None):
        super().__init__(operation_name="alias", uid=uid)
        self.alias = alias
        self.output_type = output_type  # instance attr shadows classvar

    def output_feature_name(self) -> str:
        return self.alias

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        return cols[0]

    def transform_arrays(self, arrays):
        # identity; lowers only when the input is numerically encodable
        # (object-typed aliases fail the plan's encoder probe and fall
        # back — same rename, host-side)
        return arrays[0]


class FillMissingWithMeanModel(UnaryModel):
    input_types = (OPNumeric,)
    output_type = RealNN

    def __init__(self, fill_value: float = 0.0, uid: Optional[str] = None):
        super().__init__(operation_name="fillWithMean", uid=uid)
        self.fill_value = float(fill_value)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        return FeatureColumn(
            ftype=RealNN, data=np.where(np.isnan(vals), self.fill_value, vals))

    def transform_arrays(self, arrays):
        import jax.numpy as jnp
        return jnp.where(jnp.isnan(arrays[0]), self.fill_value, arrays[0])


class FillMissingWithMean(UnaryEstimator):
    """Real -> RealNN mean imputation (reference
    core/.../feature/FillMissingWithMean.scala)."""

    input_types = (OPNumeric,)
    output_type = RealNN

    def __init__(self, default_value: float = 0.0, uid: Optional[str] = None):
        super().__init__(operation_name="fillWithMean", uid=uid)
        self.default_value = default_value

    def fit_columns(self, cols: List[FeatureColumn]) -> FillMissingWithMeanModel:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        ok = ~np.isnan(vals)
        fill = float(np.mean(vals[ok])) if ok.any() else self.default_value
        return FillMissingWithMeanModel(fill_value=fill)

    def fit_device(self, arrays, protos) -> FillMissingWithMeanModel:
        """Compiled-prepare fit statistic on device (plans/prepare.py):
        a masked mean over the device-resident column — only the one
        fitted scalar crosses to the host. Summation runs in XLA, so
        the fill value may differ from the host fit in the last ulp
        (numpy pairwise vs XLA reduction order; docs/prepare.md)."""
        import jax.numpy as jnp
        vals = jnp.asarray(arrays[0]).reshape(-1)
        ok = ~jnp.isnan(vals)
        cnt = jnp.sum(ok)
        mean = jnp.sum(jnp.where(ok, vals, 0.0)) / jnp.maximum(cnt, 1)
        return FillMissingWithMeanModel(
            fill_value=float(mean) if int(cnt) else self.default_value)


class StandardScalerModel(UnaryModel):
    input_types = (OPNumeric,)
    output_type = RealNN

    def __init__(self, mean: float = 0.0, std: float = 1.0,
                 uid: Optional[str] = None):
        super().__init__(operation_name="stdScaled", uid=uid)
        self.mean = float(mean)
        self.std = float(std)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        std = self.std if self.std > 0 else 1.0
        return FeatureColumn(ftype=RealNN, data=(vals - self.mean) / std)

    def transform_arrays(self, arrays):
        std = self.std if self.std > 0 else 1.0
        return (arrays[0] - self.mean) / std


class StandardScaler(UnaryEstimator):
    """z-normalization (reference OpScalarStandardScaler,
    RichNumericFeature.zNormalize:325)."""

    input_types = (OPNumeric,)
    output_type = RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="stdScaled", uid=uid)

    def fit_columns(self, cols: List[FeatureColumn]) -> StandardScalerModel:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        ok = ~np.isnan(vals)
        mean = float(np.mean(vals[ok])) if ok.any() else 0.0
        std = float(np.std(vals[ok])) if ok.any() else 1.0
        return StandardScalerModel(mean=mean, std=std)

    def fit_device(self, arrays, protos) -> StandardScalerModel:
        """Masked mean/std on device (see FillMissingWithMean.fit_device
        for the one-ulp caveat vs the host reduction order)."""
        import jax.numpy as jnp
        vals = jnp.asarray(arrays[0]).reshape(-1)
        ok = ~jnp.isnan(vals)
        cnt = jnp.maximum(jnp.sum(ok), 1)
        mean = jnp.sum(jnp.where(ok, vals, 0.0)) / cnt
        var = jnp.sum(jnp.where(ok, (vals - mean) ** 2, 0.0)) / cnt
        if not int(jnp.sum(ok)):
            return StandardScalerModel(mean=0.0, std=1.0)
        return StandardScalerModel(mean=float(mean),
                                   std=float(jnp.sqrt(var)))
