"""Geolocation vectorizer: (lat, lon, accuracy) triples -> OPVector.

TPU-native port of the reference GeolocationVectorizer
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
GeolocationVectorizer.scala): missing locations fill with the training
data's geographic midpoint (unit-vector average, the reference's Lucene
spatial3d computation — features/aggregators.py here), plus optional
null tracking.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import SequenceEstimator, SequenceModel
from ..types import Geolocation, OPVector
from .vector_utils import NULL_INDICATOR, VectorColumnMetadata, vector_output

__all__ = ["GeolocationVectorizer", "GeolocationVectorizerModel"]


def _geo_block(col: FeatureColumn, fill: List[float],
               track_nulls: bool) -> List[np.ndarray]:
    """[lat/lon/acc block, null indicator?] for one geolocation column
    — shared by the columnar path and the compiled plans' boundary
    encoder so parity is structural."""
    n = col.n_rows
    block = np.tile(np.asarray(fill), (n, 1))
    isnull = np.ones(n)
    for i, v in enumerate(col.data):
        if v is not None and len(v):
            block[i, :] = [v[0], v[1], v[2] if len(v) > 2 else 0.0]
            isnull[i] = 0.0
    return [block, isnull] if track_nulls else [block]


class GeolocationVectorizerModel(SequenceModel):
    input_types = (Geolocation,)
    output_type = OPVector

    def __init__(self, fill_values: List[List[float]],
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecGeo", uid=uid)
        self.fill_values = [[float(x) for x in f] for f in fill_values]
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, fill in zip(self.input_features, cols,
                                self.fill_values):
            blocks.extend(_geo_block(col, fill, self.track_nulls))
            for p in ("lat", "lon", "acc"):
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__,
                    descriptor_value=p))
            if self.track_nulls:
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__,
                    indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas)

    # -- compiled-plan lowering: the (lat, lon, acc) extraction from
    # object triples is inherently a host walk, so the encoder emits
    # the dense block (EXACTLY _geo_block) and the kernel is the concat
    # that fuses it into the downstream program.
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        parts = _geo_block(col, self.fill_values[i], self.track_nulls)
        return np.concatenate(
            [p if p.ndim == 2 else p[:, None] for p in parts], axis=1)

    def transform_arrays(self, arrays):
        import jax.numpy as jnp
        return jnp.concatenate(arrays, axis=1)


class GeolocationVectorizer(SequenceEstimator):
    """(reference GeolocationVectorizer.scala)"""

    input_types = (Geolocation,)
    output_type = OPVector

    def __init__(self, track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecGeo", uid=uid)
        self.track_nulls = track_nulls

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> GeolocationVectorizerModel:
        from ..features.aggregators import GeolocationMidpoint
        fills = []
        for col in cols:
            pts = [v for v in col.data if v is not None and len(v)]
            mid = GeolocationMidpoint().reduce(pts) if pts else None
            fills.append([float(x) for x in (mid or [0.0, 0.0, 0.0])])
        return GeolocationVectorizerModel(fill_values=fills,
                                          track_nulls=self.track_nulls)
