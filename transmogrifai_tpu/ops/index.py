"""String indexing / deindexing.

TPU-native ports of the reference index family
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
{OpStringIndexer.scala, OpStringIndexerNoFilter.scala,
OpIndexToString.scala, OpIndexToStringNoFilter.scala} and
core/.../preparators/PredictionDeIndexer.scala): labels index by
training frequency (ties lexical), unseen values map to the trailing
"unseen" index (NoFilter semantics) or raise (error semantics).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..features.columns import FeatureColumn, PredictionColumn
from ..stages.base import (AllowLabelAsInput, BinaryTransformer, UnaryEstimator,
                           UnaryModel, UnaryTransformer)
from ..types import Prediction, RealNN, Text

__all__ = ["StringIndexer", "StringIndexerModel", "IndexToString",
           "PredictionDeIndexer"]

UNSEEN_NAME = "UnseenLabel"


class StringIndexerModel(UnaryModel):
    input_types = (Text,)
    output_type = RealNN

    def __init__(self, labels: Sequence[str], handle_invalid: str = "keep",
                 uid: Optional[str] = None):
        super().__init__(operation_name="strIdx", uid=uid)
        self.labels = [str(l) for l in labels]
        self.handle_invalid = handle_invalid
        self._index = {l: i for i, l in enumerate(self.labels)}

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        out = np.zeros(cols[0].n_rows, dtype=np.float64)
        unseen = float(len(self.labels))
        for i, v in enumerate(cols[0].data):
            j = self._index.get(v if v is not None else None)
            if j is None:
                if self.handle_invalid == "error":
                    raise ValueError(f"Unseen label {v!r} at row {i}")
                out[i] = unseen
            else:
                out[i] = float(j)
        return FeatureColumn(ftype=RealNN, data=out)


class StringIndexer(UnaryEstimator):
    """(reference OpStringIndexer / NoFilter variant; handle_invalid in
    {"keep", "error"} — "keep" is the NoFilter behavior)"""

    input_types = (Text,)
    output_type = RealNN

    def __init__(self, handle_invalid: str = "keep",
                 uid: Optional[str] = None):
        super().__init__(operation_name="strIdx", uid=uid)
        if handle_invalid not in ("keep", "error"):
            raise ValueError("handle_invalid must be 'keep' or 'error'")
        self.handle_invalid = handle_invalid

    def fit_columns(self, cols: List[FeatureColumn]) -> StringIndexerModel:
        counts: dict = {}
        for v in cols[0].data:
            if v is not None:
                counts[v] = counts.get(v, 0) + 1
        labels = sorted(counts, key=lambda k: (-counts[k], k))
        return StringIndexerModel(labels=labels,
                                  handle_invalid=self.handle_invalid)


class IndexToString(UnaryTransformer):
    """(reference OpIndexToString / NoFilter variant)"""

    input_types = (RealNN,)
    output_type = Text

    def __init__(self, labels: Sequence[str], unseen_name: str = UNSEEN_NAME,
                 uid: Optional[str] = None):
        super().__init__(operation_name="idx2str", uid=uid)
        self.labels = [str(l) for l in labels]
        self.unseen_name = unseen_name

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            j = int(v) if np.isfinite(v) else -1
            out[i] = self.labels[j] if 0 <= j < len(self.labels) \
                else self.unseen_name
        return FeatureColumn(ftype=Text, data=out)


class PredictionDeIndexer(AllowLabelAsInput, BinaryTransformer):
    """Turn a Prediction back into the original label string using the
    indexer that produced the response (reference
    core/.../preparators/PredictionDeIndexer.scala). Input 1: the indexed
    response feature (its origin must be a StringIndexer model);
    input 2: the prediction."""

    input_types = (RealNN, Prediction)
    output_type = Text

    def __init__(self, labels: Optional[Sequence[str]] = None,
                 unseen_name: str = UNSEEN_NAME, uid: Optional[str] = None):
        super().__init__(operation_name="predDeIdx", uid=uid)
        self.labels = [str(l) for l in labels] if labels else None
        self.unseen_name = unseen_name

    def _labels(self) -> List[str]:
        if self.labels is not None:
            return self.labels
        origin = self.input_features[0].origin_stage
        if isinstance(origin, StringIndexerModel):
            return origin.labels
        fitted = getattr(origin, "fitted_model", None)
        if isinstance(fitted, StringIndexerModel):
            return fitted.labels
        raise ValueError(
            "PredictionDeIndexer needs labels= or a response produced by "
            "a fitted StringIndexer")

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        pred_col = cols[-1]
        preds = pred_col.data if isinstance(pred_col, PredictionColumn) \
            else np.asarray([p["prediction"] for p in pred_col.data])
        labels = self._labels()
        out = np.empty(len(preds), dtype=object)
        for i, v in enumerate(np.asarray(preds, dtype=np.float64)):
            j = int(v) if np.isfinite(v) else -1
            out[i] = labels[j] if 0 <= j < len(labels) else self.unseen_name
        return FeatureColumn(ftype=Text, data=out)
