"""Map vectorizers: typed ``str -> value`` maps -> OPVector.

TPU-native ports of the reference map vectorizer family
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
{OPMapVectorizer.scala, TextMapPivotVectorizer.scala,
MultiPickListMapVectorizer.scala, GeolocationMapVectorizer.scala,
SmartTextMapVectorizer.scala}): fit learns the key universe per input
map feature (the reference's ``allowedKeys``/whitelist pass), then each
(feature, key) pair becomes a fixed slot of the output vector with the
same impute/track-null semantics as the scalar vectorizers, and
``grouping`` metadata set to the key so SanityChecker prunes per-key
groups.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import (SequenceEstimator, SequenceModel,
                           UnaryTransformer)
from ..types import (BinaryMap, DateMap, GeolocationMap, MultiPickListMap,
                     NumericMap, OPMap, OPVector, TextMap)
from .vector_utils import (NULL_INDICATOR, OTHER_INDICATOR,
                           VectorColumnMetadata, vector_output)

__all__ = ["RealMapVectorizer", "RealMapVectorizerModel",
           "BinaryMapVectorizer", "TextMapPivotVectorizer",
           "TextMapPivotVectorizerModel", "MultiPickListMapVectorizer",
           "GeolocationMapVectorizer", "GeolocationMapVectorizerModel",
           "SmartTextMapVectorizer", "SmartTextMapVectorizerModel",
           "DateMapToUnitCircleVectorizer",
           "DateMapToUnitCircleVectorizerModel", "FilterMap",
           "TextMapLenEstimator", "TextMapNullEstimator"]


def _sorted_keys(cols: List[FeatureColumn],
                 allow_keys: Optional[Sequence[str]] = None
                 ) -> List[List[str]]:
    out = []
    for col in cols:
        keys = set()
        for m in col.data:
            if m:
                keys.update(m.keys())
        if allow_keys is not None:
            keys &= set(allow_keys)
        out.append(sorted(keys))
    return out


class RealMapVectorizerModel(SequenceModel):
    input_types = (OPMap,)  # NumericMap | IntegralMap | DateMap
    output_type = OPVector

    def __init__(self, keys: List[List[str]],
                 fill_values: List[List[float]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecRealMap", uid=uid)
        self.keys = [list(k) for k in keys]
        self.fill_values = [[float(v) for v in f] for f in fill_values]
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, keys, fills in zip(self.input_features, cols,
                                       self.keys, self.fill_values):
            n = col.n_rows
            for k, fill in zip(keys, fills):
                vals = np.full(n, np.nan)
                for i, m in enumerate(col.data):
                    if m and k in m and m[k] is not None:
                        vals[i] = float(m[k])
                isnan = np.isnan(vals)
                blocks.append(np.where(isnan, fill, vals))
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__, grouping=k))
                if self.track_nulls:
                    blocks.append(isnan.astype(np.float64))
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__, grouping=k,
                        indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)

    # -- compiled-serving lowering: the per-key dict walk runs on host
    # (one (n, n_keys) NaN-missing matrix per input); impute + null
    # tracking fuse on device.
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        return _map_values_matrix(col, self.keys[i])

    def transform_arrays(self, arrays):
        import jax.numpy as jnp
        outs = []
        for mat, fills in zip(arrays, self.fill_values):
            isnan = jnp.isnan(mat)
            filled = jnp.where(isnan, jnp.asarray(fills, mat.dtype), mat)
            if self.track_nulls:
                # interleave (value, null) per key — the numpy column order
                blk = jnp.stack([filled, isnan.astype(mat.dtype)],
                                axis=2).reshape(mat.shape[0], -1)
            else:
                blk = filled
            outs.append(blk)
        return jnp.concatenate(outs, axis=1)


def _map_values_matrix(col: FeatureColumn, keys: Sequence[str]
                       ) -> np.ndarray:
    """(n, len(keys)) float matrix of map values, NaN = key absent.
    Walks each row's ENTRIES rather than the key union — real maps are
    sparse (a few entries against a wide fitted key set), so this is
    O(rows x entries), the encoder's train-prepare hot-loop bound."""
    out = np.full((col.n_rows, len(keys)), np.nan)
    pos = {k: j for j, k in enumerate(keys)}
    get = pos.get
    for r, m in enumerate(col.data):
        if m:
            for k, v in m.items():
                j = get(k)
                if j is not None and v is not None:
                    out[r, j] = float(v)
    return out


class RealMapVectorizer(SequenceEstimator):
    """Numeric maps -> per-key columns, mean-imputed
    (reference OPMapVectorizer.scala RealMapVectorizer)."""

    input_types = (OPMap,)  # NumericMap | IntegralMap | DateMap
    output_type = OPVector

    def __init__(self, fill_with_mean: bool = True, fill_value: float = 0.0,
                 track_nulls: bool = True,
                 allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecRealMap", uid=uid)
        self.fill_with_mean = fill_with_mean
        self.fill_value = fill_value
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> RealMapVectorizerModel:
        keys = _sorted_keys(cols, self.allow_keys)
        fills = []
        for col, ks in zip(cols, keys):
            per_key = []
            for k in ks:
                vals = [float(m[k]) for m in col.data
                        if m and k in m and m[k] is not None]
                if self.fill_with_mean and vals:
                    per_key.append(float(np.mean(vals)))
                else:
                    per_key.append(float(self.fill_value))
            fills.append(per_key)
        return RealMapVectorizerModel(keys=keys, fill_values=fills,
                                      track_nulls=self.track_nulls)


class BinaryMapVectorizer(RealMapVectorizer):
    """Boolean maps -> per-key 0/1 columns, false-filled
    (reference BinaryMapVectorizer in OPMapVectorizer.scala)."""

    input_types = (BinaryMap,)

    def __init__(self, track_nulls: bool = True,
                 allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(fill_with_mean=False, fill_value=0.0,
                         track_nulls=track_nulls, allow_keys=allow_keys,
                         uid=uid)
        self.operation_name = "vecBinaryMap"


class TextMapPivotVectorizerModel(SequenceModel):
    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, keys: List[List[str]],
                 categories: List[Dict[str, List[str]]],
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="pivotTextMap", uid=uid)
        self.keys = [list(k) for k in keys]
        self.categories = [dict(c) for c in categories]
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, keys, cats in zip(self.input_features, cols,
                                      self.keys, self.categories):
            n = col.n_rows
            for k in keys:
                levels = cats.get(k, [])
                width = len(levels) + 1 + (1 if self.track_nulls else 0)
                block = np.zeros((n, width))
                index = {c: i for i, c in enumerate(levels)}
                for i, m in enumerate(col.data):
                    v = m.get(k) if m else None
                    if v is None:
                        if self.track_nulls:
                            block[i, len(levels) + 1] = 1.0
                    else:
                        j = index.get(str(v))
                        block[i, j if j is not None else len(levels)] = 1.0
                blocks.append(block)
                group = f"{f.name}_{k}"
                for c in levels:
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, indicator_value=c))
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__,
                    grouping=k, indicator_value=OTHER_INDICATOR))
                if self.track_nulls:
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)

    # -- compiled-serving lowering: per-key level->index lookup on host
    # ((n, n_keys) int32), per-key one-hot expansion on device. Index
    # layout per key: [0..L-1] levels, L = OTHER, L+1 = NULL.
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        keys, cats = self.keys[i], self.categories[i]
        kpos = {k: j for j, k in enumerate(keys)}
        indexes = [{c: q for q, c in enumerate(cats.get(k, []))}
                   for k in keys]
        others = [len(cats.get(k, [])) for k in keys]
        # every slot starts at its key's NULL index; one sparse pass
        # over each row's ENTRIES fills the present keys (see
        # _map_values_matrix for the hot-loop rationale)
        null_row = np.asarray(
            [o + 1 if self.track_nulls else -1 for o in others],
            dtype=np.int32)
        out = np.tile(null_row, (col.n_rows, 1))
        kget = kpos.get
        for r, m in enumerate(col.data):
            if m:
                for k, v in m.items():
                    j = kget(k)
                    if j is not None and v is not None:
                        out[r, j] = indexes[j].get(str(v), others[j])
        return out

    def transform_arrays(self, arrays):
        import jax
        import jax.numpy as jnp
        blocks = []
        for idx, keys, cats in zip(arrays, self.keys, self.categories):
            for j, k in enumerate(keys):
                width = len(cats.get(k, [])) + 1 \
                    + (1 if self.track_nulls else 0)
                blocks.append(jax.nn.one_hot(idx[:, j], width))
        if not blocks:
            return jnp.zeros((arrays[0].shape[0], 0))
        return jnp.concatenate(blocks, axis=1)


class TextMapPivotVectorizer(SequenceEstimator):
    """Text maps -> per-key top-K one-hot pivot
    (reference TextMapPivotVectorizer.scala)."""

    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True,
                 allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="pivotTextMap", uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> TextMapPivotVectorizerModel:
        from .categorical import _top_categories
        keys = _sorted_keys(cols, self.allow_keys)
        categories = []
        for col, ks in zip(cols, keys):
            per_key: Dict[str, List[str]] = {}
            for k in ks:
                counts: Dict[str, int] = {}
                for m in col.data:
                    v = m.get(k) if m else None
                    if v is not None:
                        counts[str(v)] = counts.get(str(v), 0) + 1
                per_key[k] = _top_categories(counts, self.top_k,
                                             self.min_support)
            categories.append(per_key)
        return TextMapPivotVectorizerModel(
            keys=keys, categories=categories, track_nulls=self.track_nulls)


class MultiPickListMapVectorizer(TextMapPivotVectorizer):
    """Set-valued maps -> per-key multi-hot pivot
    (reference MultiPickListMapVectorizer.scala)."""

    input_types = (MultiPickListMap,)

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> TextMapPivotVectorizerModel:
        from .categorical import _top_categories
        keys = _sorted_keys(cols, self.allow_keys)
        categories = []
        for col, ks in zip(cols, keys):
            per_key: Dict[str, List[str]] = {}
            for k in ks:
                counts: Dict[str, int] = {}
                for m in col.data:
                    vals = m.get(k) if m else None
                    if vals:
                        for v in vals:
                            counts[str(v)] = counts.get(str(v), 0) + 1
                per_key[k] = _top_categories(counts, self.top_k,
                                             self.min_support)
            categories.append(per_key)
        model = _MultiPickListMapModel(
            keys=keys, categories=categories, track_nulls=self.track_nulls)
        return model


class _MultiPickListMapModel(TextMapPivotVectorizerModel):
    input_types = (MultiPickListMap,)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, keys, cats in zip(self.input_features, cols,
                                      self.keys, self.categories):
            n = col.n_rows
            for k in keys:
                levels = cats.get(k, [])
                width = len(levels) + 1 + (1 if self.track_nulls else 0)
                block = np.zeros((n, width))
                index = {c: i for i, c in enumerate(levels)}
                for i, m in enumerate(col.data):
                    vals = m.get(k) if m else None
                    if not vals:
                        if self.track_nulls:
                            block[i, len(levels) + 1] = 1.0
                        continue
                    for v in vals:
                        j = index.get(str(v))
                        block[i, j if j is not None else len(levels)] = 1.0
                blocks.append(block)
                for c in levels:
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, indicator_value=c))
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__,
                    grouping=k, indicator_value=OTHER_INDICATOR))
                if self.track_nulls:
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)

    # -- compiled-serving lowering: like MultiPickListVectorizer, the
    # per-key multi-hot is inherently a host dict walk, so the encoder
    # emits the concatenated per-key blocks in transform_columns' exact
    # layout and the device kernel is the fusing concat.
    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        keys, cats = self.keys[i], self.categories[i]
        n = col.n_rows
        blocks = []
        for k in keys:
            levels = cats.get(k, [])
            width = len(levels) + 1 + (1 if self.track_nulls else 0)
            block = np.zeros((n, width))
            index = {c: q for q, c in enumerate(levels)}
            for r, m in enumerate(col.data):
                vals = m.get(k) if m else None
                if not vals:
                    if self.track_nulls:
                        block[r, len(levels) + 1] = 1.0
                    continue
                for v in vals:
                    j = index.get(str(v))
                    block[r, j if j is not None else len(levels)] = 1.0
            blocks.append(block)
        return (np.concatenate(blocks, axis=1) if blocks
                else np.zeros((n, 0)))

    def transform_arrays(self, arrays):
        import jax.numpy as jnp
        return jnp.concatenate(arrays, axis=1)


class GeolocationMapVectorizerModel(SequenceModel):
    input_types = (GeolocationMap,)
    output_type = OPVector

    def __init__(self, keys: List[List[str]],
                 fill_values: List[Dict[str, List[float]]],
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecGeoMap", uid=uid)
        self.keys = [list(k) for k in keys]
        self.fill_values = [dict(f) for f in fill_values]
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        parts = ["lat", "lon", "acc"]
        blocks, metas = [], []
        for f, col, keys, fills in zip(self.input_features, cols,
                                       self.keys, self.fill_values):
            n = col.n_rows
            for k in keys:
                fill = fills.get(k, [0.0, 0.0, 0.0])
                block = np.tile(np.asarray(fill), (n, 1))
                isnull = np.ones(n)
                for i, m in enumerate(col.data):
                    v = m.get(k) if m else None
                    if v:
                        block[i, :] = [v[0], v[1],
                                       v[2] if len(v) > 2 else 0.0]
                        isnull[i] = 0.0
                blocks.append(block)
                for p in parts:
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, descriptor_value=p))
                if self.track_nulls:
                    blocks.append(isnull)
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)

    # -- compiled-plan lowering: per-key triple extraction is a host
    # dict walk, so the encoder emits the dense per-key block and the
    # kernel is the concat that fuses it into the downstream program.
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        n = col.n_rows
        keys, fills = self.keys[i], self.fill_values[i]
        width = len(keys) * (4 if self.track_nulls else 3)
        out = np.zeros((n, width), dtype=np.float64)
        pos = 0
        for k in keys:
            fill = fills.get(k, [0.0, 0.0, 0.0])
            block = np.tile(np.asarray(fill), (n, 1))
            isnull = np.ones(n)
            for r, m in enumerate(col.data):
                v = m.get(k) if m else None
                if v:
                    block[r, :] = [v[0], v[1],
                                   v[2] if len(v) > 2 else 0.0]
                    isnull[r] = 0.0
            out[:, pos:pos + 3] = block
            pos += 3
            if self.track_nulls:
                out[:, pos] = isnull
                pos += 1
        return out

    def transform_arrays(self, arrays):
        import jax.numpy as jnp
        return jnp.concatenate(arrays, axis=1)


class GeolocationMapVectorizer(SequenceEstimator):
    """Geolocation maps -> per-key (lat, lon, acc), midpoint-imputed
    (reference GeolocationMapVectorizer.scala)."""

    input_types = (GeolocationMap,)
    output_type = OPVector

    def __init__(self, track_nulls: bool = True,
                 allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecGeoMap", uid=uid)
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> GeolocationMapVectorizerModel:
        from ..features.aggregators import GeolocationMidpoint
        keys = _sorted_keys(cols, self.allow_keys)
        fills = []
        for col, ks in zip(cols, keys):
            per_key: Dict[str, List[float]] = {}
            for k in ks:
                pts = [m[k] for m in col.data
                       if m and k in m and m[k] is not None and len(m[k])]
                mid = GeolocationMidpoint().reduce(pts) if pts else None
                per_key[k] = [float(x) for x in (mid or [0.0, 0.0, 0.0])]
            fills.append(per_key)
        return GeolocationMapVectorizerModel(
            keys=keys, fill_values=fills, track_nulls=self.track_nulls)


class SmartTextMapVectorizerModel(SequenceModel):
    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, keys: List[List[str]],
                 strategies: List[Dict[str, tuple]],
                 num_hashes: int = 512, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtMapVec", uid=uid)
        self.keys = [list(k) for k in keys]
        #: per feature: {key: ("pivot", [categories]) | ("hash", None)}
        self.strategies = [{k: tuple(v) for k, v in s.items()}
                           for s in strategies]
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        from .text import _hash_block
        blocks, metas = [], []
        for f, col, keys, strat in zip(self.input_features, cols,
                                       self.keys, self.strategies):
            n = col.n_rows
            for k in keys:
                kind, cats = strat.get(k, ("hash", None))
                vals = [(m.get(k) if m else None) for m in col.data]
                if kind == "pivot":
                    levels = list(cats or [])
                    width = len(levels) + 1 + (1 if self.track_nulls else 0)
                    block = np.zeros((n, width))
                    index = {c: i for i, c in enumerate(levels)}
                    for i, v in enumerate(vals):
                        if v is None:
                            if self.track_nulls:
                                block[i, len(levels) + 1] = 1.0
                        else:
                            j = index.get(str(v))
                            block[i, j if j is not None else len(levels)] \
                                = 1.0
                    blocks.append(block)
                    for c in levels:
                        metas.append(VectorColumnMetadata(
                            parent_feature_name=f.name,
                            parent_feature_type=f.ftype.__name__,
                            grouping=k, indicator_value=c))
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, indicator_value=OTHER_INDICATOR))
                    if self.track_nulls:
                        metas.append(VectorColumnMetadata(
                            parent_feature_name=f.name,
                            parent_feature_type=f.ftype.__name__,
                            grouping=k, indicator_value=NULL_INDICATOR))
                else:
                    blocks.append(_hash_block(vals, self.num_hashes,
                                              self.track_nulls))
                    metas.extend(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__,
                        grouping=k, descriptor_value=f"hash_{j}")
                        for j in range(self.num_hashes))
                    if self.track_nulls:
                        metas.append(VectorColumnMetadata(
                            parent_feature_name=f.name,
                            parent_feature_type=f.ftype.__name__,
                            grouping=k, indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)


class SmartTextMapVectorizer(SequenceEstimator):
    """Per-KEY pivot-or-hash decision for text maps (reference
    SmartTextMapVectorizer.scala): a key whose value cardinality stays
    within ``max_cardinality`` pivots into top-K one-hot columns, a
    free-text key falls back to the hashing trick — the map analogue of
    SmartTextVectorizer's per-feature decision."""

    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, max_cardinality: int = 30, top_k: int = 20,
                 min_support: int = 10, num_hashes: int = 512,
                 track_nulls: bool = True,
                 allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtMapVec", uid=uid)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> SmartTextMapVectorizerModel:
        from .categorical import _top_categories
        keys = _sorted_keys(cols, self.allow_keys)
        strategies = []
        for col, ks in zip(cols, keys):
            per_key: Dict[str, tuple] = {}
            for k in ks:
                counts: Dict[str, int] = {}
                for m in col.data:
                    v = m.get(k) if m else None
                    if v is not None:
                        counts[str(v)] = counts.get(str(v), 0) + 1
                if len(counts) <= self.max_cardinality:
                    per_key[k] = ("pivot", _top_categories(
                        counts, self.top_k, self.min_support))
                else:
                    per_key[k] = ("hash", None)
            strategies.append(per_key)
        return SmartTextMapVectorizerModel(
            keys=keys, strategies=strategies, num_hashes=self.num_hashes,
            track_nulls=self.track_nulls)


class DateMapToUnitCircleVectorizerModel(SequenceModel):
    input_types = (DateMap,)
    output_type = OPVector

    def __init__(self, keys: List[List[str]],
                 time_period: str = "HourOfDay",
                 uid: Optional[str] = None):
        super().__init__(operation_name="dateMapToUnitCircle", uid=uid)
        self.keys = [list(k) for k in keys]
        self.time_period = time_period

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        from .date import TIME_PERIODS
        phase_fn = TIME_PERIODS[self.time_period]
        blocks, metas = [], []
        for f, col, keys in zip(self.input_features, cols, self.keys):
            n = col.n_rows
            for k in keys:
                vals = np.full(n, np.nan)
                for i, m in enumerate(col.data):
                    if m and k in m and m[k] is not None:
                        vals[i] = float(m[k])
                ok = ~np.isnan(vals)
                ms = np.where(ok, vals, 0.0).astype(np.int64)
                phase = 2.0 * np.pi * np.asarray(phase_fn(ms),
                                                 dtype=np.float64)
                block = np.zeros((n, 2))
                block[:, 0] = np.where(ok, np.sin(phase), 0.0)
                block[:, 1] = np.where(ok, np.cos(phase), 0.0)
                blocks.append(block)
                for trig in ("sin", "cos"):
                    metas.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.ftype.__name__, grouping=k,
                        descriptor_value=f"{trig}_{self.time_period}"))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)

    # -- compiled-serving lowering: host encodes (n, n_keys) phases
    # (int64 epoch math stays on host), device projects sin/cos per key
    def encodes_input(self, i: int) -> bool:
        return True

    def encode_input_column(self, i: int, col: FeatureColumn) -> np.ndarray:
        from .date import TIME_PERIODS
        phase_fn = TIME_PERIODS[self.time_period]
        vals = _map_values_matrix(col, self.keys[i])
        ok = ~np.isnan(vals)
        ms = np.where(ok, vals, 0.0).astype(np.int64)
        phase = np.asarray(phase_fn(ms), dtype=np.float64)
        return np.where(ok, phase, np.nan)

    def transform_arrays(self, arrays):
        from .date import _unit_circle_kernel
        return _unit_circle_kernel(arrays)


class DateMapToUnitCircleVectorizer(SequenceEstimator):
    """Date maps -> per-key [sin, cos] of the chosen time period
    (reference DateMapToUnitCircleVectorizer.scala); missing -> (0, 0),
    the circle's center — equidistant from every phase."""

    input_types = (DateMap,)
    output_type = OPVector

    def __init__(self, time_period: str = "HourOfDay",
                 allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="dateMapToUnitCircle", uid=uid)
        from .date import TIME_PERIODS
        if time_period not in TIME_PERIODS:
            raise ValueError(
                f"Unknown time period {time_period!r}; "
                f"choose from {sorted(TIME_PERIODS)}")
        self.time_period = time_period
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> DateMapToUnitCircleVectorizerModel:
        return DateMapToUnitCircleVectorizerModel(
            keys=_sorted_keys(cols, self.allow_keys),
            time_period=self.time_period)


class FilterMap(UnaryTransformer):
    """Key whitelist/blacklist filtering of any map feature
    (reference FilterMap.scala:45 with MapPivotParams white/blacklist)."""

    input_types = (OPMap,)
    output_type = OPMap

    def __init__(self, allow_keys: Optional[Sequence[str]] = None,
                 block_keys: Optional[Sequence[str]] = None,
                 clean_keys: bool = False, uid: Optional[str] = None):
        super().__init__(operation_name="filterMap", uid=uid)
        self.allow_keys = list(allow_keys) if allow_keys else None
        self.block_keys = list(block_keys) if block_keys else None
        self.clean_keys = clean_keys

    def set_input(self, *features):
        # output type mirrors the concrete input map type
        out = super().set_input(*features)
        self.output_type = features[0].ftype
        return out

    def _clean(self, k: str) -> str:
        return "".join(ch for ch in str(k) if ch.isalnum()) \
            if self.clean_keys else str(k)

    def transform_value(self, value):
        m = value.value if hasattr(value, "value") else value
        allow = {self._clean(k) for k in self.allow_keys} \
            if self.allow_keys else None
        block = {self._clean(k) for k in self.block_keys} \
            if self.block_keys else set()
        out = {}
        for k, v in (m or {}).items():
            ck = self._clean(k)
            if (allow is None or ck in allow) and ck not in block:
                out[ck] = v
        return self.output_type(out)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        return FeatureColumn.from_values(
            self.output_type,
            [self.transform_value(v) for v in cols[0].data])


class TextMapLenEstimator(SequenceEstimator):
    """Text maps -> per-key total token length columns
    (reference TextMapLenEstimator.scala:44)."""

    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="textLenMap", uid=uid)
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]) -> "_TextMapLenModel":
        return _TextMapLenModel(keys=_sorted_keys(cols, self.allow_keys))


class _TextMapLenModel(SequenceModel):
    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, keys: List[List[str]], uid: Optional[str] = None):
        super().__init__(operation_name="textLenMap", uid=uid)
        self.keys = [list(k) for k in keys]

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        from .text import tokenize
        blocks, metas = [], []
        for f, col, keys in zip(self.input_features, cols, self.keys):
            n = col.n_rows
            for k in keys:
                vals = np.zeros(n)
                for i, m in enumerate(col.data):
                    v = m.get(k) if m else None
                    if v is not None:
                        vals[i] = float(sum(len(t) for t in tokenize(v)))
                blocks.append(vals)
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__, grouping=k,
                    descriptor_value="textLen"))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)


class TextMapNullEstimator(SequenceEstimator):
    """Text maps -> per-key null-indicator columns
    (reference TextMapNullEstimator in TextMapLenEstimator.scala)."""

    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="textNullMap", uid=uid)
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]) -> "_TextMapNullModel":
        return _TextMapNullModel(keys=_sorted_keys(cols, self.allow_keys))


class _TextMapNullModel(SequenceModel):
    input_types = (TextMap,)
    output_type = OPVector

    def __init__(self, keys: List[List[str]], uid: Optional[str] = None):
        super().__init__(operation_name="textNullMap", uid=uid)
        self.keys = [list(k) for k in keys]

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, keys in zip(self.input_features, cols, self.keys):
            n = col.n_rows
            for k in keys:
                isnull = np.array(
                    [0.0 if (m and m.get(k) is not None) else 1.0
                     for m in col.data])
                blocks.append(isnull)
                metas.append(VectorColumnMetadata(
                    parent_feature_name=f.name,
                    parent_feature_type=f.ftype.__name__, grouping=k,
                    indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas,
                             n_rows=cols[0].n_rows if cols else 0)
