"""NameEntityRecognizer: Text -> MultiPickListMap of entity tags.

TPU-native port of the reference NameEntityRecognizer
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
NameEntityRecognizer.scala:57-90): sentence-split the text, tag each
sentence, and merge {token -> set(entity types)} maps. The statistical
OpenNLP tagger is replaced by the deterministic heuristic tagger in
utils/text_ner.py (SURVEY §2.9 — JVM analyzers get pure-Python host
equivalents).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..features.columns import FeatureColumn
from ..stages.base import UnaryTransformer
from ..types import MultiPickListMap, Text
from ..utils.text_ner import (HeuristicNameEntityTagger, NameEntityType,
                              split_sentences)

__all__ = ["NameEntityRecognizer", "NameEntityType"]


class NameEntityRecognizer(UnaryTransformer):
    """(reference NameEntityRecognizer.scala:57)"""

    input_types = (Text,)
    output_type = MultiPickListMap

    def __init__(self, tagger: Optional[HeuristicNameEntityTagger] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="nameEntityRec", uid=uid)
        self.tagger = tagger or HeuristicNameEntityTagger()

    def transform_value(self, value) -> MultiPickListMap:
        text = value.value if hasattr(value, "value") else value
        merged: Dict[str, Set[str]] = {}
        for sentence in split_sentences(text or ""):
            for tok, ents in self.tagger.tag(sentence).items():
                merged.setdefault(tok, set()).update(ents)
        return MultiPickListMap({k: set(v) for k, v in merged.items()})

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        values = [self.transform_value(v) for v in cols[0].data]
        return FeatureColumn.from_values(MultiPickListMap, values)
