"""Numeric vectorizers: Real / Integral / Binary -> OPVector.

TPU-native ports of the reference numeric vectorizer family
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
{RealVectorizer, IntegralVectorizer, BinaryVectorizer}; dispatched from
Transmogrifier.scala:116-340). Semantics preserved:

- Real family imputes missing with the training mean (or a constant),
  Integral with the training mode, Binary fills ``false``.
- ``track_nulls`` (TransmogrifierDefaults.TrackNulls = true) appends one
  0/1 null-indicator column per input feature.

Columnar execution: each input feature is one float64 numpy column with
NaN as missing; the output matrix is assembled in one shot — no
row-at-a-time closures.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import SequenceEstimator, SequenceModel, SequenceTransformer
from ..types import Binary, Integral, OPNumeric, OPVector
from .vector_utils import NULL_INDICATOR, VectorColumnMetadata, vector_output

__all__ = ["RealVectorizer", "RealVectorizerModel", "IntegralVectorizer",
           "BinaryVectorizer"]


def _numeric_kernel(arrays, fills: List[float], track_nulls: bool):
    """Array lowering of ``_numeric_blocks`` (serving/plan.py): one (n,)
    array per input, NaN = missing; same column order as the numpy path
    (value, then null indicator, per input)."""
    import jax.numpy as jnp
    cols = []
    for x, fill in zip(arrays, fills):
        isnan = jnp.isnan(x)
        cols.append(jnp.where(isnan, fill, x))
        if track_nulls:
            cols.append(isnan.astype(x.dtype))
    return jnp.stack(cols, axis=1)


def _numeric_blocks(stage, cols: List[FeatureColumn], fills: List[float],
                    track_nulls: bool):
    blocks, metas = [], []
    for f, col, fill in zip(stage.input_features, cols, fills):
        vals = np.asarray(col.data, dtype=np.float64)
        isnan = np.isnan(vals)
        blocks.append(np.where(isnan, fill, vals))
        metas.append(VectorColumnMetadata(
            parent_feature_name=f.name,
            parent_feature_type=f.ftype.__name__))
        if track_nulls:
            blocks.append(isnan.astype(np.float64))
            metas.append(VectorColumnMetadata(
                parent_feature_name=f.name,
                parent_feature_type=f.ftype.__name__,
                indicator_value=NULL_INDICATOR))
    return blocks, metas


class RealVectorizerModel(SequenceModel):
    input_types = (OPNumeric,)
    output_type = OPVector

    def __init__(self, fill_values: List[float], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecReal", uid=uid)
        self.fill_values = [float(v) for v in np.asarray(fill_values)]
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = _numeric_blocks(self, cols, self.fill_values,
                                        self.track_nulls)
        return vector_output(self.get_output().name, blocks, metas)

    def transform_arrays(self, arrays):
        return _numeric_kernel(arrays, self.fill_values, self.track_nulls)


class RealVectorizer(SequenceEstimator):
    """Impute-with-mean (or constant) + null tracking for the Real family
    (reference RealVectorizer / FillMissingWithMean)."""

    input_types = (OPNumeric,)
    output_type = OPVector

    def __init__(self, fill_with_mean: bool = True, fill_value: float = 0.0,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecReal", uid=uid)
        self.fill_with_mean = fill_with_mean
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_columns(self, cols: List[FeatureColumn]) -> RealVectorizerModel:
        fills = []
        for col in cols:
            vals = np.asarray(col.data, dtype=np.float64)
            ok = ~np.isnan(vals)
            if self.fill_with_mean and ok.any():
                fills.append(float(np.mean(vals[ok])))
            else:
                fills.append(float(self.fill_value))
        return RealVectorizerModel(fill_values=fills,
                                   track_nulls=self.track_nulls)


class IntegralVectorizer(SequenceEstimator):
    """Impute-with-mode + null tracking for Integral features
    (reference IntegralVectorizer)."""

    input_types = (Integral,)
    output_type = OPVector

    def __init__(self, fill_with_mode: bool = True, fill_value: float = 0.0,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecIntegral", uid=uid)
        self.fill_with_mode = fill_with_mode
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_columns(self, cols: List[FeatureColumn]) -> RealVectorizerModel:
        fills = []
        for col in cols:
            vals = np.asarray(col.data, dtype=np.float64)
            ok = vals[~np.isnan(vals)]
            if self.fill_with_mode and len(ok):
                uniq, counts = np.unique(ok, return_counts=True)
                fills.append(float(uniq[np.argmax(counts)]))
            else:
                fills.append(float(self.fill_value))
        return RealVectorizerModel(fill_values=fills,
                                   track_nulls=self.track_nulls)


class BinaryVectorizer(SequenceTransformer):
    """Binary -> {0,1} with false-fill + null tracking
    (reference BinaryVectorizer; stateless, so a Transformer)."""

    input_types = (Binary,)
    output_type = OPVector

    def __init__(self, fill_value: bool = False, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecBinary", uid=uid)
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        fills = [float(self.fill_value)] * len(cols)
        blocks, metas = _numeric_blocks(self, cols, fills, self.track_nulls)
        return vector_output(self.get_output().name, blocks, metas)

    def transform_arrays(self, arrays):
        return _numeric_kernel(arrays, [float(self.fill_value)] * len(arrays),
                               self.track_nulls)
