"""Text vectorization: tokenizer, hashing trick, smart pivot-or-hash.

TPU-native ports of the reference text pipeline
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
{SmartTextVectorizer.scala:60, OPCollectionHashingVectorizer.scala,
TextTokenizer.scala}). The reference tokenizes with Lucene analyzers and
hashes with Spark's MurmurHash3 HashingTF; here tokenization is a unicode
regex analyzer (host-side, pre-TPU) and hashing a stable md5-derived
bucket hash — same semantics, no JVM.

SmartTextVectorizer's per-feature decision rule is preserved: if the
training cardinality of a text feature is at most ``max_cardinality`` it
is pivoted like a categorical (one-hot over top-K), otherwise its tokens
are hashed into ``num_hashes`` buckets (term frequencies).
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import SequenceEstimator, SequenceModel
from ..types import OPVector, Text, TextList
from .categorical import _pivot_block, _pivot_metas, _top_categories
from .vector_utils import (NULL_INDICATOR, VectorColumnMetadata, stable_hash,
                           vector_output)

__all__ = ["tokenize", "TextTokenizer", "SmartTextVectorizer",
           "SmartTextVectorizerModel", "TextHashVectorizer"]

_TOKEN_RE = re.compile(r"[^\W_]+", re.UNICODE)

#: CJK codepoint ranges (Han, Hiragana, Katakana, Hangul) — runs of
#: these emit overlapping character BIGRAMS, Lucene CJKBigramFilter's
#: behavior (the reference's analyzer chain ships CJKAnalyzer/Kuromoji,
#: core/build.gradle:18-21; bigrams are the classic statistical
#: segmentation for unsegmented scripts)
_CJK_RE = re.compile(
    "([㐀-䶿一-鿿぀-ゟ゠-ヿ"
    "가-힯]+)")


def _cjk_bigrams(run: str) -> List[str]:
    if len(run) == 1:
        return [run]
    return [run[i:i + 2] for i in range(len(run) - 1)]


def tokenize(text: Optional[str], min_token_length: int = 1,
             to_lowercase: bool = True) -> List[str]:
    """Unicode word tokenizer with CJK bigram fallback (replaces the
    Lucene analyzer chain of reference TextTokenizer.scala; host-side
    preprocessing). Non-CJK scripts split on word boundaries; CJK runs
    — which carry no spaces to split on — become overlapping character
    bigrams. min_token_length applies to word tokens only (bigrams are
    already minimal units)."""
    if text is None:
        return []
    if to_lowercase:
        text = text.lower()
    out: List[str] = []
    for part in _CJK_RE.split(text):
        if not part:
            continue
        if _CJK_RE.fullmatch(part):
            out.extend(_cjk_bigrams(part))
        else:
            out.extend(t for t in _TOKEN_RE.findall(part)
                       if len(t) >= min_token_length)
    return out


class TextTokenizer(SequenceModel):
    """Text -> TextList of tokens (reference TextTokenizer.scala). A
    stateless transformer, modeled as a 1-sequence for uniformity."""

    input_types = (Text,)
    output_type = TextList

    def __init__(self, min_token_length: int = 1, to_lowercase: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="tokenize", uid=uid)
        self.min_token_length = min_token_length
        self.to_lowercase = to_lowercase

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        col = cols[0]
        out = [tuple(tokenize(v, self.min_token_length, self.to_lowercase))
               for v in col.data]
        return FeatureColumn.from_values(TextList, out)


def _hash_block(texts, n_buckets: int, track_nulls: bool,
                binary_freq: bool = False) -> np.ndarray:
    n = len(texts)
    width = n_buckets + (1 if track_nulls else 0)
    block = np.zeros((n, width), dtype=np.float64)
    for i, v in enumerate(texts):
        toks = tokenize(v)
        if v is None:
            if track_nulls:
                block[i, n_buckets] = 1.0
            continue
        for t in toks:
            j = stable_hash(t, n_buckets)
            if binary_freq:
                block[i, j] = 1.0
            else:
                block[i, j] += 1.0
    return block


def _hash_metas(feature, n_buckets: int, track_nulls: bool
                ) -> List[VectorColumnMetadata]:
    metas = [VectorColumnMetadata(
        parent_feature_name=feature.name,
        parent_feature_type=feature.ftype.__name__,
        grouping=feature.name, descriptor_value=f"hash_{j}")
        for j in range(n_buckets)]
    if track_nulls:
        metas.append(VectorColumnMetadata(
            parent_feature_name=feature.name,
            parent_feature_type=feature.ftype.__name__,
            grouping=feature.name, indicator_value=NULL_INDICATOR))
    return metas


class SmartTextVectorizerModel(SequenceModel):
    input_types = (Text,)
    output_type = OPVector

    def __init__(self, strategies: List[Tuple[str, object]],
                 num_hashes: int = 512, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtVec", uid=uid)
        #: per input feature: ("pivot", [categories]) or ("hash", None)
        self.strategies = [tuple(s) for s in strategies]
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls

    def _vector_metas(self) -> List[VectorColumnMetadata]:
        # built once per fitted model, not per batch: a hashing slot
        # emits num_hashes (+null) metadata records whose content is
        # fully determined at fit time, and rebuilding ~512 records per
        # transform call was the dominant FIXED cost of every serving
        # batch (size-independent; profiled in the PR-8 serve loop)
        metas = getattr(self, "_metas_cache", None)
        if metas is None:
            metas = []
            for f, (kind, cats) in zip(self.input_features,
                                       self.strategies):
                if kind == "pivot":
                    metas.extend(_pivot_metas(f, list(cats),
                                              self.track_nulls))
                else:
                    metas.extend(_hash_metas(f, self.num_hashes,
                                             self.track_nulls))
            self._metas_cache = metas
        return metas

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks = []
        for col, (kind, cats) in zip(cols, self.strategies):
            if kind == "pivot":
                rows = [None if v is None else (v,) for v in col.data]
                blocks.append(_pivot_block(rows, list(cats),
                                           self.track_nulls))
            else:
                blocks.append(_hash_block(col.data, self.num_hashes,
                                          self.track_nulls))
        return vector_output(self.get_output().name, blocks,
                             self._vector_metas())


class SmartTextVectorizer(SequenceEstimator):
    """Pivot-or-hash decision per text feature
    (reference SmartTextVectorizer.scala:60, fitFn:79-98)."""

    input_types = (Text,)
    output_type = OPVector

    def __init__(self, max_cardinality: int = 30, top_k: int = 20,
                 min_support: int = 10, num_hashes: int = 512,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtVec", uid=uid)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> SmartTextVectorizerModel:
        strategies: List[Tuple[str, object]] = []
        for col in cols:
            counts: dict = {}
            for v in col.data:
                if v is not None:
                    counts[v] = counts.get(v, 0) + 1
            if len(counts) <= self.max_cardinality:
                strategies.append(
                    ("pivot",
                     _top_categories(counts, self.top_k, self.min_support)))
            else:
                strategies.append(("hash", None))
        return SmartTextVectorizerModel(strategies=strategies,
                                        num_hashes=self.num_hashes,
                                        track_nulls=self.track_nulls)


class TextListHashVectorizer(SequenceModel):
    """Hashing-trick vectorizer over pre-tokenized text lists
    (reference OPCollectionHashingVectorizer.scala list path)."""

    from ..types import TextList as _TextList
    input_types = (_TextList,)
    output_type = OPVector

    def __init__(self, num_hashes: int = 512, binary_freq: bool = False,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="hashTextList", uid=uid)
        self.num_hashes = num_hashes
        self.binary_freq = binary_freq
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col in zip(self.input_features, cols):
            n = col.n_rows
            width = self.num_hashes + (1 if self.track_nulls else 0)
            block = np.zeros((n, width), dtype=np.float64)
            for i, toks in enumerate(col.data):
                if not toks:
                    if self.track_nulls:
                        block[i, self.num_hashes] = 1.0
                    continue
                for t in toks:
                    j = stable_hash(str(t), self.num_hashes)
                    if self.binary_freq:
                        block[i, j] = 1.0
                    else:
                        block[i, j] += 1.0
            blocks.append(block)
            metas.extend(_hash_metas(f, self.num_hashes, self.track_nulls))
        return vector_output(self.get_output().name, blocks, metas)


class TextHashVectorizer(SequenceModel):
    """Pure hashing-trick vectorizer (reference
    OPCollectionHashingVectorizer.scala); stateless."""

    input_types = (Text,)
    output_type = OPVector

    def __init__(self, num_hashes: int = 512, binary_freq: bool = False,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="hashText", uid=uid)
        self.num_hashes = num_hashes
        self.binary_freq = binary_freq
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col in zip(self.input_features, cols):
            blocks.append(_hash_block(col.data, self.num_hashes,
                                      self.track_nulls, self.binary_freq))
            metas.extend(_hash_metas(f, self.num_hashes, self.track_nulls))
        return vector_output(self.get_output().name, blocks, metas)


class TextListNullTransformer(SequenceModel):
    """Text lists -> per-feature null/empty indicator column
    (reference TextListNullTransformer.scala)."""

    from ..types import TextList as _TL
    input_types = (_TL,)
    output_type = OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="textListNull", uid=uid)

    def transform_columns(self, cols):
        import numpy as _np
        from .vector_utils import NULL_INDICATOR, VectorColumnMetadata, \
            vector_output
        blocks, metas = [], []
        for f, col in zip(self.input_features, cols):
            blocks.append(_np.array(
                [0.0 if toks else 1.0 for toks in col.data]))
            metas.append(VectorColumnMetadata(
                parent_feature_name=f.name,
                parent_feature_type=f.ftype.__name__, grouping=f.name,
                indicator_value=NULL_INDICATOR))
        return vector_output(self.get_output().name, blocks, metas)
