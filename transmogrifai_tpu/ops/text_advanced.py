"""Advanced text features: count vectorization, TF-IDF, Word2Vec, LDA.

TPU-native replacements for the reference's wrapped Spark text stages
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
OpCountVectorizer.scala, the HashingTF+IDF TF-IDF pipeline,
OpWord2Vec.scala, OpLDA.scala — all thin wrappers over Spark MLlib in
the reference, re-implemented natively here):

- :class:`CountVectorizer` — vocabulary-based token counts with
  ``min_df``/``max_vocab`` pruning (MLlib CountVectorizer semantics).
- :class:`TfIdfVectorizer` — token counts scaled by smoothed inverse
  document frequency (MLlib IDF formula ``log((n+1)/(df+1))``).
- :class:`Word2Vec` — skip-gram with negative sampling trained as one
  jitted ``lax.scan`` over static-shape minibatches of (center,
  context, negatives) triples; embedding lookups and the output is the
  document-mean vector, as MLlib's Word2Vec transform does.
- :class:`LDA` — online variational-Bayes topic model: per-document
  E-steps are a vmapped fixed-point iteration (static iteration count),
  M-step one matmul — document-topic mixtures come out as the feature
  vector, matching OpLDA's output.
"""
from __future__ import annotations

import functools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import (SequenceEstimator, SequenceModel, UnaryEstimator,
                           UnaryModel)
from ..types import OPVector, TextList
from .vector_utils import VectorColumnMetadata, vector_output

__all__ = ["CountVectorizer", "CountVectorizerModel", "TfIdfVectorizer",
           "TfIdfVectorizerModel", "Word2Vec", "Word2VecModel", "LDA",
           "LDAModel"]


# ---------------------------------------------------------------------------
# count vectorizer
# ---------------------------------------------------------------------------

def _count_matrix(token_lists, vocab_index: Dict[str, int],
                  binary: bool) -> np.ndarray:
    n, v = len(token_lists), len(vocab_index)
    mat = np.zeros((n, v), dtype=np.float64)
    for i, toks in enumerate(token_lists):
        if not toks:
            continue
        for t in toks:
            j = vocab_index.get(str(t))
            if j is not None:
                if binary:
                    mat[i, j] = 1.0
                else:
                    mat[i, j] += 1.0
    return mat


class CountVectorizerModel(SequenceModel):
    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vocabulary: List[List[str]], binary: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="countVec", uid=uid)
        self.vocabulary = [list(v) for v in vocabulary]
        self.binary = binary

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, vocab in zip(self.input_features, cols,
                                 self.vocabulary):
            index = {t: j for j, t in enumerate(vocab)}
            blocks.append(_count_matrix(col.data, index, self.binary))
            metas.extend(VectorColumnMetadata(
                parent_feature_name=f.name,
                parent_feature_type=f.ftype.__name__,
                grouping=f.name, indicator_value=t) for t in vocab)
        return vector_output(self.get_output().name, blocks, metas)


class CountVectorizer(SequenceEstimator):
    """(reference OpCountVectorizer.scala / MLlib CountVectorizer)"""

    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, min_df: int = 1, max_vocab: int = 10_000,
                 binary: bool = False, uid: Optional[str] = None):
        super().__init__(operation_name="countVec", uid=uid)
        self.min_df = min_df
        self.max_vocab = max_vocab
        self.binary = binary

    def _fit_vocab(self, col: FeatureColumn) -> List[str]:
        df: Dict[str, int] = {}
        for toks in col.data:
            if not toks:
                continue
            for t in set(str(x) for x in toks):
                df[t] = df.get(t, 0) + 1
        terms = [(t, c) for t, c in df.items() if c >= self.min_df]
        terms.sort(key=lambda tc: (-tc[1], tc[0]))
        return [t for t, _ in terms[:self.max_vocab]]

    def fit_columns(self, cols: List[FeatureColumn]) -> CountVectorizerModel:
        return CountVectorizerModel(
            vocabulary=[self._fit_vocab(c) for c in cols],
            binary=self.binary)


class TfIdfVectorizerModel(CountVectorizerModel):
    def __init__(self, vocabulary: List[List[str]],
                 idf: List[List[float]], uid: Optional[str] = None):
        super().__init__(vocabulary=vocabulary, binary=False, uid=uid)
        self.operation_name = "tfIdf"
        self.idf = [[float(x) for x in v] for v in idf]

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        blocks, metas = [], []
        for f, col, vocab, idf in zip(self.input_features, cols,
                                      self.vocabulary, self.idf):
            index = {t: j for j, t in enumerate(vocab)}
            tf = _count_matrix(col.data, index, binary=False)
            blocks.append(tf * np.asarray(idf))
            metas.extend(VectorColumnMetadata(
                parent_feature_name=f.name,
                parent_feature_type=f.ftype.__name__,
                grouping=f.name, indicator_value=t,
                descriptor_value="tfidf") for t in vocab)
        return vector_output(self.get_output().name, blocks, metas)


class TfIdfVectorizer(CountVectorizer):
    """TF-IDF with MLlib's smoothed IDF ``log((n+1)/(df+1))``
    (reference TF-IDF via wrapped HashingTF + IDF)."""

    def __init__(self, min_df: int = 1, max_vocab: int = 10_000,
                 uid: Optional[str] = None):
        super().__init__(min_df=min_df, max_vocab=max_vocab, binary=False,
                         uid=uid)
        self.operation_name = "tfIdf"

    def fit_columns(self, cols: List[FeatureColumn]) -> TfIdfVectorizerModel:
        vocabs, idfs = [], []
        for col in cols:
            vocab = self._fit_vocab(col)
            index = {t: j for j, t in enumerate(vocab)}
            n = col.n_rows
            df = np.zeros(len(vocab))
            for toks in col.data:
                if not toks:
                    continue
                for t in set(str(x) for x in toks):
                    j = index.get(t)
                    if j is not None:
                        df[j] += 1
            vocabs.append(vocab)
            idfs.append(list(np.log((n + 1.0) / (df + 1.0))))
        return TfIdfVectorizerModel(vocabulary=vocabs, idf=idfs)


# ---------------------------------------------------------------------------
# word2vec (skip-gram, negative sampling)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("epochs",))
def _fit_w2v(centers, contexts, negatives, emb0, out0, lr, *, epochs: int):
    """SGD over precomputed (center, context, negatives) triples; one
    ``lax.scan`` pass per epoch, all lookups static-shape gathers."""

    def loss_fn(params, c, ctx, neg):
        emb, out = params
        v = emb[c]                             # (B, D)
        pos = jnp.sum(v * out[ctx], axis=1)
        neg_s = jnp.einsum("bd,bkd->bk", v, out[neg])
        return -(jnp.mean(jax.nn.log_sigmoid(pos))
                 + jnp.mean(jnp.sum(jax.nn.log_sigmoid(-neg_s), axis=1)))

    grad_fn = jax.grad(loss_fn)

    def epoch(params, _):
        def step(p, batch):
            c, ctx, neg = batch
            g = grad_fn(p, c, ctx, neg)
            return jax.tree_util.tree_map(
                lambda x, gx: x - lr * gx, p, g), None
        params, _ = jax.lax.scan(step, params, (centers, contexts,
                                                negatives))
        return params, None

    (emb, out), _ = jax.lax.scan(epoch, (emb0, out0), None, length=epochs)
    return emb


class Word2VecModel(UnaryModel):
    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vocabulary: List[str], vectors, uid: Optional[str]
                 = None):
        super().__init__(operation_name="word2Vec", uid=uid)
        self.vocabulary = [str(t) for t in vocabulary]
        self.vectors = np.asarray(vectors, dtype=np.float64)
        self._index = {t: i for i, t in enumerate(self.vocabulary)}

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        f = self.input_features[0]
        d = self.vectors.shape[1]
        out = np.zeros((cols[0].n_rows, d))
        for i, toks in enumerate(cols[0].data):
            if not toks:
                continue
            idx = [self._index[str(t)] for t in toks
                   if str(t) in self._index]
            if idx:
                out[i] = self.vectors[idx].mean(axis=0)
        metas = [VectorColumnMetadata(
            parent_feature_name=f.name,
            parent_feature_type=f.ftype.__name__,
            descriptor_value=f"w2v_{j}") for j in range(d)]
        return vector_output(self.get_output().name, [out], metas)


class Word2Vec(UnaryEstimator):
    """Skip-gram with negative sampling; documents transform to the mean
    of their token vectors (reference OpWord2Vec.scala / MLlib Word2Vec)."""

    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vector_size: int = 32, window: int = 3,
                 min_count: int = 2, num_negatives: int = 4,
                 epochs: int = 5, step_size: float = 0.05,
                 batch_size: int = 512, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(operation_name="word2Vec", uid=uid)
        self.vector_size = vector_size
        self.window = window
        self.min_count = min_count
        self.num_negatives = num_negatives
        self.epochs = epochs
        self.step_size = step_size
        self.batch_size = batch_size
        self.seed = seed

    def fit_columns(self, cols: List[FeatureColumn]) -> Word2VecModel:
        rng = np.random.default_rng(self.seed)
        counts: Dict[str, int] = {}
        docs = []
        for toks in cols[0].data:
            toks = [str(t) for t in (toks or [])]
            docs.append(toks)
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
        vocab = sorted([t for t, c in counts.items()
                        if c >= self.min_count])
        index = {t: i for i, t in enumerate(vocab)}
        v = len(vocab)
        if v == 0:
            return Word2VecModel(vocabulary=[],
                                 vectors=np.zeros((0, self.vector_size)))
        pairs = []
        for toks in docs:
            ids = [index[t] for t in toks if t in index]
            for i, c in enumerate(ids):
                lo = max(0, i - self.window)
                hi = min(len(ids), i + self.window + 1)
                for j in range(lo, hi):
                    if j != i:
                        pairs.append((c, ids[j]))
        if not pairs:
            return Word2VecModel(
                vocabulary=vocab,
                vectors=np.zeros((v, self.vector_size)))
        pairs = np.asarray(pairs, dtype=np.int32)
        rng.shuffle(pairs)
        b = min(self.batch_size, len(pairs))
        n_batches = max(1, len(pairs) // b)
        pairs = pairs[:n_batches * b]
        centers = pairs[:, 0].reshape(n_batches, b)
        contexts = pairs[:, 1].reshape(n_batches, b)
        negatives = rng.integers(
            0, v, (n_batches, b, self.num_negatives)).astype(np.int32)
        emb0 = (rng.random((v, self.vector_size)) - 0.5) / self.vector_size
        out0 = (rng.random((v, self.vector_size)) - 0.5) / self.vector_size
        emb = _fit_w2v(jnp.asarray(centers), jnp.asarray(contexts),
                       jnp.asarray(negatives), jnp.asarray(emb0),
                       jnp.asarray(out0), self.step_size,
                       epochs=self.epochs)
        return Word2VecModel(vocabulary=vocab, vectors=np.asarray(emb))


# ---------------------------------------------------------------------------
# LDA (online variational Bayes)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_iter",))
def _lda_e_step(counts, exp_topic_word, alpha, *, n_iter: int):
    """Batch E-step: fixed-point gamma updates (Hoffman et al. 2010),
    vmapped over documents. counts: (n_docs, vocab)."""

    def one_doc(cnts, gamma0):
        def body(_, gamma):
            e_log_theta = jnp.exp(
                jax.scipy.special.digamma(gamma)
                - jax.scipy.special.digamma(jnp.sum(gamma)))
            phi_norm = e_log_theta @ exp_topic_word + 1e-100   # (vocab,)
            return alpha + e_log_theta * (
                (cnts / phi_norm) @ exp_topic_word.T)
        return jax.lax.fori_loop(0, n_iter, body, gamma0)

    k = exp_topic_word.shape[0]
    gamma0 = jnp.ones((counts.shape[0], k))
    return jax.vmap(one_doc)(counts, gamma0)


class LDAModel(UnaryModel):
    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vocabulary: List[str], topic_word, alpha: float,
                 uid: Optional[str] = None):
        super().__init__(operation_name="lda", uid=uid)
        self.vocabulary = [str(t) for t in vocabulary]
        self.topic_word = np.asarray(topic_word, dtype=np.float64)
        self.alpha = float(alpha)
        self._index = {t: i for i, t in enumerate(self.vocabulary)}

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        f = self.input_features[0]
        k = self.topic_word.shape[0]
        counts = _count_matrix(cols[0].data, self._index, binary=False)
        gamma = np.asarray(_lda_e_step(
            jnp.asarray(counts), jnp.asarray(self.topic_word),
            self.alpha, n_iter=50))
        theta = gamma / gamma.sum(axis=1, keepdims=True)
        metas = [VectorColumnMetadata(
            parent_feature_name=f.name,
            parent_feature_type=f.ftype.__name__,
            descriptor_value=f"topic_{j}") for j in range(k)]
        return vector_output(self.get_output().name, [theta], metas)


class LDA(UnaryEstimator):
    """Online variational LDA; the feature vector is the document-topic
    mixture (reference OpLDA.scala / MLlib LDA)."""

    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, k: int = 10, max_iter: int = 20,
                 doc_concentration: float = 0.1,
                 topic_concentration: float = 0.01,
                 min_count: int = 1, max_vocab: int = 5000,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(operation_name="lda", uid=uid)
        self.k = k
        self.max_iter = max_iter
        self.doc_concentration = doc_concentration
        self.topic_concentration = topic_concentration
        self.min_count = min_count
        self.max_vocab = max_vocab
        self.seed = seed

    def fit_columns(self, cols: List[FeatureColumn]) -> LDAModel:
        col = cols[0]
        df: Dict[str, int] = {}
        for toks in col.data:
            for t in (toks or []):
                df[str(t)] = df.get(str(t), 0) + 1
        vocab = sorted([t for t, c in df.items() if c >= self.min_count],
                       key=lambda t: (-df[t], t))[:self.max_vocab]
        index = {t: i for i, t in enumerate(vocab)}
        counts = _count_matrix(col.data, index, binary=False)
        rng = np.random.default_rng(self.seed)
        lam = rng.gamma(100.0, 0.01, (self.k, len(vocab)))
        for _ in range(self.max_iter):
            import scipy.special as sps
            e_log_beta = sps.digamma(lam) - sps.digamma(
                lam.sum(axis=1, keepdims=True))
            exp_beta = np.exp(e_log_beta)
            gamma = np.asarray(_lda_e_step(
                jnp.asarray(counts), jnp.asarray(exp_beta),
                self.doc_concentration, n_iter=20))
            e_log_theta = np.exp(sps.digamma(gamma) - sps.digamma(
                gamma.sum(axis=1, keepdims=True)))
            phi_norm = e_log_theta @ exp_beta + 1e-100
            # M-step sufficient statistics
            sstats = exp_beta * (e_log_theta.T @ (counts / phi_norm))
            lam = self.topic_concentration + sstats
        topic_word = np.exp(
            sps.digamma(lam) - sps.digamma(lam.sum(axis=1, keepdims=True)))
        return LDAModel(vocabulary=vocab, topic_word=topic_word,
                        alpha=self.doc_concentration)
