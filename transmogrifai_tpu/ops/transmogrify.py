"""Transmogrifier: automated feature engineering — pillar #1.

TPU-native port of core/src/main/scala/com/salesforce/op/stages/impl/
feature/Transmogrifier.scala:91-340: group a heterogeneous bag of typed
features by feature type, dispatch each group to its default vectorizer,
and combine everything into one OPVector via VectorsCombiner. Defaults
mirror ``TransmogrifierDefaults`` (Transmogrifier.scala:52): TopK=20,
MinSupport=10, 512 hash features, TrackNulls=true, MaxCardinality=30.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Type

from ..features.feature import Feature
from ..types import (Binary, BinaryMap, Date, DateList, DateMap, DateTime,
                     FeatureType, Geolocation, GeolocationMap, Integral,
                     MultiPickList, MultiPickListMap, OPMap, OPSet,
                     OPVector, Real, Text, TextList, TextMap)
from .categorical import MultiPickListVectorizer, OneHotVectorizer
from .combiner import VectorsCombiner
from .date import DateListVectorizer, DateToUnitCircleVectorizer
from .geo import GeolocationVectorizer
from .maps import (BinaryMapVectorizer, DateMapToUnitCircleVectorizer,
                   GeolocationMapVectorizer, MultiPickListMapVectorizer,
                   RealMapVectorizer, SmartTextMapVectorizer,
                   TextMapPivotVectorizer)
from .numeric import BinaryVectorizer, IntegralVectorizer, RealVectorizer
from .text import SmartTextVectorizer, TextHashVectorizer

__all__ = ["TransmogrifierDefaults", "transmogrify"]


@dataclass
class TransmogrifierDefaults:
    """Reference Transmogrifier.scala:52."""
    top_k: int = 20
    min_support: int = 10
    num_hashes: int = 512
    track_nulls: bool = True
    max_cardinality: int = 30
    date_time_period: str = "HourOfDay"


#: categorical text subtypes pivoted directly (reference dispatches
#: PickList/ComboBox/ID/Country/State/... to one-hot, Transmogrifier.scala:116)
_PIVOT_TEXT_NAMES = {"PickList", "ComboBox", "ID", "Country", "State",
                     "PostalCode", "City", "Street", "Email", "Phone", "URL"}


def _dispatch_group(ftype: Type[FeatureType],
                    defaults: TransmogrifierDefaults):
    """Default vectorizer stage for a concrete feature type."""
    if issubclass(ftype, Date):  # Date/DateTime before Integral (subclass)
        return DateToUnitCircleVectorizer(
            time_period=defaults.date_time_period)
    if issubclass(ftype, Binary):
        return BinaryVectorizer(track_nulls=defaults.track_nulls)
    if issubclass(ftype, Integral):
        return IntegralVectorizer(track_nulls=defaults.track_nulls)
    if issubclass(ftype, Real):
        return RealVectorizer(track_nulls=defaults.track_nulls)
    if issubclass(ftype, Text):
        if ftype.__name__ in _PIVOT_TEXT_NAMES:
            return OneHotVectorizer(top_k=defaults.top_k,
                                    min_support=defaults.min_support,
                                    track_nulls=defaults.track_nulls)
        return SmartTextVectorizer(
            max_cardinality=defaults.max_cardinality,
            top_k=defaults.top_k, min_support=defaults.min_support,
            num_hashes=defaults.num_hashes,
            track_nulls=defaults.track_nulls)
    if issubclass(ftype, OPSet):
        return MultiPickListVectorizer(top_k=defaults.top_k,
                                       min_support=defaults.min_support,
                                       track_nulls=defaults.track_nulls)
    if issubclass(ftype, Geolocation):
        return GeolocationVectorizer(track_nulls=defaults.track_nulls)
    if issubclass(ftype, DateList):
        return DateListVectorizer(track_nulls=defaults.track_nulls)
    if issubclass(ftype, TextList):
        from .text import TextListHashVectorizer
        return TextListHashVectorizer(num_hashes=defaults.num_hashes,
                                      track_nulls=defaults.track_nulls)
    if issubclass(ftype, GeolocationMap):
        return GeolocationMapVectorizer(track_nulls=defaults.track_nulls)
    if issubclass(ftype, MultiPickListMap):
        return MultiPickListMapVectorizer(
            top_k=defaults.top_k, min_support=defaults.min_support,
            track_nulls=defaults.track_nulls)
    if issubclass(ftype, BinaryMap):
        return BinaryMapVectorizer(track_nulls=defaults.track_nulls)
    if issubclass(ftype, TextMap):
        # categorical map subtypes pivot directly; free-text maps get the
        # per-key pivot-or-hash decision (SmartTextMapVectorizer.scala)
        if ftype.__name__.replace("Map", "") in _PIVOT_TEXT_NAMES:
            return TextMapPivotVectorizer(
                top_k=defaults.top_k, min_support=defaults.min_support,
                track_nulls=defaults.track_nulls)
        return SmartTextMapVectorizer(
            max_cardinality=defaults.max_cardinality,
            top_k=defaults.top_k, min_support=defaults.min_support,
            num_hashes=defaults.num_hashes,
            track_nulls=defaults.track_nulls)
    if issubclass(ftype, DateMap):  # before the numeric-map catch-all
        return DateMapToUnitCircleVectorizer(
            time_period=defaults.date_time_period)
    if issubclass(ftype, OPMap):  # numeric/integral maps
        return RealMapVectorizer(track_nulls=defaults.track_nulls)
    raise TypeError(
        f"transmogrify: no default vectorizer for {ftype.__name__}")


def transmogrify(features: Sequence[Feature],
                 defaults: TransmogrifierDefaults = None) -> Feature:
    """Turn typed features into a single OPVector feature
    (reference RichFeaturesCollection.transmogrify, core/.../dsl/
    RichFeaturesCollection.scala:69 -> Transmogrifier.scala:101).
    """
    if not features:
        raise ValueError("transmogrify requires at least one feature")
    defaults = defaults or TransmogrifierDefaults()

    vectors: List[Feature] = []
    groups: Dict[type, List[Feature]] = {}
    for f in features:
        if issubclass(f.ftype, OPVector):
            vectors.append(f)  # already vectorized — pass through
        else:
            groups.setdefault(f.ftype, []).append(f)

    for ftype in sorted(groups, key=lambda t: t.__name__):
        group = groups[ftype]
        stage = _dispatch_group(ftype, defaults)
        vectors.append(stage.set_input(*group).get_output())

    if len(vectors) == 1:
        return vectors[0]
    return VectorsCombiner().set_input(*vectors).get_output()
