"""Shared helpers for vectorizer stages: matrix assembly + metadata."""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import FeatureColumn
from ..utils.vector_meta import (NULL_INDICATOR, OTHER_INDICATOR,
                                 VectorColumnMetadata, VectorMetadata)

__all__ = ["vector_output", "stable_hash", "NULL_INDICATOR",
           "OTHER_INDICATOR", "VectorColumnMetadata", "VectorMetadata"]


def vector_output(name: str, blocks: Sequence[np.ndarray],
                  columns: Sequence[VectorColumnMetadata],
                  n_rows: int = 0) -> FeatureColumn:
    """Assemble per-feature column blocks into one OPVector column.
    ``n_rows`` sizes the zero-width matrix when ``blocks`` is empty —
    a map vectorizer fitted with ZERO keys (all-empty training maps)
    must still emit one (n, 0) row per input row, not a (0, 0) column
    that breaks the dataset's row-count invariant."""
    if blocks:
        mat = np.concatenate([np.atleast_2d(b.T).T if b.ndim == 1
                              else b for b in blocks], axis=1)
    else:
        mat = np.zeros((n_rows, 0), dtype=np.float64)
    meta = VectorMetadata(name=name, columns=tuple(columns))
    return FeatureColumn.vector(mat, meta)


def stable_hash(token: str, n_buckets: int) -> int:
    """Deterministic string hash (reference uses MurmurHash3 via Spark
    HashingTF, core/.../feature/OPCollectionHashingVectorizer.scala; any
    stable uniform hash preserves the semantics)."""
    h = hashlib.md5(token.encode("utf-8")).digest()
    return int.from_bytes(h[:8], "little") % n_buckets
