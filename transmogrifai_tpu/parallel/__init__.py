"""Multi-chip execution: device meshes, sharding, collectives (SURVEY §2.9,
§5.8). Replaces the reference's Spark task parallelism + Rabit allreduce
with jax.sharding meshes and XLA collectives over ICI."""
from .distributed import (initialize_distributed, shard_wide_matrix,
                          wide_matrix_sharding)
from .mesh import (Mesh, NamedSharding, PartitionSpec, cv_mesh, make_mesh,
                   n_devices, replicate, shard_rows, to_host)

__all__ = ["Mesh", "NamedSharding", "PartitionSpec", "cv_mesh", "make_mesh",
           "n_devices", "replicate", "shard_rows", "to_host",
           "initialize_distributed", "wide_matrix_sharding",
           "shard_wide_matrix"]
