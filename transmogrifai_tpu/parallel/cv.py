"""Fold x grid x data sharded model fitting — the multi-chip CV kernel.

This is the TPU mapping of the reference's model-selection parallelism
(SURVEY §2.9): the per-fold / per-estimator ``Future`` loop of
core/src/main/scala/com/salesforce/op/tuning/OpValidator.scala:270-310 and
OpCrossValidation.scala:100-117 becomes one SPMD program over a
``("folds", "data")`` mesh:

- the feature matrix is sharded over the ``data`` axis (row parallelism;
  gradient reductions are ``psum`` over ICI — the role Rabit allreduce
  plays for the reference's XGBoost),
- folds are sharded over the ``folds`` axis (task parallelism; each shard
  trains its folds' candidates independently),
- the hyperparameter grid is ``vmap``-ed inside each shard, so a whole
  grid trains as one batched XLA computation on the MXU.

Fold membership is expressed as 0/1 sample masks, which makes every fold
the same static shape — the XLA-friendly equivalent of materializing k
train/validation splits.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

__all__ = ["fold_masks", "fit_logistic_fold_grid", "eval_fold_grid"]


def fold_masks(n: int, n_folds: int, seed: int = 42,
               y: Optional[np.ndarray] = None) -> np.ndarray:
    """(n_folds, n) float masks: mask[f, i] = 1 if row i is in fold f's
    TRAIN set (i.e. row i's held-out fold != f). Stratified by ``y`` when
    given (reference OpCrossValidation.createTrainValidationSplits:139)."""
    rng = np.random.default_rng(seed)
    assign = np.empty(n, dtype=np.int64)
    if y is None:
        assign[:] = rng.permutation(n) % n_folds
    else:
        for cls in np.unique(y):
            idx = np.nonzero(y == cls)[0]
            assign[idx] = rng.permutation(len(idx)) % n_folds
    return (assign[None, :] != np.arange(n_folds)[:, None]).astype(np.float64)


def _logistic_grad_local(params, X, y, w_mask):
    """Summed (unnormalized) logistic-loss gradient over the local rows —
    callers psum across the data axis before normalizing."""
    d = X.shape[1]
    w, b = params[:d], params[d]
    m = X @ w + b
    s = 2.0 * y - 1.0
    sig = jax.nn.sigmoid(-s * m) * w_mask
    gw = -(X.T @ (sig * s))
    gb = -jnp.sum(sig * s)
    return jnp.concatenate([gw, jnp.array([gb])])


def fit_logistic_fold_grid(X: np.ndarray, y: np.ndarray,
                           masks: np.ndarray, regs: np.ndarray,
                           mesh: Mesh, steps: int = 200,
                           lr: float = 1.0) -> np.ndarray:
    """Train logistic regression for every (fold, reg) pair on the mesh.

    Returns (n_folds, n_grid, d+1) parameters. Full-batch gradient descent
    with a fixed step schedule — every chip runs the identical program;
    row-gradient reductions cross the ``data`` axis via ``psum``.
    """
    n, d = X.shape
    n_folds = masks.shape[0]
    fold_shards = mesh.shape["folds"]
    if n_folds % fold_shards:
        raise ValueError(f"n_folds={n_folds} not divisible by mesh "
                         f"folds axis {fold_shards}")

    Xj = jnp.asarray(X, dtype=jnp.float32)
    yj = jnp.asarray(y, dtype=jnp.float32)
    mj = jnp.asarray(masks, dtype=jnp.float32)
    rj = jnp.asarray(regs, dtype=jnp.float32)

    def fit_one(X_loc, y_loc, mask_loc, reg):
        dd = X_loc.shape[1]
        count = jax.lax.psum(jnp.sum(mask_loc), "data")
        # stable step: 1/L with L >= 0.25 * mean ||x||^2 + reg
        # (trace bound on the logistic Hessian; psum across row shards)
        sq = jax.lax.psum(jnp.sum(X_loc * X_loc) + X_loc.shape[0], "data")
        n_total = jax.lax.psum(jnp.asarray(X_loc.shape[0], jnp.float32),
                               "data")
        step_size = lr / (0.25 * sq / n_total + reg + 1e-6)

        def step(i, params):
            grad_local = _logistic_grad_local(params, X_loc, y_loc, mask_loc)
            grad = jax.lax.psum(grad_local, "data") / jnp.maximum(count, 1.0)
            grad = grad + jnp.concatenate([reg * params[:dd], jnp.zeros(1)])
            return params - step_size * grad

        return jax.lax.fori_loop(0, steps, step, jnp.zeros(dd + 1))

    def shard_body(X_loc, y_loc, masks_loc, regs_all):
        # masks_loc: (folds_per_shard, n_local); vmap folds x grid
        fit_grid = jax.vmap(
            lambda mask: jax.vmap(
                lambda reg: fit_one(X_loc, y_loc, mask, reg))(regs_all))
        return fit_grid(masks_loc)

    fn = shard_map(
        shard_body, mesh=mesh,
        in_specs=(P("data", None), P("data"), P("folds", "data"), P()),
        out_specs=P("folds", None, None),
        check_rep=False)
    return np.asarray(jax.jit(fn)(Xj, yj, mj, rj))


def eval_fold_grid(X: np.ndarray, y: np.ndarray, masks: np.ndarray,
                   params: np.ndarray) -> np.ndarray:
    """Validation error for every (fold, grid) pair: evaluated on each
    fold's HELD-OUT rows (mask == 0). Returns (n_folds, n_grid) mean
    logistic loss — used to pick the winning grid point."""
    d = X.shape[1]
    Xj = jnp.asarray(X, dtype=jnp.float32)
    yj = jnp.asarray(y, dtype=jnp.float32)
    val = 1.0 - jnp.asarray(masks, dtype=jnp.float32)  # held-out indicator

    @jax.jit
    def go(params):
        w = params[..., :d]
        b = params[..., d]
        m = jnp.einsum("fgd,nd->fgn", w, Xj) + b[..., None]
        s = 2.0 * yj - 1.0
        losses = jnp.logaddexp(0.0, -s[None, None, :] * m)
        return (jnp.sum(losses * val[:, None, :], axis=-1)
                / jnp.maximum(jnp.sum(val, axis=-1)[:, None], 1.0))

    return np.asarray(go(jnp.asarray(params, dtype=jnp.float32)))
