"""Fold x grid x data sharded model fitting — the multi-chip CV kernel.

This is the TPU mapping of the reference's model-selection parallelism
(SURVEY §2.9): the per-fold / per-estimator ``Future`` loop of
core/src/main/scala/com/salesforce/op/tuning/OpValidator.scala:270-310 and
OpCrossValidation.scala:100-117 becomes one SPMD program over a
``("models", "data")`` mesh:

- every (fold, grid point) candidate of a linear family becomes one slot
  on the flattened ``models`` axis (task parallelism: each chip trains
  its own chunk of candidates, vmapped into one batched XLA program on
  the MXU),
- the feature matrix is sharded over the ``data`` axis (row parallelism;
  gradient/covariance reductions are ``psum`` over ICI — the role Rabit
  allreduce plays for the reference's XGBoost),
- fold membership is a 0/1 row-weight mask, which makes every candidate
  the same static shape — the XLA-friendly equivalent of materializing k
  train/validation splits.

Crucially the per-candidate fit is the SAME weighted core the sequential
``models/linear.py`` estimators use (``binary_logistic_core`` etc.), so
the mesh path selects the same winner as the one-candidate-at-a-time
path — the property VERDICT r2 called out as missing.
"""
from __future__ import annotations

import functools
import os
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import to_host
from ..utils.jax_setup import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..models.linear import (binary_logistic_core, linear_regression_core,
                             linear_svc_core)

__all__ = ["fold_masks", "fit_linear_fold_grid", "eval_linear_fold_grid",
           "models_mesh", "resolve_search_mesh", "mesh_model_shards",
           "LINEAR_KERNELS"]

#: kind -> weighted fit core (all share the signature
#: (X, y, w, reg, alpha, *, fit_intercept, standardize, max_iter,
#:  use_l1, axis_name) -> (coefficients, intercept))
LINEAR_KERNELS = {
    "logistic": binary_logistic_core,
    "squared": linear_regression_core,
    "svc": linear_svc_core,
}


def fold_masks(n: int, n_folds: int, seed: int = 42,
               y: Optional[np.ndarray] = None) -> np.ndarray:
    """(n_folds, n) float masks: mask[f, i] = 1 if row i is in fold f's
    TRAIN set (i.e. row i's held-out fold != f). Stratified by ``y`` when
    given (reference OpCrossValidation.createTrainValidationSplits:139)."""
    rng = np.random.default_rng(seed)
    assign = np.empty(n, dtype=np.int64)
    if y is None:
        assign[:] = rng.permutation(n) % n_folds
    else:
        for cls in np.unique(y):
            idx = np.nonzero(y == cls)[0]
            assign[idx] = rng.permutation(len(idx)) % n_folds
    return (assign[None, :] != np.arange(n_folds)[:, None]).astype(np.float64)


def models_mesh(devices: Optional[Sequence] = None,
                data_shards: int = 1) -> Mesh:
    """Mesh for candidate-parallel model selection: ``models`` x ``data``.

    ``models`` carries the flattened fold x grid candidate axis (the
    reference's per-estimator Future pool, OpValidator.scala:270-310);
    ``data`` carries row parallelism within each candidate fit."""
    from .mesh import make_mesh
    devices = list(devices if devices is not None else jax.devices())
    nd = len(devices)
    if nd % data_shards:
        raise ValueError(f"data_shards={data_shards} must divide {nd}")
    return make_mesh({"models": nd // data_shards, "data": data_shards},
                     devices)


#: resolved (platform, n_devices, data_shards) -> Mesh — one mesh per
#: process configuration, so every search (and every lru_cache'd kernel
#: keyed by it) shares ONE mesh object instead of churning the kernel
#: caches with per-search instances
_SEARCH_MESH_CACHE: Dict[tuple, Mesh] = {}


def resolve_search_mesh(policy="auto") -> Optional[Mesh]:
    """The mesh the selector shards the fold x grid candidate axis over.

    ``policy`` is what ``_ValidatorBase(mesh=...)`` was given:

    - a ``jax.sharding.Mesh`` — used as-is,
    - ``None`` — force the local single-device path,
    - ``"auto"`` (the default) — consult ``TX_SEARCH_MESH``:
      ``"auto"``/unset shards over every visible device (local path when
      only one is visible), ``"off"``/``"0"``/``"local"`` disables
      sharding, an integer uses that many devices.

    The ``data`` axis defaults to 1 shard (``TX_SEARCH_DATA_SHARDS``
    overrides): row sharding changes gradient-psum reduction order, and
    the search's contract is BITWISE invariance across device counts —
    candidate-axis sharding keeps every candidate's arithmetic identical
    to the single-device program, so a 1-chip and an 8-chip search pick
    the same winner to the last bit (tests/test_sharded_search.py).

    Resolution is lazy and cheap to repeat, but callers should invoke it
    only at search time — touching ``jax.devices()`` initializes the
    backend, which must not happen while a workflow DAG is merely being
    constructed (a dead remote-TPU tunnel can hang indefinitely there).
    """
    if policy is None or isinstance(policy, Mesh):
        return policy
    spec = str(policy).strip().lower()
    if spec in ("auto", ""):
        spec = os.environ.get("TX_SEARCH_MESH", "auto").strip().lower() \
            or "auto"
    if spec in ("off", "none", "local", "0", "1"):
        return None
    devices = jax.devices()
    if spec == "auto":
        n = len(devices)
    else:
        try:
            n = int(spec)
        except ValueError:
            raise ValueError(
                f"TX_SEARCH_MESH / mesh policy must be 'auto', 'off' or "
                f"a device count, got {policy!r}")
        n = min(n, len(devices))
    if n < 2:
        return None
    data = int(os.environ.get("TX_SEARCH_DATA_SHARDS", "1") or "1")
    if data < 1 or n % data:
        data = 1
    key = (devices[0].platform, n, data)
    mesh = _SEARCH_MESH_CACHE.get(key)
    if mesh is None:
        mesh = models_mesh(devices[:n], data_shards=data)
        _SEARCH_MESH_CACHE[key] = mesh
    return mesh


def mesh_model_shards(mesh: Optional[Mesh]) -> int:
    """Shard count of the candidate (``models``) axis — 1 without a
    mesh. The racing scheduler pads each rung's candidate subset to a
    multiple of this so rung programs stay shape-stable across alive
    counts (models/base.pad_cand_idx)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get("models", 1))


def fit_linear_fold_grid(kind: str, X: np.ndarray, y: np.ndarray,
                         masks: np.ndarray, grid: np.ndarray, *,
                         mesh: Optional[Mesh] = None,
                         fit_intercept: bool = True,
                         standardize: bool = True,
                         max_iter: int = 100) -> np.ndarray:
    """Fit every (fold, grid point) candidate of one linear family.

    kind   : "logistic" | "squared" | "svc" (see LINEAR_KERNELS)
    masks  : (F, n) 0/1 train-row masks (1 = row in the fold's train set)
    grid   : (G, 2) columns (reg_param, elastic_net_param)
    mesh   : optional ("models", "data") mesh — without one, the whole
             fold x grid batch still runs as ONE vmapped XLA program on
             the local device.

    Returns (F, G, d+1) parameters, [..., :d] coefficients + [..., d]
    intercept, in the ORIGINAL feature space.
    """
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    masks = np.asarray(masks, dtype=np.float64)
    grid = np.asarray(grid, dtype=np.float64).reshape(-1, 2)
    F, n = masks.shape
    G, d = grid.shape[0], X.shape[1]
    use_l1 = bool(np.any(grid[:, 0] * grid[:, 1] > 0))
    cfg = (kind, use_l1, fit_intercept, standardize, max_iter)

    # flatten candidates fold-major: slot f*G + g = (fold f, grid g)
    regs = np.tile(grid[:, 0], F)
    alphas = np.tile(grid[:, 1], F)
    wmat = np.repeat(masks, G, axis=0)            # (F*G, n)

    if mesh is None:
        fn = _local_kernel(cfg)
        params = fn(jnp.asarray(wmat), jnp.asarray(regs),
                    jnp.asarray(alphas), jnp.asarray(X), jnp.asarray(y))
        return np.asarray(params).reshape(F, G, d + 1)

    m_shards = mesh.shape["models"]
    d_shards = mesh.shape.get("data", 1)
    FG = F * G
    pad_c = (-FG) % m_shards                       # pad candidate axis
    if pad_c:
        wmat = np.concatenate([wmat, np.ones((pad_c, n))], axis=0)
        regs = np.concatenate([regs, np.zeros(pad_c)])
        alphas = np.concatenate([alphas, np.zeros(pad_c)])
    pad_r = (-n) % d_shards                        # pad row axis
    if pad_r:
        X = np.concatenate([X, np.zeros((pad_r, d))], axis=0)
        y = np.concatenate([y, np.zeros(pad_r)])
        wmat = np.concatenate(
            [wmat, np.zeros((wmat.shape[0], pad_r))], axis=1)

    fn = _mesh_kernel(cfg, mesh)
    params = fn(jnp.asarray(wmat), jnp.asarray(regs),
                jnp.asarray(alphas), jnp.asarray(X), jnp.asarray(y))
    return to_host(params)[:FG].reshape(F, G, d + 1)


def eval_linear_fold_grid(kind: str, X: np.ndarray, y: np.ndarray,
                          masks: np.ndarray, grid: np.ndarray,
                          X_val: np.ndarray, y_val: np.ndarray,
                          spec: tuple, *,
                          mesh: Optional[Mesh] = None,
                          fit_intercept: bool = True,
                          standardize: bool = True,
                          max_iter: int = 100) -> np.ndarray:
    """Fit AND evaluate every (fold, grid point) candidate in ONE device
    program, returning only the (F, G) validation-metric matrix.

    This is the device-resident replacement for the reference's
    fit-then-evaluate grid loop (OpValidator.scala:293-295): fitted
    parameters never leave the device — the selector refits only the
    winner afterwards — so a remote-TPU search transfers a few hundred
    bytes instead of every candidate's coefficients.

    X_val : (F, nv, d) per-fold validation rows (equal-sized folds,
            see _ValidatorBase._assignments)
    y_val : (F, nv) validation labels
    spec  : (kind, metric) for evaluators.device_metrics.metric_fn —
            "binary" uses decision margins, "regression" raw values.
    """
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    masks = np.asarray(masks, dtype=np.float64)
    grid = np.asarray(grid, dtype=np.float64).reshape(-1, 2)
    F, n = masks.shape
    G, d = grid.shape[0], X.shape[1]
    use_l1 = bool(np.any(grid[:, 0] * grid[:, 1] > 0))
    cfg = (kind, use_l1, fit_intercept, standardize, max_iter)

    regs = np.tile(grid[:, 0], F)
    alphas = np.tile(grid[:, 1], F)
    wmat = np.repeat(masks, G, axis=0)            # (F*G, n)
    fidx = np.repeat(np.arange(F, dtype=np.int32), G)
    Xv = jnp.asarray(np.asarray(X_val, dtype=np.float64))
    yv = jnp.asarray(np.asarray(y_val, dtype=np.float64))

    if mesh is None:
        fn = _local_eval_kernel(cfg, spec)
        mm = fn(jnp.asarray(wmat), jnp.asarray(regs), jnp.asarray(alphas),
                jnp.asarray(fidx), jnp.asarray(X), jnp.asarray(y), Xv, yv)
        return np.asarray(mm).reshape(F, G)

    m_shards = mesh.shape["models"]
    d_shards = mesh.shape.get("data", 1)
    FG = F * G
    pad_c = (-FG) % m_shards
    if pad_c:
        wmat = np.concatenate([wmat, np.ones((pad_c, n))], axis=0)
        regs = np.concatenate([regs, np.zeros(pad_c)])
        alphas = np.concatenate([alphas, np.zeros(pad_c)])
        fidx = np.concatenate([fidx, np.zeros(pad_c, dtype=np.int32)])
    pad_r = (-n) % d_shards
    if pad_r:
        X = np.concatenate([X, np.zeros((pad_r, d))], axis=0)
        y = np.concatenate([y, np.zeros(pad_r)])
        wmat = np.concatenate(
            [wmat, np.zeros((wmat.shape[0], pad_r))], axis=1)
    fn = _mesh_eval_kernel(cfg, spec, mesh)
    mm = fn(jnp.asarray(wmat), jnp.asarray(regs), jnp.asarray(alphas),
            jnp.asarray(fidx), jnp.asarray(X), jnp.asarray(y), Xv, yv)
    return to_host(mm)[:FG].reshape(F, G)


def _candidate_eval(cfg, spec, params, fi, Xv, yv):
    """Validation metric for one fitted candidate against its fold's
    validation rows, using the host model's exact score semantics:
    logistic ranks by softmax probability of the [-m, m] raw pair, SVC
    by the raw margin (no probability, as in MLlib), regression by the
    predicted values."""
    from ..evaluators.device_metrics import (binary_from_raw_pair,
                                             metric_fn)
    d = Xv.shape[-1]
    m = Xv[fi] @ params[:d] + params[d]
    if spec[0] == "binary":
        if cfg[0] == "svc":
            scores = (m, (m > 0).astype(m.dtype))
        else:
            scores = binary_from_raw_pair(jnp.stack([-m, m], axis=1))
    else:
        scores = m
    return metric_fn(*spec)(yv[fi], scores)


@functools.lru_cache(maxsize=32)
def _local_eval_kernel(cfg, spec):
    def one(w, r, a, fi, X_, y_, Xv, yv):
        params = _candidate_fit(cfg, w, r, a, X_, y_)
        return _candidate_eval(cfg, spec, params, fi, Xv, yv)
    return jax.jit(jax.vmap(
        one, in_axes=(0, 0, 0, 0, None, None, None, None)))


@functools.lru_cache(maxsize=32)
def _mesh_eval_kernel(cfg, spec, mesh):
    data_ax = "data" if "data" in mesh.axis_names else None

    def shard_body(w_loc, r_loc, a_loc, fi_loc, X_loc, y_loc, Xv, yv):
        def one(w, r, a, fi):
            params = _candidate_fit(cfg, w, r, a, X_loc, y_loc,
                                    axis_name=data_ax)
            # params are psum-complete (identical on every data shard),
            # and Xv/yv replicate — the metric is data-axis-invariant
            return _candidate_eval(cfg, spec, params, fi, Xv, yv)
        return jax.vmap(one)(w_loc, r_loc, a_loc, fi_loc)

    return jax.jit(shard_map(
        shard_body, mesh=mesh,
        in_specs=(P("models", data_ax), P("models"), P("models"),
                  P("models"), P(data_ax, None), P(data_ax), P(), P()),
        out_specs=P("models"), check_vma=False))


def _candidate_fit(cfg, w, reg, alpha, X_, y_, axis_name=None):
    kind, use_l1, fit_intercept, standardize, max_iter = cfg
    # solver="fista": static trip count so the mesh and local batched
    # paths are bit-identical and collectives stay in lockstep
    coef, b = LINEAR_KERNELS[kind](
        X_, y_, w, reg, alpha, fit_intercept=fit_intercept,
        standardize=standardize, max_iter=max_iter,
        use_l1=use_l1, axis_name=axis_name, solver="fista")
    return jnp.concatenate([jnp.reshape(coef, (-1,)),
                            jnp.reshape(b, (1,))])


# jitted-kernel caches: one compiled program per (config, shapes) — NOT
# per fit_linear_fold_grid call (a fresh closure per call would defeat
# the jit cache and recompile every fold of a workflow-CV search).
# Bounded (here and in the other family kernels) so long-lived processes
# that recreate meshes per workflow don't pin every mesh's device
# handles forever via cache keys.

@functools.lru_cache(maxsize=32)
def _local_kernel(cfg):
    return jax.jit(jax.vmap(
        lambda w, r, a, X_, y_: _candidate_fit(cfg, w, r, a, X_, y_),
        in_axes=(0, 0, 0, None, None)))


@functools.lru_cache(maxsize=32)
def _mesh_kernel(cfg, mesh):
    # a mesh may be candidate-only (no "data" axis): rows then stay
    # unsharded and the fit cores run without a psum axis
    data_ax = "data" if "data" in mesh.axis_names else None

    def shard_body(w_loc, r_loc, a_loc, X_loc, y_loc):
        # w_loc: (FG_local, n_local) — vmap candidates, psum row shards
        return jax.vmap(
            lambda w, r, a: _candidate_fit(cfg, w, r, a, X_loc, y_loc,
                                           axis_name=data_ax)
        )(w_loc, r_loc, a_loc)

    # check_vma=False because solver state inits (zeros) are axis-
    # invariant; gradient correctness under it comes from the SHARD-LOCAL
    # objective + explicit grad psum in fista_minimize — autodiff never
    # transposes a collective (silently wrong with vma checking off)
    return jax.jit(shard_map(
        shard_body, mesh=mesh,
        in_specs=(P("models", data_ax), P("models"), P("models"),
                  P(data_ax, None), P(data_ax)),
        out_specs=P("models", None), check_vma=False))
