"""Multi-host distribution + wide-feature-matrix sharding.

TPU-native replacement for the reference's distributed substrate
(SURVEY §5.7-5.8): the reference scales out via Spark's driver/executor
RPC + shuffle and caps feature width with the hashing trick
(Transmogrifier.scala:56 MaxNumOfFeatures=16384). Here:

**Multi-host (DCN)** — :func:`initialize_distributed` wraps
``jax.distributed.initialize``: every host runs the same program
(single-controller SPMD), ``jax.devices()`` then spans all hosts'
chips, and any mesh built from it carries collectives over ICI within a
slice and DCN across slices — no Netty RPC, no Kryo, no shuffle. The
CV kernels in parallel/cv.py work unchanged on such a mesh: candidates
shard over all chips, data-axis psums ride the fastest available link
(XLA picks ICI-first reduction topologies).

**Wide vectors (HBM)** — when a transmogrified matrix outgrows one
chip's HBM (wide one-hot/hashed blocks), :func:`wide_matrix_sharding`
shards the FEATURE axis over the mesh: layout (rows replicated or
data-sharded, features split), so per-chip memory is d/n_chips columns.
Linear-model matvecs against a feature-sharded matrix contract the
sharded axis — XLA inserts the psum automatically under jit. Histogram
trees shard cleanly too: each chip histograms its own feature block and
split-gain argmaxes reduce with one small psum (the packed-bin layout
in models/trees.py keeps blocks contiguous).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["initialize_distributed", "wide_matrix_sharding",
           "shard_wide_matrix"]


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> int:
    """Join the multi-host JAX runtime (single-controller SPMD over DCN;
    reference analogue: Spark driver/executor bring-up, OpApp.scala:93).

    On a single host (or when already initialized) this is a no-op.
    Returns the global device count visible after initialization.
    """
    try:
        if coordinator_address is not None:
            try:
                # XLA:CPU refuses cross-process programs unless a CPU
                # collectives backend is selected BEFORE bring-up; on
                # TPU/GPU this knob is inert, so set it unconditionally
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except AttributeError:
                pass
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        else:
            jax.distributed.initialize()
    except (RuntimeError, ValueError):
        # already initialized, or single-process with no coordinator —
        # the local device set is the cluster
        pass
    return len(jax.devices())


def wide_matrix_sharding(mesh: Mesh, features_axis: str = "data",
                         rows_axis: Optional[str] = None) -> NamedSharding:
    """Sharding for an (n, d) feature matrix whose WIDTH is the memory
    problem (SURVEY §5.7): features split over ``features_axis``; rows
    optionally split over ``rows_axis`` (else replicated)."""
    return NamedSharding(mesh, P(rows_axis, features_axis))


def shard_wide_matrix(X: np.ndarray, mesh: Mesh,
                      features_axis: str = "data",
                      rows_axis: Optional[str] = None):
    """Place a host matrix on the mesh feature-sharded, padding the
    feature axis up to a multiple of the shard count (zero columns — a
    no-op for every downstream linear/tree kernel)."""
    import jax.numpy as jnp
    shards = mesh.shape[features_axis]
    n, d = X.shape
    pad = (-d) % shards
    if pad:
        X = np.concatenate([X, np.zeros((n, pad), X.dtype)], axis=1)
    return jax.device_put(
        jnp.asarray(X), wide_matrix_sharding(mesh, features_axis,
                                             rows_axis))
