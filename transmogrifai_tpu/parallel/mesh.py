"""Device-mesh construction and sharding helpers.

TPU-native replacement for the reference's distributed substrate (Spark
driver/executor RPC + shuffle, SURVEY §5.8; the fold x grid task-parallel
``Future`` loop of core/src/main/scala/com/salesforce/op/tuning/
OpValidator.scala:270-310 and XGBoost's Rabit allreduce,
core/build.gradle:27). Here the unit of parallelism is a
``jax.sharding.Mesh`` over TPU chips:

- axis ``"folds"`` — cross-validation folds (each shard fits candidates on
  its own fold; metrics are averaged with ``psum``/``pmean`` over ICI),
- axis ``"data"``  — row (data) parallelism inside one candidate fit
  (gradient/histogram reductions via ``psum``).

On a single host the same code runs against a virtual CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``); on a pod slice,
against real chips over ICI — no code change, XLA inserts the collectives.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "cv_mesh", "n_devices", "replicate", "shard_rows",
           "PartitionSpec", "Mesh", "NamedSharding"]


def n_devices() -> int:
    return len(jax.devices())


def make_mesh(axis_sizes: Dict[str, int],
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh with the given axis sizes from the available
    devices (row-major assignment). The product of sizes must divide the
    device count; leftover devices are unused."""
    devices = list(devices if devices is not None else jax.devices())
    total = math.prod(axis_sizes.values())
    if total > len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}")
    arr = np.asarray(devices[:total]).reshape(*axis_sizes.values())
    return Mesh(arr, tuple(axis_sizes.keys()))


def cv_mesh(n_folds: int, devices: Optional[Sequence] = None) -> Mesh:
    """Mesh for fold-parallel cross-validation: ``folds`` x ``data``.

    Uses all devices: ``folds`` gets min(n_folds, n_devices) shards and the
    remaining device factor becomes row parallelism. Maps the reference's
    per-fold ``Future`` parallelism (OpCrossValidation.scala:100-117) onto
    chips instead of driver threads.
    """
    devices = list(devices if devices is not None else jax.devices())
    nd = len(devices)
    fold_shards = math.gcd(n_folds, nd)
    data_shards = nd // fold_shards
    return make_mesh({"folds": fold_shards, "data": data_shards}, devices)


def replicate(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding over the mesh."""
    return NamedSharding(mesh, PartitionSpec())


def shard_rows(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard a (rows, ...) array's leading dim over one mesh axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def to_host(array) -> "np.ndarray":
    """Materialize a (possibly multi-process global) jax.Array on the
    host. Single-process arrays convert directly; arrays spanning other
    processes' devices gather their remote shards first
    (multihost_utils.process_allgather) — the DCN hop of SURVEY §5.8.
    """
    if getattr(array, "is_fully_addressable", True):
        return np.asarray(array)
    sharding = getattr(array, "sharding", None)
    if sharding is not None and getattr(sharding, "is_fully_replicated",
                                        False):
        # every process already holds the complete value (e.g. a
        # row-sharded fit's out_specs=P() trees): take the local copy
        # directly instead of paying process_allgather's redundant
        # cross-process collective (which handles this case correctly,
        # just not for free)
        return np.asarray(array.addressable_data(0))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(
        array, tiled=True))
