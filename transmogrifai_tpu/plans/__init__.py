"""Compiled execution plans over a feature DAG.

One kernel library, two front doors: the serving :class:`ScoringPlan`
(serving/plan.py) freezes a FITTED DAG into fused, shape-bucketed XLA
programs per request batch, and the train-time :class:`PreparePlan`
(plans/prepare.py) runs the SAME ``transform_arrays`` kernels while the
DAG is being fitted — vectorization → combine → fold staging fused into
jitted segment programs, so the training matrices are born on the
device the sharded search occupies (docs/prepare.md). ``common.py``
holds the machinery both share: power-of-two row bucketing, padding +
validity masks, the zero-row metadata probe, stage classification and
the compile-cache counters.
"""
from .common import (DEFAULT_MAX_BUCKET, DEFAULT_MIN_BUCKET,
                     PlanCompileError, PlanCoverage, bucket_for,
                     compiles, pad_rows, record_compile)
from .placement import PlacementPolicy, placement_report
from .prepare import PreparePlan, prepare_compiles

__all__ = ["PreparePlan", "prepare_compiles", "PlacementPolicy",
           "placement_report", "PlanCoverage", "PlanCompileError",
           "bucket_for", "pad_rows", "compiles", "record_compile",
           "DEFAULT_MIN_BUCKET", "DEFAULT_MAX_BUCKET"]
