"""Plan machinery shared by the serving ScoringPlan and the train-time
PreparePlan.

Factored out of serving/plan.py (PR 2) when the compiled prepare path
landed: both plans freeze (parts of) a feature DAG into jitted XLA
programs and need the same primitives —

- **row bucketing**: pad incoming row counts up to power-of-two
  buckets (``bucket_for``/``pad_rows``) so arbitrary batch/dataset
  sizes hit a handful of cached compilations,
- **zero-row metadata probe**: run stages over ZERO rows through the
  numpy path (milliseconds, no device code) to capture every
  intermediate column's type/width/metadata (``probe_stage``),
- **stage classification**: decide per stage whether it can join the
  device graph (``lowering_reason``) — it must expose an array kernel
  and every input must be device-available or host-encodable,
- **compile counters**: namespaced (plan, bucket) program counters
  (``record_compile``/``compiles``) so benches can assert zero repeat
  compiles per plan family.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import Dataset, FeatureColumn, PredictionColumn
from ..features.feature import Feature
from ..stages.base import Transformer
from ..types import Prediction

__all__ = ["DEFAULT_MIN_BUCKET", "DEFAULT_MAX_BUCKET", "bucket_for",
           "pad_rows", "default_lattice", "normalize_lattice",
           "record_rows", "row_histogram", "row_histograms",
           "PlanCompileError", "PlanStep", "PlanCoverage",
           "empty_raw_dataset", "probe_stage", "lowering_reason",
           "fallback_reason", "record_compile", "compiles", "plan_seq",
           "bucket_section", "bucket_profile"]

from ..tuning.lattice import (bucket_for_lattice, default_lattice,
                              normalize_lattice)
from ..tuning.registry import STATIC_DEFAULTS as _TUNABLES

#: smallest padded batch — single-record requests share one program
#: (the number lives in tuning/registry.py, the single knob registry
#: lint rule TX-T01 enforces)
DEFAULT_MIN_BUCKET = int(_TUNABLES["serving.min_bucket"])
#: largest padded batch — bigger inputs are chunked so the compile
#: count stays bounded at log2(max/min)+1 programs per plan
DEFAULT_MAX_BUCKET = int(_TUNABLES["serving.max_bucket"])

#: distinct compiled programs per namespace ("score" for ScoringPlan
#: buckets, "prepare" for PreparePlan segments)
_COMPILE_KEYS: Dict[str, set] = {}
_PLAN_IDS = itertools.count()


def plan_seq() -> int:
    """Process-unique plan id (shared sequence across plan kinds)."""
    return next(_PLAN_IDS)


def record_compile(namespace: str, key) -> None:
    _COMPILE_KEYS.setdefault(namespace, set()).add(key)


def compiles(namespace: str) -> int:
    """Distinct compiled programs recorded under ``namespace`` so far
    in this process."""
    return len(_COMPILE_KEYS.get(namespace, ()))


def bucket_for(n: int, min_bucket: int = DEFAULT_MIN_BUCKET,
               max_bucket: int = DEFAULT_MAX_BUCKET,
               lattice: Optional[Sequence[int]] = None) -> int:
    """Smallest bucket >= n on the plan's lattice (clamped to the
    bucket range); n beyond the largest bucket is the caller's cue to
    chunk. With no explicit ``lattice`` the default power-of-two
    ladder applies — bitwise the historical doubling behavior."""
    if lattice:
        return bucket_for_lattice(n, lattice)
    b = min_bucket
    while b < n and b < max_bucket:
        b *= 2
    return min(b, max_bucket)


#: process-local occupancy histograms: {namespace: {real_rows: calls}}
#: — the raw material the lattice chooser (tuning/lattice.py) needs;
#: the power-of-two padding in cost records destroys exactly this
#: information, so it is recorded separately at dispatch.
_ROW_HIST: Dict[str, Dict[int, int]] = {}


def record_rows(namespace: str, rows: int) -> None:
    """Record one dispatch's REAL (pre-padding) row count."""
    h = _ROW_HIST.setdefault(namespace, {})
    r = int(rows)
    h[r] = h.get(r, 0) + 1


def row_histogram(namespace: str) -> Dict[int, int]:
    """This process's recorded rows-per-dispatch histogram."""
    return dict(_ROW_HIST.get(namespace, {}))


def row_histograms() -> Dict[str, Dict[int, int]]:
    """All namespaces' histograms (what the ProfileStore persists)."""
    return {ns: dict(h) for ns, h in _ROW_HIST.items() if h}


def pad_rows(arr, bucket: int):
    """Pad the leading (row) axis up to ``bucket`` with zeros. Host
    numpy arrays pad host-side; device (jax) arrays pad on device so a
    device-resident input never round-trips through the host."""
    n = arr.shape[0]
    if n == bucket:
        return np.ascontiguousarray(arr) if isinstance(arr, np.ndarray) \
            else arr
    pad = [(0, bucket - n)] + [(0, 0)] * (arr.ndim - 1)
    if isinstance(arr, np.ndarray):
        return np.pad(np.ascontiguousarray(arr), pad)
    import jax.numpy as jnp
    return jnp.pad(arr, pad)


def _bucket_label(namespace: str, plan_id: int, bucket: int) -> str:
    return f"{namespace}:{plan_id}:b{bucket}"


def bucket_section(namespace: str, plan_id: int, bucket: int):
    """A ``utils/compile_time.section`` labelled for ONE (plan, bucket)
    dispatch — the per-bucket cost ledger the serving coalescer reads
    (``bucket_profile``) to pick its deadline-or-full thresholds from
    recorded data instead of static defaults (the learned-performance-
    model direction in PAPERS.md)."""
    from ..utils.compile_time import section
    return section(_bucket_label(namespace, plan_id, bucket))


def bucket_profile(namespace: str, plan_id: int,
                   rows_by_bucket: Optional[Dict[int, int]] = None
                   ) -> Dict[int, dict]:
    """Per-bucket dispatch cost observed so far for one plan:
    ``{bucket: {calls, wall_seconds, compile_seconds, execute_seconds,
    rows}}``. ``execute_seconds`` is the steady-state estimate
    (wall minus trace/lower/compile events observed inside the span);
    treat 0.0 as "unknown", not "free" (utils/compile_time.py)."""
    from ..utils.compile_time import seconds_by_section
    prefix = f"{namespace}:{plan_id}:b"
    out: Dict[int, dict] = {}
    for label, rec in seconds_by_section(prefix).items():
        try:
            bucket = int(label[len(prefix):])
        except ValueError:              # pragma: no cover - foreign label
            continue
        out[bucket] = {
            "calls": int(rec["calls"]),
            "wall_seconds": rec["seconds"],
            "compile_seconds": rec["compile"],
            "execute_seconds": max(rec["seconds"] - rec["compile"], 0.0),
            "rows": int((rows_by_bucket or {}).get(bucket, 0)),
        }
    return out


class PlanCompileError(RuntimeError):
    """The feature DAG could not be frozen into a plan (e.g. a stage
    crashed during the zero-row metadata probe). Callers fall back to
    the per-stage numpy path."""


@dataclass
class PlanStep:
    """One stage of a plan in execution order."""
    stage: Transformer
    out_name: str
    input_names: Tuple[str, ...]
    phase: str          # "pre" | "device" | "post" | "host" | "fit"
    reason: str = ""    # why a fallback stage did not lower


@dataclass
class PlanCoverage:
    """Which stages lowered into the fused program(s) and which fell
    back to per-stage numpy (with the reason)."""
    lowered: List[str] = field(default_factory=list)
    fallback: List[Tuple[str, str]] = field(default_factory=list)

    @property
    def total(self) -> int:
        return len(self.lowered) + len(self.fallback)

    @property
    def lowered_fraction(self) -> float:
        return len(self.lowered) / self.total if self.total else 1.0

    def to_json(self) -> dict:
        return {"lowered": list(self.lowered),
                "fallback": [list(f) for f in self.fallback],
                "lowered_fraction": round(self.lowered_fraction, 3)}


def empty_raw_dataset(raw_features: Sequence[Feature]) -> Dataset:
    """Zero-row typed dataset for the metadata probe."""
    return Dataset({f.name: FeatureColumn.from_values(f.ftype, [])
                    for f in raw_features})


def probe_stage(stage: Transformer, proto: Dataset,
                out_name: Optional[str] = None) -> Dataset:
    """Run ONE stage over the zero-row proto dataset through the numpy
    path, capturing its output column's type/width/metadata.
    Prediction outputs are stubbed (they carry no metadata).
    ``out_name`` pins the column name to the DAG handle's — a fitted
    model re-deriving its own output name can disagree with the
    estimator's cached feature after a rewiring (raw-feature filter),
    and the DAG name is the one downstream stages were wired to."""
    if out_name is None:
        out_name = stage.get_output().name
    if issubclass(stage.static_output_type(), Prediction):
        return proto.with_column(
            out_name, PredictionColumn.from_arrays(np.zeros(0)))
    cols = [proto[f.name] for f in stage.input_features]
    return proto.with_column(out_name, stage.transform_columns(cols))


def fallback_reason(what: str, e: Exception) -> str:
    """One-line fallback reason for coverage records (the TX-R01
    contract: a swallowed hot-path exception must surface as a
    recorded degradation, never vanish)."""
    return f"{what}: {type(e).__name__}: {e}"


def lowering_reason(stage: Transformer, input_names: Sequence[str],
                    producer: Dict[str, str],
                    proto_cols: Callable[[str], FeatureColumn],
                    demoted: Optional[Dict[str, str]] = None) -> str:
    """Empty string when ``stage`` can join the device graph; otherwise
    the human-readable reason it must run through its host
    ``transform_columns`` fallback. A stage lowers when it has an array
    kernel AND every input is either produced on device already or
    host-materialized and encodable; an input produced by a host
    fallback DOWNSTREAM of the device graph ("post") blocks lowering
    for single-program plans (the device program runs once).

    This classification is a PREDICTION about what will lower; the
    plan auditor verifies it against the actually-lowered IR and
    emits a TX-P05 WARNING on disagreement (analysis/rules.py
    ``verify_classification`` — e.g. a stage that grew
    ``transform_arrays`` after being classified host, or a 'device'
    kernel that no longer traces)."""
    if demoted and stage.uid in demoted:
        return demoted[stage.uid]
    if not stage.supports_arrays():
        return "no array kernel (transform_arrays)"
    for i, name in enumerate(input_names):
        src = producer.get(name, "host")
        if src == "post":
            return (f"input {name!r} is produced by a host fallback "
                    f"downstream of the device graph")
        if src == "device":
            if stage.encodes_input(i):
                return (f"input {name!r} needs host encoding but is "
                        f"produced on device")
            continue
        # host-materialized input: probe the encoder on the zero-row
        # proto column
        try:
            stage.encode_input_column(i, proto_cols(name))
        except Exception as e:
            return fallback_reason(f"input {name!r} not encodable", e)
    return ""
