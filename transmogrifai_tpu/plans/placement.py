"""Host-vs-device placement for fit-time statistics stages.

The compiled prepare plan (plans/prepare.py) can fit some estimators
directly from device-resident arrays (``Estimator.fit_device`` —
SanityChecker, the scalers) instead of materializing their inputs back
to host columns. Whether that is a WIN depends on the workload: on a
cold CPU process the device fit pays an XLA trace+compile bill a tiny
dataset never amortizes, while on wide/tall data (or any warm process)
the host materialization is the cost. Rather than a hardcoded
allowlist, placement is driven by the recorded compile/execute split
(utils/compile_time.py, the same accumulator behind
``stage_profile_top`` — "A Learned Performance Model for TPUs" is the
grown-up version of this record-and-compare seed):

- every fit the plan dispatches is measured under a section label;
  wall seconds minus monitoring compile seconds is the steady-state
  execute cost,
- the decision for stage class C compares the recorded steady-state
  device cost against the recorded host cost at a similar row count,
  preferring the device path on a tie (it keeps the matrix resident),
- with no record yet, the device path is tried first (optimistic) —
  one measurement converts the guess into data for the rest of the
  process.

``TX_PREPARE_FIT=device|host`` overrides the policy wholesale (the
escape hatches the identity tests pin); ``auto`` (default) applies the
recorded-cost rule above.

Cross-run memory (docs/autotuning.md): ``auto`` mode additionally
SEEDS the comparison from the profile store's persisted
``placement:<Class>:<where>`` records at construction — a fresh
process whose predecessor measured that (say) StandardScaler fits
cheaper on host places correctly on its FIRST fit instead of paying
the optimistic device compile again. Seeds live in a separate map so
:func:`placement_report` (and hence ``persist_process_profiles``)
only ever reports/persists what THIS process measured — cross-run
records never double-count. An empty store or ``TX_TUNE=off`` leaves
the seed map empty: decisions are bitwise the optimistic-device
defaults.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["PlacementPolicy", "placement_report", "reset_placement"]

_LOCK = threading.Lock()
#: (stage class name, "host"|"device") -> accumulated fit cost record
_RECORDS: Dict[Tuple[str, str], Dict[str, float]] = {}
#: cross-run seeds from the profile store (tuning/policy.py) — read as
#: a fallback by decide_fit, NEVER persisted back
_SEEDS: Dict[Tuple[str, str], Dict[str, float]] = {}
_SEED_STATE = {"done": False}


def _ensure_seeded(policy=None) -> None:
    """Load the store's placement records into the seed map, once per
    process (reset_placement re-arms it for tests)."""
    with _LOCK:
        if _SEED_STATE["done"]:
            return
        _SEED_STATE["done"] = True
    try:
        if policy is None:
            from ..tuning.policy import TuningPolicy
            policy = TuningPolicy()
        seeds, _decision = policy.placement_seed()
    except Exception:  # pragma: no cover - store unreadable
        seeds = {}
    with _LOCK:
        for key, rec in seeds.items():
            _SEEDS.setdefault(key, dict(rec))


def _record(cls_name: str, where: str, seconds: float,
            compile_seconds: float, n_rows: int) -> None:
    with _LOCK:
        rec = _RECORDS.setdefault((cls_name, where), {
            "seconds": 0.0, "compile": 0.0, "calls": 0, "rows": 0})
        rec["seconds"] += seconds
        rec["compile"] += min(compile_seconds, seconds)
        rec["calls"] += 1
        rec["rows"] += int(n_rows)


def _steady_state(rec: Optional[Dict[str, float]]) -> Optional[float]:
    """Mean steady-state (execute) seconds per fit, or None without a
    record. Compile seconds are excluded — they are first-call cost a
    warm process (and every repeat train) never pays again."""
    if rec is None or not rec["calls"]:
        return None
    return max(0.0, rec["seconds"] - rec["compile"]) / rec["calls"]


def placement_report() -> List[dict]:
    """Recorded per-(stage class, placement) fit costs, for bench
    output and ``docs/prepare.md`` debugging."""
    with _LOCK:
        return [
            {"stage": cls, "placement": where,
             "seconds": round(rec["seconds"], 4),
             "compileSeconds": round(rec["compile"], 4),
             "executeSeconds": round(
                 max(0.0, rec["seconds"] - rec["compile"]), 4),
             "calls": int(rec["calls"]), "rows": int(rec["rows"])}
            for (cls, where), rec in sorted(_RECORDS.items())]


def reset_placement() -> None:
    with _LOCK:
        _RECORDS.clear()
        _SEEDS.clear()
        _SEED_STATE["done"] = False


class PlacementPolicy:
    """Decide where one estimator's fit statistics run, and record the
    measured outcome so the next decision is data-driven."""

    def __init__(self, mode: Optional[str] = None):
        self.mode = mode or os.environ.get("TX_PREPARE_FIT", "auto")
        if self.mode not in ("auto", "device", "host"):
            raise ValueError(
                f"TX_PREPARE_FIT must be auto, device or host, "
                f"got {self.mode!r}")
        from ..tuning.registry import STATIC_DEFAULTS
        self.margin = float(STATIC_DEFAULTS["prepare.placement_margin"])
        if self.mode == "auto":
            try:
                from ..tuning.policy import TuningPolicy
                policy = TuningPolicy()
                self.margin = float(policy.placement_margin().chosen)
                _ensure_seeded(policy)
            except Exception:  # pragma: no cover - store unreadable
                pass

    def decide_fit(self, stage, n_rows: int) -> Tuple[str, str]:
        """("device"|"host", reason). "device" is only returned for
        stages exposing a ``fit_device`` kernel."""
        supports = getattr(stage, "supports_device_fit", lambda: False)()
        if not supports:
            return "host", "no fit_device kernel"
        if self.mode == "device":
            return "device", "TX_PREPARE_FIT=device"
        if self.mode == "host":
            return "host", "TX_PREPARE_FIT=host"
        cls = type(stage).__name__
        with _LOCK:
            dev = _RECORDS.get((cls, "device"))
            host = _RECORDS.get((cls, "host"))
            seeded = dev is None and host is None
            if seeded:
                # no process-local measurement yet: fall back to the
                # cross-run seeds (empty unless the store has history)
                dev = _SEEDS.get((cls, "device"))
                host = _SEEDS.get((cls, "host"))
        dev_s, host_s = _steady_state(dev), _steady_state(host)
        via = " (cross-run seed)" if seeded and (
            dev_s is not None or host_s is not None) else ""
        if dev_s is None:
            if host_s is not None and seeded:
                return "host", (f"cross-run seed: only a host record "
                                f"({host_s:.4f}s) — keep measuring it")
            return "device", "no record yet; measuring the device path"
        if host_s is None or dev_s <= self.margin * host_s:
            return "device", (f"recorded steady-state device fit "
                              f"{dev_s:.4f}s <= host "
                              f"{host_s if host_s is not None else '?'}"
                              f"{via}")
        return "host", (f"recorded steady-state device fit {dev_s:.4f}s "
                        f"> host {host_s:.4f}s{via}")

    @staticmethod
    def record_fit(stage, where: str, seconds: float,
                   compile_seconds: float, n_rows: int) -> None:
        _record(type(stage).__name__, where, seconds, compile_seconds,
                n_rows)
