"""PreparePlan: compiled train-time feature engineering.

``Workflow.train()`` historically materialized every feature through
host-side ``transform_columns`` loops before the device ever saw a
matrix — two parallel kernel code paths for the same math, because the
serving ScoringPlan (PR 2) already lowers every transmogrify family
through ``Transformer.transform_arrays``. This module deletes the fork:
at train time the SAME array kernels execute the feature DAG on device,
with the per-family chain vectorization → ``VectorsCombiner`` →
fold-matrix staging fused into jitted segment programs ("Operator
Fusion in XLA": hand the compiler the program, not one stage at a
time). The training matrices are born on the device the sharded search
already occupies — ``ModelSelector`` receives a device-resident feature
matrix and the validator stages its fold arrays with device gathers, no
host round-trip in between (docs/prepare.md).

Execution model — a :class:`ScoringPlan` that interleaves fits:

1. Stages are walked in topo order. Transformers (and fitted models)
   whose kernels lower join the CURRENT SEGMENT — a maximal run of
   device steps that will trace into one jitted program.
2. An estimator forces the segment to FLUSH first when its fit needs
   device-produced values (vectorizers fitting on raw host columns
   don't): the fused program executes over power-of-two row buckets
   (padding + validity mask, chunking past the max bucket), outputs
   stay on device AND are wrapped back into jax-backed columns.
3. The fit itself is placed by :class:`~.placement.PlacementPolicy`
   (host vs a ``fit_device`` kernel, driven by the recorded
   compile/execute split) — a host fit of a device-resident input is a
   RECORDED fallback, never a silent one.
4. Stage kernels that fail the abstract trace are demoted to their
   host ``transform_columns`` path with the reason in ``coverage``
   (the ScoringPlan graceful-degradation contract).

Repeat trains reuse compiled segments: a segment's jitted callable is
cached process-wide under a fingerprint of every step's fitted state,
so retraining on identical data re-executes the cached XLA programs
with ZERO new traces or compiles (``prepare_compiles()`` stays flat —
asserted in tests/test_prepare_plan.py).

Per-stage telemetry inside a fused program cannot come from wall-clock
alone; each stage's kernel is traced under a ``prepare:stage:<uid>``
compile-time section (utils/compile_time.py) and segment dispatch under
``prepare:seg<k>``, and the listener receives per-stage compile/execute
seconds apportioned by trace share — ``stage_profile_top`` keeps its
per-stage rows (the telemetry-autotuning data source).
"""
from __future__ import annotations

import hashlib
import logging
import pickle
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import Dataset, FeatureColumn
from ..features.feature import Feature, topo_layers
from ..features.generator import FeatureGeneratorStage
from ..runtime import telemetry as _telemetry
from ..runtime.faults import maybe_inject
from ..runtime.retry import RetryPolicy
from ..stages.base import Estimator, PipelineStage, Transformer
from ..types import Prediction
from ..utils import compile_time
from .common import (DEFAULT_MIN_BUCKET, PlanCompileError, PlanCoverage,
                     PlanStep, bucket_for, compiles, empty_raw_dataset,
                     fallback_reason, lowering_reason, normalize_lattice,
                     pad_rows, plan_seq, probe_stage, record_compile,
                     record_rows)
from .placement import PlacementPolicy

_log = logging.getLogger(__name__)

__all__ = ["PreparePlan", "prepare_compiles",
           "DEFAULT_PREPARE_MAX_BUCKET"]

#: train datasets are one batch, not a request stream — a larger max
#: bucket keeps typical training sizes in ONE fused dispatch while the
#: power-of-two ladder still bounds distinct programs
DEFAULT_PREPARE_MAX_BUCKET = 65536


def prepare_compiles() -> int:
    """Distinct compiled prepare segment programs so far in this
    process (the flat-across-repeat-trains diagnostic the bench and
    tests/test_prepare_plan.py assert on)."""
    return compiles("prepare")


#: the most recent PreparePlan executed in this process — the plan
#: auditor's handle to the fused segment programs a train() just built
#: (workflow.train constructs the plan internally; audit_prepare_plan
#: re-lowers its segments from the recorded audit handles)
_LAST_PLAN: Optional["PreparePlan"] = None


def last_prepare_plan() -> Optional["PreparePlan"]:
    """The most recently executed PreparePlan of this process (None
    before any plan-mode train). Audit-only introspection — the plan's
    ``audit_handles`` carry each fused segment's jitted fn, input
    avals, dispatched buckets and stage roster (analysis/audit.py)."""
    return _LAST_PLAN


# ---------------------------------------------------------------------------
# cross-train segment cache
# ---------------------------------------------------------------------------

#: (segment signature) -> (jitted fn, trace_seconds by uid). Bounded
#: LRU: a long-lived retraining process keeps its hot segments warm.
_SEGMENT_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_SEGMENT_CACHE_MAX = 64


def _state_fingerprint(stage: PipelineStage) -> Optional[str]:
    """Deterministic digest of a stage's fitted state (every public
    attribute except DAG wiring and identity). Retraining a workflow on
    identical data produces models with equal state -> equal
    fingerprints -> the cached jitted segment is reused with zero
    retrace. Over-inclusion is safe by construction (a spurious
    difference only costs a recompile, never stale reuse); unpicklable
    state (lambdas) returns None: that stage's segments never
    cross-train cache — correct, just cold."""
    try:
        payload = {k: v for k, v in sorted(stage.__dict__.items())
                   if k not in ("input_features", "_output_feature",
                                "fitted_model", "uid", "operation_name")}
        blob = pickle.dumps(payload, protocol=4)
    except Exception:
        return None
    return hashlib.sha1(blob).hexdigest()


def _sig_digest(sig) -> Optional[str]:
    """Stable digest of a segment signature — the cross-PROCESS reuse
    key the AOT artifact store files prepare executables under
    (artifacts/export.py). The signature is already deterministic
    (state fingerprints + positions + bucket range), so its repr is."""
    if sig is None:
        return None
    return hashlib.sha1(repr(sig).encode()).hexdigest()


def _prepare_aot_executable(sig_digest: Optional[str], bucket: int):
    """The deserialized AOT executable for one (segment, bucket), or
    None — a thin guard over artifacts/loader.prepare_executable so a
    broken artifacts layer can never take training down."""
    if sig_digest is None:
        return None
    try:
        from ..artifacts.loader import prepare_executable
        return prepare_executable(sig_digest, bucket)
    except Exception:           # registry is an optimization, not truth
        return None


def _segment_cache_get(sig):
    hit = _SEGMENT_CACHE.get(sig)
    if hit is not None:
        _SEGMENT_CACHE.move_to_end(sig)
    return hit


def _segment_cache_put(sig, value) -> None:
    _SEGMENT_CACHE[sig] = value
    _SEGMENT_CACHE.move_to_end(sig)
    while len(_SEGMENT_CACHE) > _SEGMENT_CACHE_MAX:
        _SEGMENT_CACHE.popitem(last=False)


def _is_jax_array(x) -> bool:
    try:
        import jax
        return isinstance(x, jax.Array)
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        return False


def _fit_encode(col: FeatureColumn):
    """Array view of a host column for a device fit: numeric/vector
    columns encode identically to the transform boundary; device-
    resident arrays pass through. Object columns have no array form —
    the caller falls back to the host fit with a recorded reason."""
    if _is_jax_array(col.data):
        return col.data
    if col.kind in ("numeric", "vector"):
        return np.asarray(col.data, dtype=np.float64)
    raise NotImplementedError(
        f"{col.ftype.__name__} column has no array encoding for a "
        f"device fit")


class PreparePlan:
    """Execute (fit + transform) a feature DAG with the serving kernel
    library at train time. One instance per ``train()`` call; compiled
    segments are shared process-wide (see module docstring).

    >>> plan = PreparePlan(result_features, listener=listener)
    >>> train_ds, fitted = plan.execute(raw_ds)
    """

    def __init__(self, result_features: Sequence[Feature],
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 max_bucket: int = DEFAULT_PREPARE_MAX_BUCKET,
                 listener=None, placement: Optional[PlacementPolicy] = None,
                 lattice: Optional[Sequence[int]] = None):
        self.result_features = tuple(result_features)
        #: explicit bucket lattice — None keeps the default
        #: power-of-two ladder bitwise; a lattice overrides the range
        #: args (its first/last rungs become min/max), and joins the
        #: cross-train segment signature so cached programs never mix
        #: lattices
        self.lattice: Optional[Tuple[int, ...]] = \
            normalize_lattice(lattice) if lattice else None
        if self.lattice:
            self.min_bucket = self.lattice[0]
            self.max_bucket = self.lattice[-1]
        else:
            self.min_bucket = int(min_bucket)
            self.max_bucket = int(max_bucket)
        if self.min_bucket < 1 or self.max_bucket < self.min_bucket:
            raise ValueError(
                f"bad bucket range [{min_bucket}, {max_bucket}]")
        self.listener = listener
        self.placement = placement or PlacementPolicy()
        self.coverage = PlanCoverage()
        #: [(stage label, "host"|"device", reason)] fit placements
        self.fit_placements: List[Tuple[str, str, str]] = []
        #: seconds spent executing fused device segments (+ encoders)
        self.device_transform_seconds = 0.0
        #: seconds spent in host transform_columns fallbacks
        self.host_transform_seconds = 0.0
        self.segments_run = 0
        self._plan_id = plan_seq()
        self._retry = RetryPolicy.from_env()
        #: one record per executed segment — the auditor's re-lowering
        #: handles: {label, fn (jitted), in_avals [(trailing shape,
        #: dtype)], buckets dispatched, stages}. Holding the jitted fn
        #: keeps re-lowering exact (same traced program) and costs
        #: nothing: the fn is alive in _SEGMENT_CACHE anyway.
        self.audit_handles: List[Dict[str, Any]] = []

    # -- public ------------------------------------------------------------
    def execute(self, ds: Dataset,
                prefitted: Optional[Dict[str, PipelineStage]] = None
                ) -> Tuple[Dataset, Dict[str, PipelineStage]]:
        """Fit every estimator and materialize every stage output over
        ``ds`` (the ``_fit_and_transform_layers(fit=True)`` contract:
        returns the fully transformed Dataset — device-lowered columns
        are jax-backed, host fallbacks numpy — and the fitted models by
        estimator uid). ``prefitted`` supplies models already fitted on
        THIS dataset (the workflow-CV pre-pass)."""
        global _LAST_PLAN
        _LAST_PLAN = self
        compile_time.install()
        import jax  # noqa: F401  (device path; deferred like the plans)
        stages = [s for layer in topo_layers(list(self.result_features))
                  for s in layer
                  if not isinstance(s, FeatureGeneratorStage)]
        raw_names = [f.name for f in _raw_features(self.result_features)]
        self._proto = empty_raw_dataset(
            _raw_features(self.result_features))
        self._producer: Dict[str, str] = {n: "host" for n in raw_names}
        self._device_env: Dict[str, Any] = {}
        self._aval_env: Dict[str, Any] = {}
        self._pending: List[PlanStep] = []
        fitted: Dict[str, PipelineStage] = {}

        for stage in stages:
            if isinstance(stage, Estimator):
                model = (prefitted or {}).get(stage.uid)
                if model is None:
                    ds, model = self._fit_stage(stage, ds)
                fitted[stage.uid] = model
                out_name = stage.get_output().name
                ds = self._add_transform(model, out_name, ds,
                                         n_rows=ds.n_rows)
            elif isinstance(stage, Transformer):
                ds = self._add_transform(stage, stage.get_output().name,
                                         ds, n_rows=ds.n_rows)
            else:
                raise TypeError(f"Cannot execute stage {stage!r}")
        ds = self._flush(ds)
        return ds, fitted

    def describe(self) -> dict:
        """Plan summary for logs/benchmarks."""
        return {
            "coverage": self.coverage.to_json(),
            "fit_placements": [list(p) for p in self.fit_placements],
            "segments_run": self.segments_run,
            "device_transform_seconds":
                round(self.device_transform_seconds, 4),
            "host_transform_seconds":
                round(self.host_transform_seconds, 4),
            "lattice": list(self.lattice) if self.lattice else None,
        }

    # -- transform classification ------------------------------------------
    def _add_transform(self, stage: Transformer, out_name: str,
                       ds: Dataset, n_rows: int) -> Dataset:
        """Classify one (fitted) stage's transform and either append it
        to the pending device segment or run its host fallback now."""
        in_names = tuple(f.name for f in stage.input_features)
        is_prediction = issubclass(stage.static_output_type(), Prediction)
        if is_prediction:
            # the train-time prediction column feeds boxed evaluation /
            # insights host-side anyway; raw-margin lowering buys
            # nothing here (serving lowers it — serving/plan.py)
            reason = "prediction output assembles host-side at train time"
        else:
            reason = lowering_reason(
                stage, in_names, self._producer,
                lambda n: self._proto[n])
        if not reason:
            reason = self._verify_kernel(stage, in_names, out_name)
        # proto update AFTER classification: lowering_reason probes
        # encoders on the zero-row proto of the stage's INPUTS. A stage
        # that crashes the probe cannot be wrapped from device output
        # metadata, so it is demoted to the host path (its real output,
        # sliced to zero rows, becomes the proto instead).
        probed = True
        try:
            self._proto = probe_stage(stage, self._proto, out_name)
        except Exception as e:
            probed = False
            if not reason:
                self._note_demotion(stage, "zero-row probe failed", e)
                reason = fallback_reason("zero-row probe failed", e)
        label = f"{type(stage).__name__}({out_name})"
        if not reason:
            self._pending.append(
                PlanStep(stage, out_name, in_names, "device"))
            self._producer[out_name] = "device"
            self.coverage.lowered.append(label)
            return ds
        # host fallback: needs the VALUES of its inputs materialized
        ds = self._flush(ds)
        self.coverage.fallback.append((label, reason))
        self._producer[out_name] = "host"
        t0 = time.perf_counter()
        c0 = compile_time.compile_seconds()
        col = stage.transform_columns([ds[n] for n in in_names])
        ds = ds.with_column(out_name, col)
        if not probed:
            self._proto = self._proto.with_column(
                out_name, col.take(np.zeros(0, dtype=np.int64)))
        wall = time.perf_counter() - t0
        self.host_transform_seconds += wall
        if self.listener is not None:
            self.listener.on_stage_completed(
                stage, "transform", wall, n_rows,
                compile_seconds=compile_time.compile_seconds() - c0)
        return ds

    def _input_key(self, step: PlanStep, i: int, name: str) -> str:
        if self._producer.get(name) == "device":
            return name
        if step.stage.encodes_input(i):
            return f"enc:{step.stage.uid}:{i}"
        return name

    def _verify_kernel(self, stage: Transformer,
                       in_names: Tuple[str, ...], out_name: str) -> str:
        """Abstractly trace ONE stage's kernel (``jax.eval_shape`` — no
        device code) against its input avals; a failing kernel is
        demoted to the host path with the recorded reason instead of
        failing the plan. Deterministic test hook: an injected
        ``prepare:<Stage>:compile`` fault demotes exactly like a real
        trace failure."""
        import jax
        try:
            maybe_inject("prepare", type(stage).__name__, "compile")
        except Exception as e:
            self._note_demotion(stage, "injected compile fault", e)
            return fallback_reason("injected compile fault", e)
        avals = []
        try:
            for i, name in enumerate(in_names):
                if self._producer.get(name) == "device":
                    avals.append(self._aval_env[name])
                else:
                    arr = np.asarray(stage.encode_input_column(
                        i, self._proto[name]))
                    avals.append(jax.ShapeDtypeStruct(
                        (self.min_bucket,) + arr.shape[1:], arr.dtype))
            out = jax.eval_shape(
                lambda *a, s=stage: s.transform_arrays(list(a)), *avals)
        except Exception as e:
            self._note_demotion(stage, "kernel failed abstract trace", e)
            return fallback_reason("kernel failed abstract trace", e)
        self._aval_env[out_name] = out
        return ""

    def _note_demotion(self, stage, what: str, e: Exception) -> None:
        _telemetry.count("prepare_fallbacks")
        _telemetry.event("prepare_fallback", stage=type(stage).__name__,
                         reason=f"{what}: {type(e).__name__}: {e}")
        _log.warning(
            "prepare plan: stage %s failed to lower (%s: %s); falling "
            "back to its host transform_columns path",
            type(stage).__name__, what, e)

    # -- estimator fits ----------------------------------------------------
    def _fit_stage(self, stage: Estimator, ds: Dataset
                   ) -> Tuple[Dataset, PipelineStage]:
        in_names = [f.name for f in stage.input_features]
        srcs = [self._producer.get(n, "host") for n in in_names]
        n_rows = ds.n_rows
        if all(s == "host" for s in srcs):
            # vocab builders fit on raw/host-materialized columns — the
            # data is host-resident either way, nothing to place
            return self._host_fit(stage, ds, n_rows,
                                  reason="inputs host-resident")
        ds = self._flush(ds)    # fit needs VALUES of device outputs
        where, why = self.placement.decide_fit(stage, n_rows)
        if where == "device":
            try:
                arrays = [
                    self._device_env[n]
                    if self._producer.get(n) == "device"
                    else _fit_encode(ds[n])
                    for n in in_names]
                protos = [self._proto[n] for n in in_names]
                return ds, self._device_fit(stage, arrays, protos,
                                            n_rows, why)
            except NotImplementedError as e:
                why = fallback_reason("fit_device rejected the inputs", e)
                _telemetry.count("prepare_fit_fallbacks")
        else:
            _telemetry.count("prepare_fit_fallbacks")
        return self._host_fit(stage, ds, n_rows, reason=why,
                              pulled_device=True)

    def _host_fit(self, stage: Estimator, ds: Dataset, n_rows: int,
                  reason: str, pulled_device: bool = False
                  ) -> Tuple[Dataset, PipelineStage]:
        label = f"{type(stage).__name__}({stage.uid})"
        if pulled_device:
            # a host fit of device-resident inputs is a recorded
            # degradation (TX-R01 spirit), not a silent np.asarray
            reason = f"host fit over device columns: {reason}"
        self.fit_placements.append((label, "host", reason))
        t0 = time.perf_counter()
        c0 = compile_time.compile_seconds()
        with compile_time.section(f"prepare:fit:{type(stage).__name__}"):
            model = stage.fit(ds)
        wall = time.perf_counter() - t0
        cdelta = compile_time.compile_seconds() - c0
        PlacementPolicy.record_fit(stage, "host", wall, cdelta, n_rows)
        if self.listener is not None:
            self.listener.on_stage_completed(stage, "fit", wall, n_rows,
                                             compile_seconds=cdelta)
        return ds, model

    def _device_fit(self, stage: Estimator, arrays, protos, n_rows: int,
                    why: str) -> PipelineStage:
        label = f"{type(stage).__name__}({stage.uid})"
        self.fit_placements.append((label, "device", why))
        t0 = time.perf_counter()
        c0 = compile_time.compile_seconds()
        with compile_time.section(f"prepare:fit:{type(stage).__name__}"):
            model = stage.fit_from_arrays(arrays, protos)
        wall = time.perf_counter() - t0
        cdelta = compile_time.compile_seconds() - c0
        PlacementPolicy.record_fit(stage, "device", wall, cdelta, n_rows)
        if self.listener is not None:
            self.listener.on_stage_completed(stage, "fit", wall, n_rows,
                                             compile_seconds=cdelta)
        return model

    # -- segment execution -------------------------------------------------
    def _flush(self, ds: Dataset) -> Dataset:
        """Execute the pending device segment as ONE jitted program
        over padded row buckets; outputs land in the device env AND as
        jax-backed columns of the returned Dataset."""
        if not self._pending:
            return ds
        steps, self._pending = self._pending, []
        seg_idx = self.segments_run
        self.segments_run += 1
        n = ds.n_rows

        # device inputs: device-env arrays pass through by name; host
        # columns encode once per distinct (encoder, column) key
        in_keys: List[str] = []
        sources: List[Tuple[str, Any]] = []   # (key, array)
        seen = set()
        produced = {s.out_name for s in steps}
        for step in steps:
            for i, name in enumerate(step.input_names):
                key = self._input_key(step, i, name)
                if key in seen or key in produced:
                    continue
                seen.add(key)
                if self._producer.get(name) == "device":
                    arr = self._device_env[name]
                else:
                    arr = stage_encode(step.stage, i, ds[name])
                in_keys.append(key)
                sources.append((key, arr))

        # canonical POSITIONAL form: inputs 0..K-1 in discovery order,
        # then one slot per step output. Stage uids / feature names
        # stay out of the traced function and the cache signature —
        # retraining a workflow on identical data reuses the compiled
        # programs (fitted state that embeds output names, e.g. vector
        # metadata, still fingerprints per workflow instance).
        pos_of = {key: i for i, key in enumerate(in_keys)}
        k_in = len(in_keys)
        step_pos = []
        for j, s in enumerate(steps):
            in_pos = tuple(
                pos_of[self._input_key(s, i, nm)]
                for i, nm in enumerate(s.input_names))
            step_pos.append((s.stage, in_pos))
            pos_of[s.out_name] = k_in + j
        step_pos = tuple(step_pos)
        sig = self._segment_signature(step_pos, k_in)
        sig_digest = _sig_digest(sig)
        seg_label = f"prepare:seg{seg_idx}"
        t0 = time.perf_counter()
        c0 = compile_time.compile_seconds()
        with compile_time.section(seg_label):
            cached = None if sig is None else _segment_cache_get(sig)
            if cached is None:
                fn, trace_seconds = _build_segment_fn(step_pos, k_in)
                if sig is not None:
                    _segment_cache_put(sig, (fn, trace_seconds))
            else:
                fn, trace_seconds = cached

            chunks: List[List[Any]] = [[] for _ in steps]
            seg_buckets: List[int] = []
            for start in range(0, max(n, 1), self.max_bucket):
                stop = min(start + self.max_bucket, n)
                rows = stop - start
                bucket = bucket_for(rows, self.min_bucket,
                                    self.max_bucket,
                                    lattice=self.lattice)
                record_rows("prepare", rows)
                if bucket not in seg_buckets:
                    seg_buckets.append(bucket)
                inputs = tuple(pad_rows(arr[start:stop], bucket)
                               for _, arr in sources)
                mask = np.zeros(bucket, dtype=np.float64)
                mask[:rows] = 1.0
                # a seeded AOT executable (artifacts/loader.py — the
                # lifecycle retrain path seeds from the live model's
                # artifact store) dispatches without compiling; the
                # prepare-compile diagnostic stays flat
                aot_fn = _prepare_aot_executable(sig_digest, bucket)
                if aot_fn is not None:
                    _telemetry.count("prepare_aot_dispatches")
                else:
                    record_compile(
                        "prepare",
                        (sig if sig is not None else self._plan_id,
                         bucket))
                outs = self._dispatch(aot_fn or fn, inputs, mask)
                for i, o in enumerate(outs):
                    chunks[i].append(o[:rows])
                if n == 0:
                    break
        wall = time.perf_counter() - t0
        cdelta = compile_time.compile_seconds() - c0
        self.device_transform_seconds += wall
        # audit handle: enough to RE-LOWER this exact segment program
        # per dispatched bucket without re-executing anything
        # (analysis/audit.audit_prepare_plan). Shapes/dtypes read off
        # the source arrays' metadata — no materialization.
        self.audit_handles.append({
            "label": f"seg{seg_idx}",
            "fn": fn,
            "sig_digest": sig_digest,
            "in_avals": [(tuple(arr.shape[1:]), arr.dtype)
                         for _, arr in sources],
            "buckets": sorted(seg_buckets),
            "stages": [type(s.stage).__name__ for s in steps],
            "stage_modules": sorted({type(s.stage).__module__
                                     for s in steps}),
        })

        import jax.numpy as jnp
        for step, outs in zip(steps, chunks):
            arr = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
            self._device_env[step.out_name] = arr
            ds = ds.with_column(step.out_name,
                                self._wrap_output(step.out_name, arr))
        self._report_segment(steps, trace_seconds, wall, cdelta, n)
        return ds

    def _dispatch(self, fn, inputs, mask):
        """One fused-program dispatch behind the runtime retry policy
        (transient backend errors back off and retry; persistent ones
        propagate — train has the selector-level quarantine above)."""
        def attempt():
            maybe_inject("prepare", "device", "dispatch")
            return fn(inputs, mask)

        return self._retry.call(attempt, description="prepare-dispatch")

    def _segment_signature(self, step_pos, k_in: int):
        parts = []
        for stage, in_pos in step_pos:
            fp = _state_fingerprint(stage)
            if fp is None:
                return None     # unfingerprintable: no cross-train reuse
            parts.append((type(stage).__name__, fp, in_pos))
        return (tuple(parts), k_in, self.min_bucket, self.max_bucket,
                self.lattice)

    def _wrap_output(self, name: str, arr) -> FeatureColumn:
        """Wrap a device output as the column the numpy path would have
        produced — metadata from the zero-row probe, the ARRAY left on
        device (numpy consumers convert lazily on first touch)."""
        proto = self._proto[name]
        if proto.kind == "vector":
            return FeatureColumn(ftype=proto.ftype,
                                 data=arr.reshape(len(arr), -1),
                                 metadata=proto.metadata)
        return FeatureColumn(ftype=proto.ftype, data=arr.reshape(-1))

    def _report_segment(self, steps, trace_seconds, wall, cdelta,
                        n_rows) -> None:
        """Per-stage listener rows for a fused segment: wall/compile
        apportioned by each stage's recorded TRACE share (the only
        per-stage signal a fused program leaves; documented
        approximation, docs/prepare.md)."""
        if self.listener is None:
            return
        shares = [max(trace_seconds.get(j, 0.0), 0.0)
                  for j in range(len(steps))]
        total = sum(shares)
        if total <= 0:
            shares = [1.0] * len(steps)
            total = float(len(steps))
        for step, share in zip(steps, shares):
            frac = share / total
            self.listener.on_stage_completed(
                step.stage, "transform", wall * frac, n_rows,
                compile_seconds=cdelta * frac)


def stage_encode(stage: Transformer, i: int, col: FeatureColumn):
    """Host boundary encoder for input slot ``i`` — identity for
    numeric/vector columns (device-resident arrays pass through
    untouched instead of round-tripping via numpy)."""
    if not stage.encodes_input(i) and col.kind in ("numeric", "vector") \
            and _is_jax_array(col.data):
        return col.data
    return stage.encode_input_column(i, col)


def _build_segment_fn(step_pos, k_in: int):
    """Compose the segment's kernels into ONE traced function and jit
    it. The body runs exactly once per trace: per-stage wall time
    measured here IS that stage's trace cost, and the compile-time
    section attributes its trace/lower events (utils/compile_time.py).
    Everything is positional (slot 0..k_in-1 = inputs, then one slot
    per step) so the program is identical across retrains regardless
    of stage uids or feature names."""
    import jax

    trace_seconds: Dict[int, float] = {}

    def run(inputs, mask):
        env = list(inputs)
        for j, (stage, in_pos) in enumerate(step_pos):
            t0 = time.perf_counter()
            with compile_time.section(
                    f"prepare:stage:{type(stage).__name__}"):
                env.append(stage.transform_arrays(
                    [env[p] for p in in_pos]))
            trace_seconds[j] = trace_seconds.get(
                j, 0.0) + time.perf_counter() - t0
        outs = []
        for o in env[k_in:]:
            outs.append(o * (mask[:, None] if o.ndim == 2 else mask))
        return tuple(outs)

    return jax.jit(run), trace_seconds  # tx-lint: disable=TX-J02 (one jit per SEGMENT, cached across trains via the state fingerprint)


def _raw_features(result_features: Sequence[Feature]) -> List[Feature]:
    uniq: Dict[str, Feature] = {}
    for rf in result_features:
        for f in rf.raw_features():
            uniq.setdefault(f.uid, f)
    return sorted(uniq.values(), key=lambda f: f.name)
