"""Typed data ingestion (SURVEY §2.12; readers/src/main/scala/com/
salesforce/op/readers/)."""
from .data_readers import (AggregateDataReader, AvroProductReader,
                           ConditionalDataReader, CSVAutoReader,
                           CSVProductReader, DataReader, DataReaders,
                           ParquetProductReader)
from .joined import (JoinedAggregateReaders, JoinedDataReader,
                     JoinKeys)
from .streaming import StreamingReader, StreamingReaders

__all__ = ["DataReader", "AggregateDataReader", "ConditionalDataReader",
           "CSVProductReader", "CSVAutoReader", "AvroProductReader",
           "ParquetProductReader", "DataReaders", "JoinedDataReader",
           "JoinedAggregateReaders",
           "JoinKeys", "StreamingReader", "StreamingReaders"]
