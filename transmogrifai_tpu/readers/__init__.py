"""Typed data ingestion (SURVEY §2.12; readers/src/main/scala/com/
salesforce/op/readers/)."""
from .data_readers import (AggregateDataReader, ConditionalDataReader,
                           CSVAutoReader, CSVProductReader, DataReader,
                           DataReaders, ParquetProductReader)
from .joined import JoinedDataReader, JoinKeys

__all__ = ["DataReader", "AggregateDataReader", "ConditionalDataReader",
           "CSVProductReader", "CSVAutoReader", "ParquetProductReader",
           "DataReaders", "JoinedDataReader", "JoinKeys"]
