"""Typed data readers: simple, aggregate, and conditional ingestion.

TPU-native port of the reference readers module
(readers/src/main/scala/com/salesforce/op/readers/{Reader.scala:96,168,
DataReader.scala:57,173,206,252,288,351, DataReaders.scala:44}):
a reader loads raw records (CSV/Parquet/in-memory), optionally groups
them by key and monoid-aggregates each feature's dated events around a
cutoff time, and materializes the raw-feature Dataset the workflow
trains on. Where the reference runs extract fns in a Spark RDD map,
here extraction is a host-side columnar pass feeding device arrays.

- :class:`DataReader` — one record = one row (simple readers).
- :class:`AggregateDataReader` — groupBy(key); predictors aggregate
  events strictly before the cutoff, responses at/after it
  (leakage-safe feature/label windows with the reference's exact
  boundaries, DataReader.scala:206-330 +
  FeatureAggregator.scala:114-122).
- :class:`ConditionalDataReader` — per-key cutoff from a target
  condition (e.g. "first purchase"); predictors aggregate before the
  key's own event, responses within a window after
  (ConditionalParams:351).
"""
from __future__ import annotations

import csv as _csv
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..features.aggregators import CutOffTime, Event, default_aggregator
from ..features.columns import Dataset, FeatureColumn
from ..features.feature import Feature
from ..features.generator import FeatureGeneratorStage
from ..types import OPNumeric
from ..types.base import NonNullable


def _box_aggregated(ftype, values: List[Any]) -> List[Any]:
    """Box aggregated values; non-nullable numeric types get the monoid
    zero for keys with no surviving events (reference: RealNN monoid zero
    is 0.0, MonoidAggregatorDefaults.scala)."""
    if issubclass(ftype, NonNullable) and issubclass(ftype, OPNumeric):
        values = [0.0 if v is None else v for v in values]
    return [ftype.from_any(v) for v in values]

__all__ = ["DataReader", "AggregateDataReader", "ConditionalDataReader",
           "CSVProductReader", "CSVAutoReader", "ParquetProductReader",
           "DataReaders"]


class DataReader:
    """Batch reader over in-memory records or a file
    (reference DataReader.scala:57; key fn per ReaderKey.scala:74-94)."""

    def __init__(self, records: Optional[Iterable[Any]] = None,
                 key_fn: Optional[Callable[[Any], str]] = None,
                 source: Optional["DataReader"] = None):
        self._records = list(records) if records is not None else None
        self._source = source
        self.key_fn = key_fn

    # -- loading -----------------------------------------------------------
    def read_records(self) -> List[Any]:
        if self._records is not None:
            return self._records
        if self._source is not None:
            return self._source.read_records()  # lazy file I/O
        raise ValueError(f"{type(self).__name__} has no data source")

    # -- materialization (reference generateDataFrame:173) -----------------
    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        records = self.read_records()
        cols: Dict[str, FeatureColumn] = {}
        for f in raw_features:
            gen = self._generator(f)
            cols[f.name] = gen.extract_column(records)
        return Dataset(cols)

    @staticmethod
    def _generator(f: Feature) -> FeatureGeneratorStage:
        gen = f.origin_stage
        if not isinstance(gen, FeatureGeneratorStage):
            raise TypeError(f"Feature {f.name!r} has no generator stage")
        return gen


class AggregateDataReader(DataReader):
    """GroupBy-key + monoid aggregation with a cutoff
    (reference AggregateDataReader, DataReader.scala:252).

    ``timestamp_fn`` extracts each record's event time (ms). Predictor
    features aggregate events with ``cutoff - window <= time < cutoff``
    (window set per feature on the builder); response features
    aggregate events with ``cutoff <= time <= cutoff + window`` — the
    reference's exact leakage-safe boundaries
    (FeatureAggregator.scala:114-122).
    """

    def __init__(self, records: Optional[Iterable[Any]] = None,
                 key_fn: Optional[Callable[[Any], str]] = None,
                 timestamp_fn: Optional[Callable[[Any], int]] = None,
                 cutoff_time: Optional[CutOffTime] = None,
                 response_window_ms: Optional[int] = None,
                 source: Optional[DataReader] = None):
        super().__init__(records, key_fn, source=source)
        if key_fn is None:
            raise ValueError("AggregateDataReader requires key_fn")
        self.timestamp_fn = timestamp_fn or (lambda r: 0)
        self.cutoff_time = cutoff_time or CutOffTime.no_cutoff()
        self.response_window_ms = response_window_ms

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        records = self.read_records()
        groups: Dict[str, List[Any]] = {}
        for r in records:
            groups.setdefault(str(self.key_fn(r)), []).append(r)
        keys = sorted(groups)
        cutoff = self.cutoff_time.time_ms

        cols: Dict[str, FeatureColumn] = {}
        for f in raw_features:
            gen = self._generator(f)
            agg = gen.aggregator or default_aggregator(f.ftype)
            window = gen.aggregate_window_ms
            values: List[Any] = []
            for k in keys:
                events = [Event(int(self.timestamp_fn(r)),
                                gen.extract_fn(r), f.is_response)
                          for r in groups[k]]
                events = self._filter(events, f.is_response, cutoff, window)
                if hasattr(agg, "reduce_events"):
                    values.append(agg.reduce_events(events))
                else:
                    values.append(agg.reduce([e.value for e in events]))
            values = [v.value if hasattr(v, "value") else v for v in values]
            cols[f.name] = FeatureColumn.from_values(
                f.ftype, _box_aggregated(f.ftype, values))
        ds = Dataset(cols)
        ds.keys = keys  # row identity (reference KeyFieldName column)
        return ds

    def _filter(self, events: List[Event], is_response: bool,
                cutoff: Optional[int], window: Optional[int]
                ) -> List[Event]:
        """Reference boundary semantics exactly
        (FeatureAggregator.filterByDateWithCutoff, features/.../
        aggregators/FeatureAggregator.scala:114-122): responses take
        ``cutoff <= t <= cutoff + window``; predictors take
        ``cutoff - window <= t < cutoff``."""
        if cutoff is None:
            return events
        if is_response:
            kept = [e for e in events if e.date_ms >= cutoff]
            # per-feature window takes precedence over the reader-level
            # response window (reference specialTimeWindow.orElse(timeWindow))
            rw = window if window is not None else self.response_window_ms
            if rw is not None:
                kept = [e for e in kept if e.date_ms <= cutoff + rw]
        else:
            kept = [e for e in events if e.date_ms < cutoff]
            if window is not None:
                kept = [e for e in kept if e.date_ms >= cutoff - window]
        return kept


class ConditionalDataReader(AggregateDataReader):
    """Per-key cutoff from a target condition
    (reference ConditionalDataReader, DataReader.scala:288 +
    ConditionalParams:351): each key's cutoff is the time of its first
    record matching ``target_condition``; keys with no match are dropped
    (``drop_if_no_target=True``) or, when kept, contribute all events to
    predictors and none to responses (no label without a target event —
    leakage-safe)."""

    def __init__(self, records: Optional[Iterable[Any]] = None,
                 key_fn: Optional[Callable[[Any], str]] = None,
                 timestamp_fn: Optional[Callable[[Any], int]] = None,
                 target_condition: Optional[Callable[[Any], bool]] = None,
                 response_window_ms: Optional[int] = None,
                 predictor_window_ms: Optional[int] = None,
                 drop_if_no_target: bool = True,
                 source: Optional[DataReader] = None):
        super().__init__(records, key_fn, timestamp_fn,
                         CutOffTime.no_cutoff(), response_window_ms,
                         source=source)
        if target_condition is None:
            raise ValueError("ConditionalDataReader requires "
                             "target_condition")
        self.target_condition = target_condition
        self.predictor_window_ms = predictor_window_ms
        self.drop_if_no_target = drop_if_no_target

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        records = self.read_records()
        groups: Dict[str, List[Any]] = {}
        for r in records:
            groups.setdefault(str(self.key_fn(r)), []).append(r)

        cutoffs: Dict[str, int] = {}
        for k, rs in groups.items():
            times = [int(self.timestamp_fn(r)) for r in rs
                     if self.target_condition(r)]
            if times:
                cutoffs[k] = min(times)
        keys = sorted(cutoffs if self.drop_if_no_target else groups)

        cols: Dict[str, FeatureColumn] = {}
        for f in raw_features:
            gen = self._generator(f)
            agg = gen.aggregator or default_aggregator(f.ftype)
            # per-feature window; reader-level defaults (predictor vs
            # response) are resolved per branch in _filter_conditional
            window = gen.aggregate_window_ms
            values: List[Any] = []
            for k in keys:
                cutoff = cutoffs.get(k)
                events = [Event(int(self.timestamp_fn(r)),
                                gen.extract_fn(r), f.is_response)
                          for r in groups[k]]
                if cutoff is not None:
                    events = self._filter_conditional(
                        events, f.is_response, cutoff, window)
                elif f.is_response:
                    events = []  # no target event -> no response value
                if hasattr(agg, "reduce_events"):
                    values.append(agg.reduce_events(events))
                else:
                    values.append(agg.reduce([e.value for e in events]))
            values = [v.value if hasattr(v, "value") else v for v in values]
            cols[f.name] = FeatureColumn.from_values(
                f.ftype, _box_aggregated(f.ftype, values))
        ds = Dataset(cols)
        ds.keys = keys
        return ds

    def _filter_conditional(self, events, is_response, cutoff, window):
        """Predictors strictly before the target event; responses at or
        after it, up to and INCLUDING cutoff + window — the same
        boundaries as the aggregate filter (FeatureAggregator.scala:
        114-122), with the per-key target time as the cutoff. ``window``
        is the PER-FEATURE window; the reader-level defaults
        (predictor_window_ms / response_window_ms) apply per branch when
        the feature has none (reference
        specialTimeWindow.orElse(timeWindow))."""
        if is_response:
            kept = [e for e in events if e.date_ms >= cutoff]
            rw = window if window is not None else self.response_window_ms
            if rw is not None:
                kept = [e for e in kept if e.date_ms <= cutoff + rw]
        else:
            kept = [e for e in events if e.date_ms < cutoff]
            pw = window if window is not None else self.predictor_window_ms
            if pw is not None:
                kept = [e for e in kept if e.date_ms >= cutoff - pw]
        return kept


# ---------------------------------------------------------------------------
# file-format readers (reference CSVReaders.scala / CSVAutoReaders.scala /
# ParquetProductReader.scala)
# ---------------------------------------------------------------------------

def _parse_cell(v: str):
    if v is None or v == "":
        return None
    return v


class CSVProductReader(DataReader):
    """Header CSV -> dict records, raw strings (reference csvCase readers;
    typed conversion happens in feature extract fns)."""

    def __init__(self, path: str,
                 key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(records=None, key_fn=key_fn)
        self.path = path

    def read_records(self) -> List[Dict[str, Any]]:
        with open(self.path, newline="") as fh:
            return [{k: _parse_cell(v) for k, v in row.items()}
                    for row in _csv.DictReader(fh)]


class CSVAutoReader(CSVProductReader):
    """CSV with schema inference: numeric-looking cells become floats/ints
    (reference CSVAutoReaders.scala + spark-csv inference)."""

    def read_records(self) -> List[Dict[str, Any]]:
        rows = super().read_records()
        if not rows:
            return rows
        cols = rows[0].keys()
        casts: Dict[str, Callable] = {}
        for c in cols:
            vals = [r[c] for r in rows if r[c] is not None]
            if vals and all(_is_number(v) for v in vals):
                casts[c] = float if any("." in v or "e" in v.lower()
                                        for v in vals) else int
        for r in rows:
            for c, cast in casts.items():
                if r[c] is not None:
                    # int columns cast directly (no float round-trip, so
                    # ids > 2^53 stay exact)
                    r[c] = cast(r[c])
        return rows


def _is_number(v: str) -> bool:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return False
    # 'nan'/'inf' cells are not numeric data (common export artifacts)
    return np.isfinite(f)


class AvroProductReader(DataReader):
    """Avro object container file(s) -> dict records (reference
    AvroReaders.scala; decoding via utils/avro_io.py — no avro library
    in the image). ``path`` may be a file or a glob."""

    def __init__(self, path: str,
                 key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(records=None, key_fn=key_fn)
        self.path = path

    def read_records(self) -> List[Dict[str, Any]]:
        import glob as _glob
        from ..utils.avro_io import read_avro
        paths = sorted(_glob.glob(self.path)) or [self.path]
        out: List[Dict[str, Any]] = []
        for p in paths:
            out.extend(read_avro(p))
        return out


class ParquetProductReader(DataReader):
    """Parquet via pandas/pyarrow (reference ParquetProductReader.scala)."""

    def __init__(self, path: str,
                 key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(records=None, key_fn=key_fn)
        self.path = path

    def read_records(self) -> List[Dict[str, Any]]:
        import pandas as pd
        df = pd.read_parquet(self.path)
        recs = df.to_dict(orient="records")
        for r in recs:
            for k, v in r.items():
                if isinstance(v, float) and np.isnan(v):
                    r[k] = None
        return recs


class DataReaders:
    """Factory namespace (reference DataReaders.scala:44)."""

    class Simple:
        @staticmethod
        def csv(path: str, key_fn=None) -> CSVProductReader:
            return CSVProductReader(path, key_fn)

        @staticmethod
        def csv_auto(path: str, key_fn=None) -> CSVAutoReader:
            return CSVAutoReader(path, key_fn)

        @staticmethod
        def avro(path: str, key_fn=None) -> AvroProductReader:
            return AvroProductReader(path, key_fn)

        @staticmethod
        def parquet(path: str, key_fn=None) -> ParquetProductReader:
            return ParquetProductReader(path, key_fn)

        @staticmethod
        def custom(records, key_fn=None) -> DataReader:
            return DataReader(records, key_fn)

    class Aggregate:
        @staticmethod
        def csv(path: str, key_fn, timestamp_fn, cutoff_time=None,
                response_window_ms=None) -> AggregateDataReader:
            return AggregateDataReader(
                source=CSVProductReader(path),
                key_fn=key_fn, timestamp_fn=timestamp_fn,
                cutoff_time=cutoff_time,
                response_window_ms=response_window_ms)

        @staticmethod
        def custom(records, key_fn, timestamp_fn, cutoff_time=None,
                   response_window_ms=None) -> AggregateDataReader:
            return AggregateDataReader(records, key_fn, timestamp_fn,
                                       cutoff_time, response_window_ms)

    class Conditional:
        @staticmethod
        def custom(records, key_fn, timestamp_fn, target_condition,
                   response_window_ms=None, predictor_window_ms=None,
                   drop_if_no_target=True) -> ConditionalDataReader:
            return ConditionalDataReader(
                records, key_fn, timestamp_fn, target_condition,
                response_window_ms, predictor_window_ms, drop_if_no_target)
