"""Joined readers: combine two readers on their keys.

TPU-native port of the reference reader algebra
(readers/src/main/scala/com/salesforce/op/readers/JoinedDataReader.scala:
83,119,251): ``left.outer_join(right)`` / ``inner_join`` produce a
reader whose records merge the two sides' fields per key; features
extract from the merged record. A secondary aggregation can run after
the join (JoinedAggregateDataReader:251) via ``with_aggregation``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from ..features.feature import Feature
from .data_readers import AggregateDataReader, DataReader

__all__ = ["JoinedDataReader", "JoinedAggregateReaders", "JoinKeys"]


class JoinKeys:
    """(reference JoinKeys, JoinedDataReader.scala:83)"""

    def __init__(self, left_key: Callable[[Any], str],
                 right_key: Callable[[Any], str]):
        self.left_key = left_key
        self.right_key = right_key


class JoinedDataReader(DataReader):
    """Join two readers' records by key (reference JoinedReader:119).

    ``join_type``: "leftOuter" keeps all left keys (right fields None
    when absent); "inner" keeps only matched keys. Colliding field
    names take the left side's value (right accessible via
    ``right_prefix``).
    """

    def __init__(self, left: DataReader, right: DataReader,
                 join_keys: JoinKeys, join_type: str = "leftOuter",
                 right_prefix: str = "right_"):
        super().__init__(records=None, key_fn=None)
        if join_type not in ("leftOuter", "inner"):
            raise ValueError("join_type must be 'leftOuter' or 'inner'")
        self.left = left
        self.right = right
        self.join_keys = join_keys
        self.join_type = join_type
        self.right_prefix = right_prefix
        self._aggregation: Optional[AggregateDataReader] = None

    # -- reader algebra (reference innerJoin/leftOuterJoin) -----------------
    @staticmethod
    def left_outer(left: DataReader, right: DataReader,
                   left_key, right_key) -> "JoinedDataReader":
        return JoinedDataReader(left, right,
                                JoinKeys(left_key, right_key), "leftOuter")

    @staticmethod
    def inner(left: DataReader, right: DataReader,
              left_key, right_key) -> "JoinedDataReader":
        return JoinedDataReader(left, right,
                                JoinKeys(left_key, right_key), "inner")

    def with_aggregation(self, key_fn, timestamp_fn, cutoff_time=None,
                         response_window_ms=None) -> AggregateDataReader:
        """Secondary aggregation after the join
        (reference JoinedAggregateDataReader:251)."""
        return AggregateDataReader(
            source=self, key_fn=key_fn, timestamp_fn=timestamp_fn,
            cutoff_time=cutoff_time,
            response_window_ms=response_window_ms)

    # -- materialization ----------------------------------------------------
    def read_records(self) -> List[Dict[str, Any]]:
        left_records = self.left.read_records()
        right_records = self.right.read_records()
        by_key: Dict[str, List[Any]] = {}
        for r in right_records:
            by_key.setdefault(str(self.join_keys.right_key(r)), []).append(r)

        def fields(rec) -> Dict[str, Any]:
            return dict(rec) if isinstance(rec, dict) else {
                k: getattr(rec, k) for k in dir(rec)
                if not k.startswith("_")}

        out: List[Dict[str, Any]] = []
        for l in left_records:
            key = str(self.join_keys.left_key(l))
            matches = by_key.get(key)
            if not matches:
                if self.join_type == "inner":
                    continue
                out.append(fields(l))
                continue
            for r in matches:
                merged = fields(r)
                merged.update({f"{self.right_prefix}{k}": v
                               for k, v in merged.items()})
                merged.update(fields(l))  # left wins on collision
                out.append(merged)
        return out


class JoinedAggregateReaders(DataReader):
    """Key-join of two KEYED readers' PREPARED datasets — the
    reference's actual join semantics (JoinedDataReader.scala:119 joins
    the sides' generated dataframes on their key columns, after each
    side aggregated its own features).

    Features bind to a side with ``FeatureBuilder...from_source(name)``
    (the reference encodes the side in FeatureBuilder[T]'s reader type
    parameter); untagged features default to the left side. For
    "leftOuter" the row keys are the left side's keys and right-side
    columns are empty (None) for keys absent from the right DATA —
    distinct from the monoid zero a present-but-filtered key aggregates
    to, matching the reference's null-vs-0.0 output. "inner" keeps the
    key intersection (left order).
    """

    def __init__(self, left: DataReader, right: DataReader,
                 left_name: str = "left", right_name: str = "right",
                 join_type: str = "leftOuter"):
        super().__init__(records=None, key_fn=None)
        if join_type not in ("leftOuter", "inner"):
            raise ValueError("join_type must be 'leftOuter' or 'inner'")
        self.left = left
        self.right = right
        self.left_name = left_name
        self.right_name = right_name
        self.join_type = join_type

    def _split(self, raw_features: Sequence[Feature]):
        lf, rf = [], []
        for f in raw_features:
            src = getattr(f.origin_stage, "source_name", None)
            if src == self.right_name:
                rf.append(f)
            elif src in (None, self.left_name):
                lf.append(f)
            else:
                raise ValueError(
                    f"feature {f.name!r} is bound to unknown source "
                    f"{src!r}; sides are {self.left_name!r} / "
                    f"{self.right_name!r}")
        dup = {f.name for f in lf} & {f.name for f in rf}
        if dup:
            raise ValueError(
                f"feature names {sorted(dup)} appear on both join "
                f"sides; rename one side's features")
        return lf, rf

    def generate_dataset(self, raw_features: Sequence[Feature]):
        from ..features.columns import Dataset, FeatureColumn
        lf, rf = self._split(raw_features)
        lds = self.left.generate_dataset(lf)
        rds = self.right.generate_dataset(rf)
        lkeys = getattr(lds, "keys", None)
        rkeys = getattr(rds, "keys", None)
        if lkeys is None or rkeys is None:
            raise ValueError(
                "JoinedAggregateReaders requires keyed sides (readers "
                "whose datasets carry per-row keys, e.g. aggregate/"
                "conditional readers)")
        if self.join_type == "inner":
            rset = set(rkeys)
            keys = [k for k in lkeys if k in rset]
        else:
            keys = list(lkeys)
        lpos = {k: i for i, k in enumerate(lkeys)}
        rpos = {k: i for i, k in enumerate(rkeys)}
        from .data_readers import _box_aggregated
        cols = {}
        for f, ds, pos in ([(f, lds, lpos) for f in lf]
                           + [(f, rds, rpos) for f in rf]):
            side_col = ds[f.name]
            values = [side_col.boxed(pos[k]).value if k in pos else None
                      for k in keys]
            # keys absent from a side get null for nullable types; the
            # monoid zero for NonNullable numerics (RealNN cannot hold
            # null — same rule _box_aggregated applies to empty
            # aggregations)
            cols[f.name] = FeatureColumn.from_values(
                f.ftype, _box_aggregated(f.ftype, values))
        out = Dataset(cols)
        out.keys = keys
        return out
