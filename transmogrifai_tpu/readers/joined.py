"""Joined readers: combine two readers on their keys.

TPU-native port of the reference reader algebra
(readers/src/main/scala/com/salesforce/op/readers/JoinedDataReader.scala:
83,119,251): ``left.outer_join(right)`` / ``inner_join`` produce a
reader whose records merge the two sides' fields per key; features
extract from the merged record. A secondary aggregation can run after
the join (JoinedAggregateDataReader:251) via ``with_aggregation``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from ..features.feature import Feature
from .data_readers import AggregateDataReader, DataReader

__all__ = ["JoinedDataReader", "JoinKeys"]


class JoinKeys:
    """(reference JoinKeys, JoinedDataReader.scala:83)"""

    def __init__(self, left_key: Callable[[Any], str],
                 right_key: Callable[[Any], str]):
        self.left_key = left_key
        self.right_key = right_key


class JoinedDataReader(DataReader):
    """Join two readers' records by key (reference JoinedReader:119).

    ``join_type``: "leftOuter" keeps all left keys (right fields None
    when absent); "inner" keeps only matched keys. Colliding field
    names take the left side's value (right accessible via
    ``right_prefix``).
    """

    def __init__(self, left: DataReader, right: DataReader,
                 join_keys: JoinKeys, join_type: str = "leftOuter",
                 right_prefix: str = "right_"):
        super().__init__(records=None, key_fn=None)
        if join_type not in ("leftOuter", "inner"):
            raise ValueError("join_type must be 'leftOuter' or 'inner'")
        self.left = left
        self.right = right
        self.join_keys = join_keys
        self.join_type = join_type
        self.right_prefix = right_prefix
        self._aggregation: Optional[AggregateDataReader] = None

    # -- reader algebra (reference innerJoin/leftOuterJoin) -----------------
    @staticmethod
    def left_outer(left: DataReader, right: DataReader,
                   left_key, right_key) -> "JoinedDataReader":
        return JoinedDataReader(left, right,
                                JoinKeys(left_key, right_key), "leftOuter")

    @staticmethod
    def inner(left: DataReader, right: DataReader,
              left_key, right_key) -> "JoinedDataReader":
        return JoinedDataReader(left, right,
                                JoinKeys(left_key, right_key), "inner")

    def with_aggregation(self, key_fn, timestamp_fn, cutoff_time=None,
                         response_window_ms=None) -> AggregateDataReader:
        """Secondary aggregation after the join
        (reference JoinedAggregateDataReader:251)."""
        return AggregateDataReader(
            source=self, key_fn=key_fn, timestamp_fn=timestamp_fn,
            cutoff_time=cutoff_time,
            response_window_ms=response_window_ms)

    # -- materialization ----------------------------------------------------
    def read_records(self) -> List[Dict[str, Any]]:
        left_records = self.left.read_records()
        right_records = self.right.read_records()
        by_key: Dict[str, List[Any]] = {}
        for r in right_records:
            by_key.setdefault(str(self.join_keys.right_key(r)), []).append(r)

        def fields(rec) -> Dict[str, Any]:
            return dict(rec) if isinstance(rec, dict) else {
                k: getattr(rec, k) for k in dir(rec)
                if not k.startswith("_")}

        out: List[Dict[str, Any]] = []
        for l in left_records:
            key = str(self.join_keys.left_key(l))
            matches = by_key.get(key)
            if not matches:
                if self.join_type == "inner":
                    continue
                out.append(fields(l))
                continue
            for r in matches:
                merged = fields(r)
                merged.update({f"{self.right_prefix}{k}": v
                               for k, v in merged.items()})
                merged.update(fields(l))  # left wins on collision
                out.append(merged)
        return out
