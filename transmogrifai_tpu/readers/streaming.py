"""Streaming readers: micro-batch record streams for scoring.

TPU-native equivalent of the reference streaming stack
(readers/src/main/scala/com/salesforce/op/readers/StreamingReader.scala:54
and StreamingReaders.scala:43-59): the reference turns a directory of
Avro files into a Spark DStream of micro-batches; here a
:class:`StreamingReader` yields batches of dict records that plug
straight into ``WorkflowRunner.streaming_score`` (workflow/runner.py).
Sources: an iterable of records (chunked), a directory of Avro/CSV
files (one batch per file — the DStream fileStream analogue), or any
iterator of pre-built batches.
"""
from __future__ import annotations

import glob
import os
from typing import Any, Callable, Iterable, Iterator, List, Optional

__all__ = ["StreamingReader", "StreamingReaders"]


class StreamingReader:
    """A re-iterable stream of record micro-batches."""

    def __init__(self, batch_source: Callable[[], Iterator[List[dict]]]):
        self._batch_source = batch_source

    def stream(self) -> Iterator[List[dict]]:
        """(reference StreamingReader.stream:54)"""
        return self._batch_source()

    def __iter__(self) -> Iterator[List[dict]]:
        return self.stream()

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_records(records: Iterable[dict],
                     batch_size: int = 1000) -> "StreamingReader":
        """Chunk an iterable of records into fixed-size micro-batches."""
        records = list(records)

        def gen():
            for i in range(0, len(records), batch_size):
                yield records[i:i + batch_size]
        return StreamingReader(gen)

    @staticmethod
    def from_batches(batches: Iterable[List[dict]]) -> "StreamingReader":
        batches = [list(b) for b in batches]
        return StreamingReader(lambda: iter(batches))

    @staticmethod
    def avro(path_glob: str) -> "StreamingReader":
        """One micro-batch per Avro container file, in name order
        (reference StreamingReaders.Simple.avro:43 fileStream)."""
        from ..utils.avro_io import read_avro

        def gen():
            for p in sorted(glob.glob(path_glob)):
                yield read_avro(p)
        return StreamingReader(gen)

    @staticmethod
    def csv(path_glob: str) -> "StreamingReader":
        """One micro-batch per CSV file, in name order."""
        from .data_readers import CSVAutoReader

        def gen():
            for p in sorted(glob.glob(path_glob)):
                yield CSVAutoReader(p).read_records()
        return StreamingReader(gen)

    @staticmethod
    def tail_directory(path_glob: str, poll_interval_s: float = 1.0,
                       idle_timeout_s: Optional[float] = None,
                       fmt: str = "auto",
                       on_error: str = "raise") -> "StreamingReader":
        """LIVE directory tail: yield one micro-batch per NEW file
        matching ``path_glob`` as it appears, polling every
        ``poll_interval_s`` — the continuous-source behavior of the
        reference's DStream fileStream (StreamingReader.scala:54),
        which r3's static listing did not have. Files present at start
        are emitted first (in name order); the stream then keeps
        polling until ``idle_timeout_s`` passes with no new file
        (None = tail forever, like a DStream until its context stops).
        ``fmt``: "avro" | "csv" | "auto" (by extension).
        ``on_error``: "raise" stops the stream on an unreadable file
        (the reference's stop-on-error); "skip" logs it, marks it
        consumed, and keeps tailing."""
        import logging
        import time as _time
        if on_error not in ("raise", "skip"):
            raise ValueError("on_error must be 'raise' or 'skip'")
        log = logging.getLogger(__name__)

        def _read(path: str) -> List[dict]:
            kind = fmt
            if kind == "auto":
                kind = "avro" if path.endswith(".avro") else "csv"
            if kind == "avro":
                from ..utils.avro_io import read_avro
                return read_avro(path)
            from .data_readers import CSVAutoReader
            return CSVAutoReader(path).read_records()

        def _stat(p: str):
            try:
                st = os.stat(p)
                return (st.st_size, st.st_mtime_ns)
            except OSError:
                return None

        def gen():
            seen: set = set()
            #: path -> (last observed (size, mtime), first-stable time)
            pending: dict = {}
            last_new = _time.monotonic()
            while True:
                now = _time.monotonic()
                current = sorted(glob.glob(path_glob))
                # bound memory on long tails over high-churn spools:
                # rotated-away files leave the bookkeeping
                live = set(current)
                seen &= live
                for p in list(pending):
                    if p not in live:
                        del pending[p]
                delivered = False
                for p in current:
                    if p in seen:
                        continue
                    sig = _stat(p)
                    if sig is None:
                        continue
                    prev = pending.get(p)
                    if prev is None:
                        # a NEW file resets the idle clock so a complete
                        # file landing just inside the window still gets
                        # its one stabilization interval; subsequent
                        # (size, mtime) churn does NOT reset it, so a
                        # perpetually-growing file cannot hold the
                        # stream open past the timeout
                        last_new = now
                    if prev is None or prev[0] != sig:
                        # first sighting or still growing: the
                        # (size, mtime) must hold for a full poll
                        # interval so a file caught mid-write is not
                        # truncated (DStream mod-time windowing role).
                        # Wall-clock age, not poll count — delivery
                        # passes skip the sleep, so consecutive polls
                        # can be microseconds apart.
                        pending[p] = (sig, now)
                        continue
                    if now - prev[1] < poll_interval_s:
                        continue
                    del pending[p]
                    seen.add(p)
                    last_new = now
                    delivered = True
                    try:
                        batch = _read(p)
                    except Exception:
                        if on_error == "raise":
                            raise
                        log.warning("tail_directory: unreadable file "
                                    "%s skipped", p, exc_info=True)
                        continue
                    yield batch
                if not delivered:
                    # timeout is measured from the last DELIVERY only: a
                    # file that keeps growing (or is touched forever)
                    # stays pending but must not hold the stream open
                    # past the idle window
                    if idle_timeout_s is not None and \
                            _time.monotonic() - last_new > idle_timeout_s:
                        if pending:
                            log.warning(
                                "tail_directory: idle timeout with %d "
                                "never-stabilizing file(s) undelivered: "
                                "%s", len(pending),
                                sorted(pending)[:5])
                        return
                    _time.sleep(poll_interval_s)
        return StreamingReader(gen)


class StreamingReaders:
    """Factory namespace (reference StreamingReaders.scala:43)."""

    class Simple:
        avro = staticmethod(StreamingReader.avro)
        csv = staticmethod(StreamingReader.csv)
        custom = staticmethod(StreamingReader.from_records)
        tail = staticmethod(StreamingReader.tail_directory)
