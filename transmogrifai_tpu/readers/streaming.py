"""Streaming readers: micro-batch record streams for scoring.

TPU-native equivalent of the reference streaming stack
(readers/src/main/scala/com/salesforce/op/readers/StreamingReader.scala:54
and StreamingReaders.scala:43-59): the reference turns a directory of
Avro files into a Spark DStream of micro-batches; here a
:class:`StreamingReader` yields batches of dict records that plug
straight into ``WorkflowRunner.streaming_score`` (workflow/runner.py).
Sources: an iterable of records (chunked), a directory of Avro/CSV
files (one batch per file — the DStream fileStream analogue), or any
iterator of pre-built batches.
"""
from __future__ import annotations

import glob
import os
from typing import Any, Callable, Iterable, Iterator, List, Optional

__all__ = ["StreamingReader", "StreamingReaders"]


class StreamingReader:
    """A re-iterable stream of record micro-batches."""

    def __init__(self, batch_source: Callable[[], Iterator[List[dict]]]):
        self._batch_source = batch_source

    def stream(self) -> Iterator[List[dict]]:
        """(reference StreamingReader.stream:54)"""
        return self._batch_source()

    def __iter__(self) -> Iterator[List[dict]]:
        return self.stream()

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_records(records: Iterable[dict],
                     batch_size: int = 1000) -> "StreamingReader":
        """Chunk an iterable of records into fixed-size micro-batches."""
        records = list(records)

        def gen():
            for i in range(0, len(records), batch_size):
                yield records[i:i + batch_size]
        return StreamingReader(gen)

    @staticmethod
    def from_batches(batches: Iterable[List[dict]]) -> "StreamingReader":
        batches = [list(b) for b in batches]
        return StreamingReader(lambda: iter(batches))

    @staticmethod
    def avro(path_glob: str) -> "StreamingReader":
        """One micro-batch per Avro container file, in name order
        (reference StreamingReaders.Simple.avro:43 fileStream)."""
        from ..utils.avro_io import read_avro

        def gen():
            for p in sorted(glob.glob(path_glob)):
                yield read_avro(p)
        return StreamingReader(gen)

    @staticmethod
    def csv(path_glob: str) -> "StreamingReader":
        """One micro-batch per CSV file, in name order."""
        from .data_readers import CSVAutoReader

        def gen():
            for p in sorted(glob.glob(path_glob)):
                yield CSVAutoReader(p).read_records()
        return StreamingReader(gen)


class StreamingReaders:
    """Factory namespace (reference StreamingReaders.scala:43)."""

    class Simple:
        avro = staticmethod(StreamingReader.avro)
        csv = staticmethod(StreamingReader.csv)
        custom = staticmethod(StreamingReader.from_records)
