"""Fault-tolerant training runtime (docs/resilience.md).

The Spark reference inherits executor-level fault tolerance for free;
this package rebuilds the equivalent for the JAX port as four
composable pieces threaded through the selector, workflow and serving
paths:

- **journal** — append-only, fsync'd, fingerprint-keyed JSONL of
  completed family evaluations; ``ModelSelector(checkpoint_dir=...)``
  writes it and ``Workflow.train(resume_from=...)`` replays it to a
  bitwise-identical winner with zero re-dispatched work.
- **errors + retry** — a transient-error classifier (preemption /
  RESOURCE_EXHAUSTED shapes) and an exponential-backoff
  ``RetryPolicy`` with deterministic jitter wrapping per-family
  dispatch and compiled-program dispatch.
- **context** — per-search ``RuntimeContext`` carrying the quarantine
  ledger: a family that keeps failing is removed with a recorded
  reason and the search degrades to survivors, raising one aggregated
  :class:`AllFamiliesFailedError` only when nothing is left.
- **faults** — the deterministic fault injector
  (``TX_FAULT_PLAN="family:GBTClassifier:dispatch:2=oom"``) that makes
  every recovery path testable.
"""
from .context import RuntimeContext
from .errors import (AllFamiliesFailedError, QuarantineRecord,
                     classify_error)
from .faults import (FaultInjector, InjectedFault, KillPoint,
                     maybe_inject)
from .journal import (SearchJournal, read_journal, search_fingerprint)
from .retry import RetryPolicy

__all__ = [
    "RuntimeContext", "RetryPolicy",
    "AllFamiliesFailedError", "QuarantineRecord", "classify_error",
    "FaultInjector", "InjectedFault", "KillPoint", "maybe_inject",
    "SearchJournal", "read_journal", "search_fingerprint",
]
