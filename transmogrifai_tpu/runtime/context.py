"""RuntimeContext: one search's fault-tolerance state.

Owned by the validator for the duration of one ``validate()`` call and
read back by the ``ModelSelector`` afterwards; bundles the retry
policy, the optional search journal, the per-family deadline and the
quarantine ledger so the dispatch layer threads ONE object instead of
five knobs.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import List, Optional, Sequence

from . import telemetry
from .errors import QuarantineRecord
from .journal import SearchJournal
from .retry import RetryPolicy

_log = logging.getLogger(__name__)

__all__ = ["RuntimeContext"]


class RuntimeContext:
    """Fault-tolerance state for one search.

    - ``retry``: the transient-error RetryPolicy for family dispatch.
    - ``family_deadline``: wall-clock seconds one family's dispatch may
      take before the threaded dispatcher abandons it (None = no
      deadline; ``TX_FAMILY_DEADLINE_S`` sets a process default).
    - ``journal``: opened when the selector carries a
      ``checkpoint_dir`` — completed family evaluations are appended
      and replayed on resume.
    - ``quarantined``: the ledger of families removed from this
      search, surfaced in ``ModelSelectorSummary.quarantined``.
    - ``nan_quarantine_fraction``: quarantine a family whose device
      metric matrix is at least this fraction non-finite (default 1.0
      — only a fully poisoned family is removed, so legitimate
      partial-NaN candidates keep today's drop-the-candidate
      semantics).
    """

    def __init__(self, retry: Optional[RetryPolicy] = None,
                 family_deadline: Optional[float] = None,
                 nan_quarantine_fraction: float = 1.0):
        self.retry = retry if retry is not None else RetryPolicy.from_env()
        if family_deadline is None:
            env = os.environ.get("TX_FAMILY_DEADLINE_S", "")
            family_deadline = float(env) if env else None
        self.family_deadline = family_deadline
        self.nan_quarantine_fraction = float(nan_quarantine_fraction)
        self.journal: Optional[SearchJournal] = None
        self.quarantined: List[QuarantineRecord] = []
        self._lock = threading.Lock()

    # -- journal -----------------------------------------------------------
    def open_journal(self, checkpoint_dir: str, fingerprint: str,
                     topology: Optional[dict] = None) -> None:
        """``topology`` (the validator's resolved mesh shape) is header
        metadata only — a journal resumes across device counts to the
        bitwise-identical winner (runtime/journal.py open())."""
        self.journal = SearchJournal(checkpoint_dir).open(
            fingerprint, topology=topology)
        if self.journal.replayed:
            telemetry.event("journal_resume",
                            checkpoint_dir=checkpoint_dir,
                            entries=self.journal.replayed)

    def close_journal(self) -> None:
        if self.journal is not None:
            self.journal.close()

    def journal_lookup(self, family_key: str, rung_label: str,
                       cands: Sequence[int]):
        """Replayed metric vectors, counting the resume savings."""
        if self.journal is None:
            return None
        hit = self.journal.lookup(family_key, rung_label, cands)
        if hit is not None:
            telemetry.count("journal_hits")
            telemetry.count("journal_replayed_entries",
                            len(hit) * (len(hit[0]) if hit else 0))
        return hit

    def journal_record(self, family_key: str, rung_label: str,
                       cands: Sequence[int], metrics, folds: int) -> None:
        if self.journal is None:
            return
        self.journal.record(family_key, rung_label, cands, metrics, folds)

    # -- quarantine --------------------------------------------------------
    def quarantine(self, family: str, reason: str, kind: str,
                   error_type: str = "", rung: Optional[int] = None,
                   retries: int = 0) -> QuarantineRecord:
        rec = QuarantineRecord(family=family, reason=reason, kind=kind,
                               error_type=error_type, rung=rung,
                               retries=retries)
        with self._lock:
            self.quarantined.append(rec)
        telemetry.count("quarantines")
        telemetry.event("quarantine", family=family, kind=kind,
                        reason=reason)
        return rec

    def quarantined_families(self) -> List[str]:
        with self._lock:
            return [r.family for r in self.quarantined]
