"""Error taxonomy + classifier for the fault-tolerant runtime.

The Spark reference leans on executor-level fault tolerance: a lost
worker re-runs its tasks, a sick executor is blacklisted, and the
driver aggregates what survived. The JAX port has no executors — a
raised `XlaRuntimeError` in one family's dispatch thread used to kill
the whole ``Workflow.train``. This module restores the *triage* half
of that machinery: every exception crossing a family-dispatch or
compile boundary is classified into one of three buckets:

- ``"transient"`` — preemption/RESOURCE_EXHAUSTED/UNAVAILABLE-shaped
  backend errors: worth retrying with backoff (runtime/retry.py);
  after retries are exhausted the family is quarantined.
- ``"family"`` — deterministic family-scoped failures (compile
  rejections, precondition violations, a poisoned metric matrix):
  retrying is futile; the family is quarantined immediately and the
  search continues with survivors.
- ``"bug"`` — everything else. A genuine code defect must PROPAGATE,
  not be silently absorbed into a quarantine record (the same
  discipline lint rule TX-R01 enforces statically on ``except``
  blocks in the selector/serving hot paths).

Classification is structural (type names + message patterns), not
``isinstance``-against-jaxlib: the classifier must work identically
whether the error came from a real TPU runtime, a CPU test process, or
the deterministic fault injector (runtime/faults.py).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["classify_error", "QuarantineRecord", "AllFamiliesFailedError",
           "TRANSIENT", "FAMILY", "BUG"]

TRANSIENT = "transient"
FAMILY = "family"
BUG = "bug"

#: backend error shapes worth retrying: resource pressure that may
#: clear (another family just freed its HBM), preempted/restarting
#: workers, flaky transport. Mirrors the gRPC/absl status names the
#: TPU runtime stamps into XlaRuntimeError messages.
_TRANSIENT_RE = re.compile(
    r"RESOURCE_EXHAUSTED|DEADLINE_EXCEEDED|UNAVAILABLE|ABORTED"
    r"|preempt(?:ed|ion)?|out of memory|allocat\w* failure"
    r"|connection (?:reset|refused|closed)|socket closed"
    r"|temporarily unavailable",
    re.IGNORECASE)

#: deterministic family-scoped failure shapes: the backend rejected
#: THIS program/data and will again (compile failures, numerical
#: blow-ups surfacing as runtime errors).
_FAMILY_RE = re.compile(
    r"INTERNAL|INVALID_ARGUMENT|FAILED_PRECONDITION|UNIMPLEMENTED"
    r"|compilation fail|lowering fail|injected family fault",
    re.IGNORECASE)

#: python-level exception types that behave like transient infra
#: failures regardless of message
_TRANSIENT_TYPES = (ConnectionError, TimeoutError, InterruptedError,
                    BrokenPipeError)


def _type_names(exc: BaseException) -> List[str]:
    return [c.__name__ for c in type(exc).__mro__]


def classify_error(exc: BaseException) -> str:
    """``"transient"`` / ``"family"`` / ``"bug"`` for one exception.

    ``XlaRuntimeError`` (matched by type NAME so jaxlib need not be
    importable) is never a "bug": the program crossed the compile
    bridge, so the defect is family-scoped at worst — transient when
    the status code says so, quarantinable otherwise."""
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    msg = f"{type(exc).__name__}: {exc}"
    if _TRANSIENT_RE.search(msg):
        return TRANSIENT
    names = _type_names(exc)
    if isinstance(exc, MemoryError):
        return FAMILY
    if "XlaRuntimeError" in names:
        return FAMILY if not _TRANSIENT_RE.search(msg) else TRANSIENT
    from ..models.base import FamilyPreconditionError
    if isinstance(exc, (FamilyPreconditionError, FloatingPointError)):
        return FAMILY
    if _FAMILY_RE.search(msg):
        return FAMILY
    return BUG


@dataclass
class QuarantineRecord:
    """One family removed from a search, and why — surfaced in
    ``ModelSelectorSummary.quarantined`` and ``model_insights()``."""
    family: str
    reason: str
    kind: str = FAMILY          # "transient" | "family" | "deadline" | "metrics"
    error_type: str = ""
    rung: Optional[int] = None
    retries: int = 0

    def to_json(self) -> dict:
        out = {"family": self.family, "reason": self.reason,
               "kind": self.kind, "errorType": self.error_type,
               "retries": self.retries}
        if self.rung is not None:
            out["rung"] = self.rung
        return out

    @classmethod
    def from_json(cls, d: dict) -> "QuarantineRecord":
        return cls(family=d.get("family", ""), reason=d.get("reason", ""),
                   kind=d.get("kind", FAMILY),
                   error_type=d.get("errorType", ""),
                   rung=d.get("rung"), retries=d.get("retries", 0))

    def __str__(self) -> str:
        tag = f" at rung {self.rung}" if self.rung is not None else ""
        return (f"{self.family}{tag}: [{self.kind}] {self.reason}"
                + (f" (after {self.retries} retries)" if self.retries
                   else ""))


class AllFamiliesFailedError(RuntimeError):
    """Every candidate family was quarantined (or produced no finite
    metric): there is nothing left to select. Raised ONCE with the full
    aggregated quarantine ledger instead of whichever family happened
    to die first — the operator sees every failure reason in one
    traceback."""

    def __init__(self, records: List[QuarantineRecord],
                 detail: str = ""):
        self.records = list(records)
        lines = "\n".join(f"  - {r}" for r in self.records) or "  (none)"
        super().__init__(
            f"all candidate families failed validation"
            + (f" ({detail})" if detail else "")
            + f"; quarantine ledger:\n{lines}")
