"""Deterministic fault injection: the test harness for the runtime.

Fault tolerance that is only exercised by real TPU preemptions is
fault tolerance that has never been tested. This module plants named
**injection sites** through the training/serving paths (family
dispatch, host fits, rung boundaries, model save, plan compile/
dispatch) and fires *planned* faults at exact occurrence counts, so
every recovery path — retry, quarantine, journal resume, atomic save
— is provable in a unit test and reproducible byte-for-byte.

A plan is a comma-separated list of rules::

    TX_FAULT_PLAN="family:GBTClassifier:dispatch:2=oom"

with the grammar ``scope:name:site:n=fault``:

- ``scope``  — ``family`` (name = model family class), ``rung``
  (name = rung index), ``workflow`` (save/load path), ``plan``
  (serving ScoringPlan; name = stage class, or ``device`` for the
  fused-program dispatch), ``serving`` (the guardrail layer,
  docs/serving_guardrails.md), ``lifecycle`` (the self-healing
  retrain/swap loop, docs/self_healing.md; name = the registered model
  name), ``state`` (the warm-restart snapshot path,
  docs/serving_restart.md; name = the registered model name or
  ``server``), ``admission`` (the overload admission edge,
  docs/admission.md; name = the registered model name), ``fleet``
  (the replica set + router layer, docs/fleet.md; name = the replica
  name, e.g. ``r0``).
- ``name``   — exact match or ``*``.
- ``site``   — where the probe sits: ``dispatch`` (per-family device
  eval or the serving plan's fused-program dispatch, once per retry
  attempt), ``fit`` (host-path candidate fit), ``metric`` (after a
  family's metric matrix lands), ``boundary`` (between racing rungs),
  ``save``, ``compile``, ``guard`` (``serving:output:guard`` — a
  ``nan`` fault poisons one scored row so the output guard's
  invalidate path is provable), and the lifecycle trio ``retrain``
  (top of every background training attempt — an ``oom`` there drills
  retry-then-quarantine with the old model still serving), ``canary``
  (candidate shadow-scoring — any fault rejects the candidate), and
  ``postswap`` (probed on each watched batch after a hot-swap — a
  fault there triggers the instant rollback drill), and the
  warm-restart pair ``snapshot`` (``state:<model>:snapshot`` — probed
  before each serving-state snapshot write; a ``torn`` fault truncates
  the document mid-write so the restore side's torn-tail detection is
  drillable) and ``restore`` (``state:<model>:restore`` — probed while
  rebuilding warm state on ``--resume-state`` boot; any fault must
  degrade to a clean cold start, never a crash), and ``enqueue``
  (``admission:<model>:enqueue`` — probed on every admission check; a
  ``burst`` fault registers a phantom arrival spike against the lane
  so shed answers, retry hints and the brownout state machine are
  drillable without generating real load), and the fleet trio
  (docs/fleet.md) ``kill`` (``fleet:<replica>:kill`` — probed by the
  replica manager's watch loop; a ``kill`` fault SIGKILLs that child
  process, driving the warm-takeover drill), ``partition``
  (``fleet:<replica>:partition`` — probed by the router on every
  forward to that replica; a raising fault such as ``preempt`` is
  treated as a transport failure, so the lane fails over), and
  ``hang`` (``fleet:<replica>:hang`` — probed inside the router's
  forward round-trip; a ``hang:<s>`` fault stalls only that forward
  in an executor thread so the per-request timeout and failover path
  fire deterministically).
- ``n``      — fire at the Nth matching probe (1-based), or ``*`` for
  every one.
- ``fault``  — ``oom`` (RESOURCE_EXHAUSTED-shaped — transient, then
  quarantined when persistent), ``preempt`` (UNAVAILABLE preemption —
  transient), ``bug`` (non-transient InjectedFamilyBug), ``kill``
  (:class:`KillPoint` — simulated process death, a BaseException the
  quarantine layer deliberately does NOT absorb), ``nan`` (poison the
  metric matrix), ``torn`` (the snapshot writer truncates the
  document mid-serialization — a simulated crash between write and
  rename), ``hang:<seconds>`` (sleep — the deadline test),
  ``burst[:<rows>]`` (an injected arrival spike of ``rows`` phantom
  queued rows — default 256 — that the admission controller treats as
  real backlog draining at the measured rate; caller-handled like
  ``nan``/``torn``).

Activate with the context manager (tests) or ``TX_FAULT_PLAN`` (bench,
reproducing a field failure)::

    with FaultInjector.plan("family:LinearSVC:dispatch:*=oom"):
        selector.fit_arrays(X, y)

Probes are free when no injector is active (one global ``None``
check), so production paths keep the instrumentation permanently.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger(__name__)

__all__ = ["FaultInjector", "maybe_inject", "injector_active",
           "KillPoint", "InjectedFault", "InjectedOom",
           "InjectedPreemption", "InjectedFamilyBug"]


class InjectedFault(Exception):
    """Base for injector-raised exceptions. Messages are shaped so the
    runtime classifier (runtime/errors.py) triages them exactly like
    their real-world counterparts."""


class InjectedOom(InjectedFault):
    def __init__(self, site: str = ""):
        super().__init__(
            f"RESOURCE_EXHAUSTED: out of memory allocating device "
            f"buffer (injected at {site})")


class InjectedPreemption(InjectedFault):
    def __init__(self, site: str = ""):
        super().__init__(
            f"UNAVAILABLE: TPU worker preempted, replica restarting "
            f"(injected at {site})")


class InjectedFamilyBug(InjectedFault):
    def __init__(self, site: str = ""):
        super().__init__(f"injected family fault at {site} "
                         f"(non-transient)")


class KillPoint(BaseException):
    """Simulated process death (VM preempted mid-search, OOM-killer,
    ctrl-C). A ``BaseException`` on purpose: the quarantine layer's
    ``except Exception`` must NOT absorb it — the run dies exactly as
    a real kill would, and only the journal survives."""

    def __init__(self, site: str = ""):
        super().__init__(f"injected kill point at {site}")


@dataclass(frozen=True)
class _Rule:
    scope: str
    name: str        # exact or "*"
    site: str
    nth: Optional[int]   # None = every occurrence
    fault: str   # "oom"|"preempt"|"bug"|"kill"|"nan"|"torn"
    #             |"hang:<s>"|"burst[:<rows>]"


def _parse_plan(text: str) -> List[_Rule]:
    rules: List[_Rule] = []
    for part in filter(None, (p.strip() for p in text.split(","))):
        try:
            spec, fault = part.split("=", 1)
            scope, name, site, n = spec.split(":")
        except ValueError:
            raise ValueError(
                f"bad fault rule {part!r}: expected "
                f"'scope:name:site:n=fault' "
                f"(e.g. 'family:GBTClassifier:dispatch:2=oom')")
        nth = None if n == "*" else int(n)
        if nth is not None and nth < 1:
            raise ValueError(f"bad fault rule {part!r}: n is 1-based")
        rules.append(_Rule(scope, name, site, nth, fault))
    return rules


class FaultInjector:
    """Holds a parsed plan + per-(scope, name, site) occurrence
    counters. Install via the :meth:`plan` context manager or let
    :func:`maybe_inject` pick up ``TX_FAULT_PLAN`` from the
    environment."""

    def __init__(self, plan_text: str):
        self.plan_text = plan_text
        self.rules = _parse_plan(plan_text)
        self._counts: Dict[Tuple[str, str, str], int] = {}
        self._lock = threading.Lock()
        #: fired (rule, occurrence) log, for assertions in tests
        self.fired: List[Tuple[_Rule, int]] = []

    # -- installation ------------------------------------------------------
    @classmethod
    def plan(cls, plan_text: str) -> "FaultInjector":
        return cls(plan_text)

    def __enter__(self) -> "FaultInjector":
        global _ACTIVE
        _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None

    # -- the probe ---------------------------------------------------------
    def check(self, scope: str, name: str, site: str) -> Optional[str]:
        """Count this probe occurrence; fire the first matching rule.
        Raising faults raise; ``nan``/``torn`` return their own name
        for the caller to poison its metrics / tear its write;
        ``hang`` sleeps then returns None."""
        with self._lock:
            key = (scope, name, site)
            self._counts[key] = n = self._counts.get(key, 0) + 1
            rule = next(
                (r for r in self.rules
                 if r.scope == scope and r.site == site
                 and r.name in ("*", name)
                 and (r.nth is None or r.nth == n)), None)
            if rule is None:
                return None
            self.fired.append((rule, n))
        where = f"{scope}:{name}:{site}#{n}"
        _log.warning("fault injector firing %s at %s", rule.fault, where)
        if rule.fault == "oom":
            raise InjectedOom(where)
        if rule.fault == "preempt":
            raise InjectedPreemption(where)
        if rule.fault == "bug":
            raise InjectedFamilyBug(where)
        if rule.fault == "kill":
            raise KillPoint(where)
        if rule.fault == "nan":
            return "nan"
        if rule.fault == "torn":
            return "torn"
        if rule.fault.startswith("hang"):
            _, _, secs = rule.fault.partition(":")
            time.sleep(float(secs or "60"))
            return None
        if rule.fault.startswith("burst"):
            # caller-handled (serving/admission.py): the controller
            # parses the row count and queues a phantom backlog
            return rule.fault
        raise ValueError(f"unknown fault {rule.fault!r} in plan "
                         f"{self.plan_text!r}")


_ACTIVE: Optional[FaultInjector] = None
_ENV_CACHE: Tuple[str, Optional[FaultInjector]] = ("", None)


def _active() -> Optional[FaultInjector]:
    if _ACTIVE is not None:
        return _ACTIVE
    global _ENV_CACHE
    text = os.environ.get("TX_FAULT_PLAN", "")
    if not text:
        return None
    if _ENV_CACHE[0] != text:
        _ENV_CACHE = (text, FaultInjector(text))
    return _ENV_CACHE[1]


def injector_active() -> bool:
    """True when a fault plan is installed (context manager or
    ``TX_FAULT_PLAN``). Lets hot paths skip probe plumbing that is
    only meaningful under a drill — e.g. the fleet router only routes
    its ``hang`` probe through an executor thread when a plan exists."""
    return _active() is not None


def maybe_inject(scope: str, name: str, site: str) -> Optional[str]:
    """The injection-site probe. No-op (returns None) unless an
    injector is active and a rule matches this occurrence."""
    inj = _active()
    if inj is None:
        return None
    return inj.check(scope, name, site)
