"""Search journal: append-only JSONL checkpointing for model search.

Spark's ModelSelector survives worker loss because each task is
restartable from the driver's lineage; our JAX search had no such
ledger — a preempted VM at candidate 140/144 threw away every
completed fold fit. The journal restores restartability at the unit
the TPU search actually dispatches: one **family evaluation** (a
``(family, candidate-subset, rung)`` metric matrix, covering
``len(cands) x folds`` candidate-fold fits).

Properties:

- **Append-only JSONL, fsync'd per record.** A crash can at worst
  truncate the final line; torn tails are detected and dropped on
  replay (a partially-written record re-runs, never mis-parses).
- **Schema-versioned, fingerprint-keyed.** The header pins a SHA-1
  fingerprint over the candidate pool (family class + grid), the
  validator's split protocol (folds/seed/stratify/racing schedule)
  and the training data bytes. A journal only replays into the SAME
  search; anything else is rotated aside as ``.stale``, never
  silently reused.
- **Bit-exact replay.** Metric vectors round-trip through JSON
  ``repr`` (exact for IEEE doubles, NaN included), and every pruning /
  ranking decision downstream of the metrics is deterministic — so a
  resumed search picks the bitwise-identical winner while
  re-dispatching ZERO journaled entries (asserted via
  ``runtime.telemetry.dispatch_log`` in tests/test_resilience.py).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_log = logging.getLogger(__name__)

__all__ = ["SearchJournal", "search_fingerprint", "read_journal",
           "JOURNAL_VERSION", "JOURNAL_NAME"]

JOURNAL_VERSION = 1
JOURNAL_NAME = "search-journal.jsonl"


def search_fingerprint(pool, validator_params: dict,
                       X: np.ndarray, y: np.ndarray) -> str:
    """SHA-1 identity of one search: candidate pool (family class names
    + grids — uids deliberately excluded, they differ across
    processes), validation protocol, and the training arrays' bytes.
    Two runs with the same fingerprint walk the same fold masks, the
    same rung schedule and the same candidate pool, so journaled
    metrics are interchangeable between them."""
    h = hashlib.sha1()
    h.update(f"v{JOURNAL_VERSION}".encode())
    pool_desc = [
        (type(est).__name__,
         json.dumps(list(grid) or [{}], sort_keys=True, default=str))
        for est, grid in pool]
    h.update(json.dumps(pool_desc, sort_keys=True).encode())
    h.update(json.dumps(validator_params, sort_keys=True,
                        default=str).encode())
    X = np.ascontiguousarray(np.asarray(X))
    y = np.ascontiguousarray(np.asarray(y))
    h.update(f"{X.shape}:{X.dtype}:{y.shape}:{y.dtype}".encode())
    h.update(X.tobytes())
    h.update(y.tobytes())
    return h.hexdigest()


def _entry_key(family_key: str, rung_label: str) -> Tuple[str, str]:
    return (family_key, rung_label)


class SearchJournal:
    """One search's ledger under ``<checkpoint_dir>/search-journal
    .jsonl``. Life cycle: ``open(fingerprint)`` -> ``lookup``/
    ``record`` during the search -> ``close()``."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, JOURNAL_NAME)
        self._entries: Dict[Tuple[str, str], dict] = {}
        self._fh = None
        self._lock = threading.Lock()
        self.fingerprint: Optional[str] = None
        #: entries replayed from disk at open() (resume telemetry)
        self.replayed = 0
        #: mesh topology of the run that WROTE the header (metadata,
        #: deliberately outside the fingerprint — see open())
        self.recorded_topology: Optional[dict] = None

    # -- lifecycle ---------------------------------------------------------
    def open(self, fingerprint: str,
             topology: Optional[dict] = None) -> "SearchJournal":
        """``topology`` describes the mesh this run searches on, e.g.
        ``{"devices": 8, "mesh": {"models": 8, "data": 1}}``. It is
        recorded in the header as METADATA and deliberately excluded
        from the fingerprint: metric matrices are device-count-invariant
        (candidate-axis sharding never changes a candidate's
        arithmetic), so a journal written on a 2-chip mesh legally
        resumes on an 8-chip one — the resumed search replays the same
        metrics and picks the bitwise-identical winner
        (tests/test_sharded_search.py asserts exactly this)."""
        os.makedirs(self.directory, exist_ok=True)
        self.fingerprint = fingerprint
        existing, header = self._read_existing()
        if header is not None and header.get("fingerprint") != fingerprint:
            stale = self.path + ".stale"
            _log.warning(
                "journal at %s was written by a different search "
                "(fingerprint %s != %s); rotating it to %s and starting "
                "fresh", self.path,
                (header.get("fingerprint") or "?")[:12], fingerprint[:12],
                stale)
            os.replace(self.path, stale)
            existing = []
            header = None
        if header is not None:
            self.recorded_topology = header.get("topology")
            if topology is not None and self.recorded_topology is not None \
                    and self.recorded_topology != topology:
                _log.info(
                    "journal %s was recorded on topology %s; resuming on "
                    "%s — metric matrices are device-count-invariant, so "
                    "the resumed search replays them unchanged",
                    self.path, self.recorded_topology, topology)
        self._entries = {
            _entry_key(e["family"], e["rung"]): e for e in existing}
        self.replayed = len(self._entries)
        self._fh = open(self.path, "a", encoding="utf-8")
        if header is None:
            head = {"kind": "header", "v": JOURNAL_VERSION,
                    "fingerprint": fingerprint}
            if topology is not None:
                head["topology"] = topology
            self.recorded_topology = topology
            self._write_line(head)
        return self

    def _read_existing(self):
        """(entries, header) from disk; a torn final line (crash mid-
        append) is dropped, and a journal from a NEWER schema is
        refused rather than mis-replayed."""
        if not os.path.exists(self.path):
            return [], None
        header, entries = None, []
        with open(self.path, encoding="utf-8") as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    _log.warning("journal %s: dropping torn record at "
                                 "line %d (crash mid-append)",
                                 self.path, i + 1)
                    break
                if rec.get("kind") == "header":
                    if rec.get("v", 0) > JOURNAL_VERSION:
                        raise ValueError(
                            f"journal {self.path} uses schema v{rec['v']}; "
                            f"this build reads up to v{JOURNAL_VERSION}")
                    header = rec
                elif rec.get("kind") == "eval":
                    entries.append(rec)
        return entries, header

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- records -----------------------------------------------------------
    def _write_line(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def record(self, family_key: str, rung_label: str,
               cands: Sequence[int], metrics: Sequence[Sequence[float]],
               folds: int) -> None:
        """Append one completed family evaluation: ``metrics[i]`` is
        candidate ``cands[i]``'s per-fold metric vector. Fsync'd before
        returning — once ``record`` returns, a kill cannot lose the
        work."""
        rec = {"kind": "eval", "family": family_key, "rung": rung_label,
               "cands": [int(c) for c in cands],
               "metrics": [[float(v) for v in row] for row in metrics],
               "folds": int(folds)}
        with self._lock:
            if self._fh is None:
                raise RuntimeError("journal is not open")
            self._entries[_entry_key(family_key, rung_label)] = rec
            self._write_line(rec)

    def lookup(self, family_key: str, rung_label: str,
               cands: Sequence[int]
               ) -> Optional[List[List[float]]]:
        """The journaled per-candidate metric vectors for this exact
        (family, rung, candidate-subset) — None when absent or when the
        candidate subset disagrees (a half-changed search must re-run,
        not mis-replay)."""
        with self._lock:
            rec = self._entries.get(_entry_key(family_key, rung_label))
        if rec is None:
            return None
        if [int(c) for c in cands] != rec["cands"]:
            return None
        return [list(row) for row in rec["metrics"]]

    def entries(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._entries.values()]


def read_journal(directory: str) -> dict:
    """Inspection summary of a checkpoint dir (the ``tx journal`` CLI):
    header, entry rows, and the fold-fit equivalents a resume would
    skip."""
    path = os.path.join(directory, JOURNAL_NAME)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no {JOURNAL_NAME} under {directory!r} — not a search "
            f"checkpoint directory")
    j = SearchJournal(directory)
    entries, header = j._read_existing()
    saved = sum(len(e["cands"]) * e["folds"] for e in entries)
    return {
        "path": path,
        "fingerprint": (header or {}).get("fingerprint"),
        "version": (header or {}).get("v"),
        # mesh topology of the writing run (metadata only: a resume on
        # a different device count replays the same metrics —
        # docs/distributed.md)
        "recordedTopology": (header or {}).get("topology"),
        "entries": entries,
        "families": sorted({e["family"] for e in entries}),
        "rungs": sorted({e["rung"] for e in entries}),
        "resumeSavedFoldFits": saved,
    }
