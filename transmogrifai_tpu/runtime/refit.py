"""Warm-start refit: retrain a fitted model in the background.

The serving lifecycle manager (serving/lifecycle.py) needs to turn a
FITTED model plus a window of recent live traffic back into a fresh
``Workflow.train()`` — off the event loop, bounded by a wall-clock
budget, under the same retry/quarantine runtime as the original search,
and journal-resumed through the PR-4 ``resume_from`` machinery when the
workflow carries a ``ModelSelector``. This module is that bridge:

- :func:`rebuild_training_workflow` reconstructs a trainable workflow
  from a fitted model generically: every fitted ``Model`` stage is
  swapped back for a fresh instance of the estimator class that
  produced it (``parent_estimator_class``, wired by
  ``Estimator._wire_model``), matched by uid via
  ``Feature.copy_with_new_stages``. Hyperparameters that survive on the
  fitted model's captured constructor args are carried over; the rest
  fall back to the estimator's defaults. When reconstruction is
  impossible the error says so (:class:`RefitUnavailableError`) instead
  of training garbage.
- :func:`run_refit` merges a base training set with the LABELED slice
  of the live window, trains under a :class:`~.retry.RetryPolicy`
  (transient failures retry, everything else propagates to the caller's
  quarantine layer), enforces the wall-clock budget by abandoning the
  training thread at the deadline (the selector's orphaning idiom), and
  passes ``resume_from`` only when there is actually a search to
  resume.

Deterministic drills: ``TX_FAULT_PLAN="lifecycle:<model>:retrain:..."``
injects at the top of every training attempt (runtime/faults.py).
"""
from __future__ import annotations

import concurrent.futures as _cf
import inspect
import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from .faults import maybe_inject
from .retry import RetryPolicy

_log = logging.getLogger(__name__)

__all__ = ["RefitSpec", "RefitResult", "RefitUnavailableError",
           "RefitBudgetExceeded", "rebuild_training_workflow",
           "labeled_rows", "run_refit"]


class RefitUnavailableError(RuntimeError):
    """The model cannot be retrained from what we have — no trainable
    workflow can be reconstructed, or there are no labeled rows."""


class RefitBudgetExceeded(RuntimeError):
    """The retrain overran its wall-clock budget; the training thread
    was abandoned and the candidate discarded (old model keeps
    serving)."""


@dataclass
class RefitSpec:
    """How to retrain one registered model.

    ``workflow_factory`` returns a FRESH unfitted workflow (the exact
    estimators + hyperparameters — the high-fidelity path, used by
    ``ServingServer.register_refit``). Without one, the workflow is
    reconstructed generically from the fitted model. ``base_records``
    are merged with the labeled live window so a small drift ring does
    not starve the fit; ``checkpoint_dir`` points the search journal at
    a directory so a repeated/crashed refit warm-starts; ``save_dir``
    persists the accepted candidate atomically (workflow/persistence)."""
    workflow_factory: Optional[Callable[[], Any]] = None
    base_records: Optional[List[dict]] = None
    checkpoint_dir: Optional[str] = None
    save_dir: Optional[str] = None
    validate: str = "off"


@dataclass
class RefitResult:
    model: Any
    #: wall-clock train seconds (inside the budget)
    seconds: float
    #: rows the candidate was trained on (base + labeled live window)
    rows: int
    #: True when the train actually passed ``resume_from`` (a
    #: ModelSelector was present to replay the journal)
    resumed: bool
    journal_dir: Optional[str] = None


def rebuild_training_workflow(model) -> Any:
    """A trainable ``Workflow`` reconstructed from a fitted model:
    fitted stages swap back to fresh estimators by uid. Raises
    :class:`RefitUnavailableError` when any fitted stage's estimator
    class cannot be resolved or constructed."""
    from ..stages.base import stage_class_by_name
    from ..workflow.workflow import Workflow
    stage_map: Dict[str, Any] = {}
    for s in model.stages():
        parent = getattr(s, "parent_estimator_class", None)
        if not parent:
            continue
        try:
            cls = stage_class_by_name(parent)
        except KeyError as e:
            raise RefitUnavailableError(
                f"fitted stage {s!r} came from unknown estimator class "
                f"{parent!r}; supply RefitSpec.workflow_factory") from e
        params = dict(getattr(s, "get_params", dict)() or {})
        try:
            sig = inspect.signature(cls.__init__)
        except (TypeError, ValueError):  # pragma: no cover
            sig = None
        kwargs = {}
        if sig is not None:
            has_var_kw = any(
                p.kind == inspect.Parameter.VAR_KEYWORD
                for p in sig.parameters.values())
            kwargs = {k: v for k, v in params.items()
                      if k != "uid" and (has_var_kw
                                         or k in sig.parameters)}
            if "uid" in sig.parameters:
                kwargs["uid"] = s.uid
        try:
            est = cls(**kwargs)
        except TypeError as e:
            raise RefitUnavailableError(
                f"estimator {parent}({', '.join(sorted(kwargs))}) could "
                f"not be reconstructed for stage {s.uid}: {e}; supply "
                f"RefitSpec.workflow_factory") from e
        est.uid = s.uid
        stage_map[s.uid] = est
    if not stage_map:
        raise RefitUnavailableError(
            "model has no fitted estimator stages — nothing to refit")
    result = tuple(f.copy_with_new_stages(stage_map)
                   for f in model.result_features)
    return Workflow().set_result_features(*result)


def labeled_rows(model, records: Sequence[dict]) -> List[dict]:
    """The slice of ``records`` that carries every response feature
    (a retrain can only learn from labeled traffic)."""
    responses = [f.name for f in model.raw_features() if f.is_response]
    if not responses:
        return [dict(r) for r in records]
    return [dict(r) for r in records
            if isinstance(r, dict)
            and all(r.get(name) is not None for name in responses)]


def run_refit(model, live_records: Sequence[dict],
              spec: Optional[RefitSpec] = None,
              budget_seconds: Optional[float] = None,
              name: str = "model",
              retry: Optional[RetryPolicy] = None,
              generation: int = 0) -> RefitResult:
    """Train a candidate replacement for ``model``. Blocking — run it
    on the lifecycle worker, never on the event loop. Raises on
    failure (retries exhausted, budget exceeded, reconstruction
    impossible); the CALLER decides what failure means (the lifecycle
    manager quarantines and keeps serving the old model)."""
    spec = spec or RefitSpec()
    retry = retry or RetryPolicy.from_env()
    t0 = time.monotonic()
    records = [dict(r) for r in (spec.base_records or [])]
    records += labeled_rows(model, live_records)
    if not records:
        raise RefitUnavailableError(
            f"refit of {name!r} has no labeled rows (live window of "
            f"{len(live_records)} rows carries no responses and no "
            f"base_records were registered)")
    resumed = {"v": False}

    def train_once():
        # the deterministic drill site: lifecycle:<model>:retrain
        maybe_inject("lifecycle", name, "retrain")
        if spec.workflow_factory is not None:
            wf = spec.workflow_factory()
        else:
            wf = rebuild_training_workflow(model)
        wf.set_input_records([dict(r) for r in records])
        resume = None
        if spec.checkpoint_dir:
            from ..selector.selector import ModelSelector
            if any(isinstance(s, ModelSelector) for s in wf.stages()):
                resume = spec.checkpoint_dir
        resumed["v"] = resume is not None
        return wf.train(validate=spec.validate, resume_from=resume)

    def attempt():
        return retry.call(train_once, description=f"refit:{name}")

    if budget_seconds is None:
        candidate = attempt()
    else:
        # budget enforcement mirrors the device-deadline idiom: the
        # training thread is ABANDONED at the deadline (it may be deep
        # inside a fit), the candidate discarded
        pool = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tx-refit")
        fut = pool.submit(attempt)
        try:
            candidate = fut.result(timeout=budget_seconds)
        except _cf.TimeoutError:
            raise RefitBudgetExceeded(
                f"refit of {name!r} exceeded its "
                f"{budget_seconds}s wall-clock budget; training thread "
                f"abandoned, old model keeps serving") from None
        finally:
            pool.shutdown(wait=False)
    candidate.trained_generation = generation
    if spec.save_dir:
        from ..workflow.persistence import save_model
        save_model(candidate, spec.save_dir)
    return RefitResult(model=candidate,
                       seconds=time.monotonic() - t0,
                       rows=len(records), resumed=resumed["v"],
                       journal_dir=spec.checkpoint_dir)
