"""RetryPolicy: exponential backoff + deterministic jitter for
transient backend failures.

Wraps the two places a TPU search actually dies in production —
per-family dispatch (selector/validator.py) and compiled-program
dispatch (serving/plan.py) — with the classic preemption playbook:
classify the error (runtime/errors.py), retry transient shapes with
exponentially growing, jittered delays, and hand anything persistent
to the quarantine layer instead of looping forever.

Jitter is DETERMINISTIC (seeded from the policy seed + the call
description + the attempt index): resumed searches must replay
bit-identically, so nothing in the runtime may consult a wall-clock
or OS entropy source for a decision — only for waiting.
"""
from __future__ import annotations

import logging
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Optional

from . import telemetry
from .errors import TRANSIENT, classify_error

_log = logging.getLogger(__name__)

__all__ = ["RetryPolicy"]


@dataclass(frozen=True)
class RetryPolicy:
    """``call(fn)`` runs ``fn`` up to ``max_attempts`` times, sleeping
    ``base_delay * multiplier**attempt`` (capped at ``max_delay``,
    +/- ``jitter`` fraction) between attempts. Only errors the
    classifier marks ``"transient"`` are retried; everything else
    propagates to the caller (which quarantines or crashes as its
    contract demands)."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25
    seed: int = 0

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Policy from ``TX_RETRY_*`` env knobs (docs/resilience.md):
        ``TX_RETRY_MAX_ATTEMPTS``, ``TX_RETRY_BASE_DELAY_S``,
        ``TX_RETRY_MAX_DELAY_S``."""
        import os
        return cls(
            max_attempts=int(os.environ.get("TX_RETRY_MAX_ATTEMPTS", "3")),
            base_delay=float(os.environ.get("TX_RETRY_BASE_DELAY_S",
                                            "0.05")),
            max_delay=float(os.environ.get("TX_RETRY_MAX_DELAY_S", "2.0")))

    def delay_for(self, attempt: int, description: str = "") -> float:
        """Backoff delay before retry ``attempt`` (0-based), with the
        deterministic jitter derived from (seed, description,
        attempt)."""
        d = min(self.max_delay,
                self.base_delay * (self.multiplier ** attempt))
        if self.jitter:
            h = zlib.crc32(f"{self.seed}:{description}:{attempt}"
                           .encode()) / 0xFFFFFFFF
            d *= 1.0 + self.jitter * (2.0 * h - 1.0)
        return max(0.0, d)

    def call(self, fn: Callable, description: str = "",
             classify: Callable = classify_error,
             on_retry: Optional[Callable] = None):
        """Run ``fn()`` under the policy. ``on_retry(attempt, exc)``
        fires before each backoff sleep. The LAST transient error is
        re-raised once attempts are exhausted — the caller's
        quarantine layer records it."""
        attempts = max(1, int(self.max_attempts))
        for attempt in range(attempts):
            try:
                return fn()
            except Exception as e:
                if classify(e) != TRANSIENT or attempt == attempts - 1:
                    raise
                telemetry.count("retries")
                telemetry.event("retry", target=description or "call",
                                attempt=attempt + 1,
                                error=f"{type(e).__name__}: {e}")
                if on_retry is not None:
                    on_retry(attempt, e)
                time.sleep(self.delay_for(attempt, description))
        raise AssertionError("unreachable")  # pragma: no cover
