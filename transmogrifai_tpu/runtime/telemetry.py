"""Process-wide fault-tolerance telemetry: counters, dispatch log,
event stream.

The same idiom as ``serving.plan_compiles()`` / ``racing
.search_compiles()``: module-level accumulators that bench.py and the
resilience tests read to prove runtime behavior (zero re-dispatch of
journaled work, retry counts, quarantine counts) rather than infer it
from timing. ``WorkflowListener`` snapshots the event stream into
``AppMetrics.fault_events`` so one training run's retries and
quarantines land next to its stage profile.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Tuple

_log = logging.getLogger(__name__)

__all__ = ["count", "counters", "reset", "note_dispatch", "dispatch_log",
           "event", "events_mark", "events_since"]

_LOCK = threading.Lock()
_COUNTERS: Dict[str, int] = {}
#: every ACTUAL family dispatch of this process:
#: (family, rung_label, cand_indices, folds) — the unit the resume
#: acceptance gate asserts over ("zero re-dispatch of journaled
#: (family, cand, fold) entries")
_DISPATCH_LOG: List[Tuple[str, str, Tuple[int, ...], int]] = []
_EVENTS: List[dict] = []


def count(name: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def counters() -> Dict[str, int]:
    """Snapshot of all counters (``retries``, ``quarantines``,
    ``journal_hits``, ``journal_replayed_entries``,
    ``candidate_fold_dispatches``, ``family_dispatches``, ...)."""
    with _LOCK:
        return dict(_COUNTERS)


def note_dispatch(family: str, rung_label: str,
                  cands: Tuple[int, ...], folds: int) -> None:
    """Record one REAL family dispatch (journal replays never land
    here) of ``len(cands) x folds`` candidate-fold evaluations."""
    with _LOCK:
        _DISPATCH_LOG.append((family, rung_label, tuple(cands),
                              int(folds)))
        _COUNTERS["family_dispatches"] = \
            _COUNTERS.get("family_dispatches", 0) + 1
        _COUNTERS["candidate_fold_dispatches"] = \
            _COUNTERS.get("candidate_fold_dispatches", 0) \
            + len(cands) * int(folds)


def dispatch_log() -> List[Tuple[str, str, Tuple[int, ...], int]]:
    with _LOCK:
        return list(_DISPATCH_LOG)


def event(event_name: str, **fields) -> None:
    """Append one fault event (``retry`` / ``quarantine`` /
    ``journal_resume`` / ``plan_fallback`` / ...) and log it — the
    runtime degrades LOUDLY, never silently."""
    rec = {"event": event_name, **fields}
    with _LOCK:
        _EVENTS.append(rec)
    _log.warning("runtime: %s %s", event_name,
                 " ".join(f"{k}={v}" for k, v in fields.items()))


def events_mark() -> int:
    with _LOCK:
        return len(_EVENTS)


def events_since(mark: int) -> List[dict]:
    with _LOCK:
        return [dict(e) for e in _EVENTS[mark:]]


def reset() -> None:
    """Zero every accumulator (tests / bench isolation)."""
    with _LOCK:
        _COUNTERS.clear()
        _DISPATCH_LOG.clear()
        _EVENTS.clear()
