"""Process-wide fault-tolerance telemetry: counters, dispatch log,
event stream.

The same idiom as ``serving.plan_compiles()`` / ``racing
.search_compiles()``: module-level accumulators that bench.py and the
resilience tests read to prove runtime behavior (zero re-dispatch of
journaled work, retry counts, quarantine counts) rather than infer it
from timing. ``WorkflowListener`` snapshots the event stream into
``AppMetrics.fault_events`` so one training run's retries and
quarantines land next to its stage profile.
"""
from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Dict, List, Tuple

from ..observability import trace as _trace

_log = logging.getLogger(__name__)

__all__ = ["count", "counters", "reset", "note_dispatch", "dispatch_log",
           "event", "events_mark", "events_since", "events_dropped",
           "OVERFLOW_EVENT"]

_LOCK = threading.Lock()
_COUNTERS: Dict[str, int] = {}
#: every ACTUAL family dispatch of this process:
#: (family, rung_label, cand_indices, folds) — the unit the resume
#: acceptance gate asserts over ("zero re-dispatch of journaled
#: (family, cand, fold) entries")
_DISPATCH_LOG: List[Tuple[str, str, Tuple[int, ...], int]] = []
#: the event stream is a RING: a long-running `tx serve` process emits
#: events forever, so the in-process list is bounded
#: (``TX_TELEMETRY_EVENTS_CAP``, default 4096) — overflow drops the
#: OLDEST events, counts them (``telemetry_events_dropped``), and
#: ``events_since`` marks the gap with an explicit overflow record
_EVENTS: "deque[dict]" = deque()
#: absolute stream index of _EVENTS[0] (how many events were dropped
#: off the front so far) — events_mark()/events_since() marks are
#: absolute stream positions, so they stay valid across overflow
_EVENTS_BASE = 0

#: the synthetic record events_since() prepends when its mark fell off
#: the ring
OVERFLOW_EVENT = "telemetry_events_overflow"


def _events_cap() -> int:
    """Env-tunable ring capacity (re-read per event so tests and a
    live process can retune without reimport)."""
    try:
        return max(16, int(os.environ.get("TX_TELEMETRY_EVENTS_CAP",
                                          "4096")))
    except ValueError:
        return 4096


def count(name: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def counters() -> Dict[str, int]:
    """Snapshot of all counters (``retries``, ``quarantines``,
    ``journal_hits``, ``journal_replayed_entries``,
    ``candidate_fold_dispatches``, ``family_dispatches``, ...)."""
    with _LOCK:
        return dict(_COUNTERS)


def note_dispatch(family: str, rung_label: str,
                  cands: Tuple[int, ...], folds: int) -> None:
    """Record one REAL family dispatch (journal replays never land
    here) of ``len(cands) x folds`` candidate-fold evaluations."""
    with _LOCK:
        _DISPATCH_LOG.append((family, rung_label, tuple(cands),
                              int(folds)))
        _COUNTERS["family_dispatches"] = \
            _COUNTERS.get("family_dispatches", 0) + 1
        _COUNTERS["candidate_fold_dispatches"] = \
            _COUNTERS.get("candidate_fold_dispatches", 0) \
            + len(cands) * int(folds)


def dispatch_log() -> List[Tuple[str, str, Tuple[int, ...], int]]:
    with _LOCK:
        return list(_DISPATCH_LOG)


def event(event_name: str, **fields) -> None:
    """Append one fault event (``retry`` / ``quarantine`` /
    ``journal_resume`` / ``plan_fallback`` / ...) and log it — the
    runtime degrades LOUDLY, never silently. With tracing enabled the
    event ALSO attaches to the current span (observability/trace.py),
    so a retry/quarantine lands inside the dispatch that suffered it."""
    global _EVENTS_BASE
    rec = {"event": event_name, **fields}
    with _LOCK:
        _EVENTS.append(rec)
        cap = _events_cap()
        while len(_EVENTS) > cap:
            _EVENTS.popleft()
            _EVENTS_BASE += 1
            _COUNTERS["telemetry_events_dropped"] = \
                _COUNTERS.get("telemetry_events_dropped", 0) + 1
    if _trace.enabled():
        _trace.add_event(event_name, **fields)
    _log.warning("runtime: %s %s", event_name,
                 " ".join(f"{k}={v}" for k, v in fields.items()))


def events_mark() -> int:
    """Absolute position in the event stream (events emitted so far) —
    stable across ring overflow."""
    with _LOCK:
        return _EVENTS_BASE + len(_EVENTS)


def events_since(mark: int) -> List[dict]:
    """Events from ``mark`` on. If the ring dropped events past the
    mark, the FIRST returned record is an explicit
    ``{"event": OVERFLOW_EVENT, "dropped": n}`` marker — consumers see
    the gap instead of a silently shortened history."""
    with _LOCK:
        if mark >= _EVENTS_BASE:
            start = mark - _EVENTS_BASE
            return [dict(e) for e in list(_EVENTS)[start:]]
        out: List[dict] = [{"event": OVERFLOW_EVENT,
                            "dropped": _EVENTS_BASE - mark}]
        out.extend(dict(e) for e in _EVENTS)
        return out


def events_dropped() -> int:
    """Events lost to ring overflow so far in this process."""
    with _LOCK:
        return _COUNTERS.get("telemetry_events_dropped", 0)


def reset() -> None:
    """Zero every accumulator (tests / bench isolation)."""
    global _EVENTS_BASE
    with _LOCK:
        _COUNTERS.clear()
        _DISPATCH_LOG.clear()
        _EVENTS.clear()
        _EVENTS_BASE = 0
