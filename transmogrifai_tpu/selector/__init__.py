"""Model selection & tuning (SURVEY §2.7; core/.../selector/
ModelSelector.scala:74 — the north-star TPU-acceleration target)."""
from .factories import (BinaryClassificationModelSelector,
                        MultiClassificationModelSelector,
                        RegressionModelSelector)
from .racing import RacingCrossValidation, search_compiles
from .random_params import RandomParamBuilder
from .selector import ModelSelector, ModelSelectorSummary, SelectedModel
from .splitters import (DataBalancer, DataCutter, DataSplitter, Splitter,
                        SplitterSummary)
from .validator import (BestEstimator, CrossValidation,
                        TrainValidationSplit, ValidationResult)

__all__ = [
    "ModelSelector", "ModelSelectorSummary", "SelectedModel",
    "BinaryClassificationModelSelector", "MultiClassificationModelSelector",
    "RegressionModelSelector",
    "Splitter", "SplitterSummary", "DataSplitter", "DataBalancer",
    "DataCutter",
    "CrossValidation", "TrainValidationSplit", "BestEstimator",
    "ValidationResult", "RandomParamBuilder",
    "RacingCrossValidation", "search_compiles",
]
