"""Problem-typed selector factories with default model grids.

TPU-native ports of the reference factories
(core/src/main/scala/com/salesforce/op/stages/impl/classification/
BinaryClassificationModelSelector.scala:47, MultiClassificationModelSelector
.scala:47, .../regression/RegressionModelSelector.scala:47, default grids
DefaultSelectorParams.scala:38-60). Model families appear in the default
pool as they land in the zoo; ``model_types_to_use`` narrows the pool the
same way the reference's ``modelTypesToUse`` does.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Type

from ..evaluators import (BinaryClassificationEvaluator, Evaluator,
                          MultiClassificationEvaluator, RegressionEvaluator)
from ..models import Predictor
from .selector import ModelSelector
from .splitters import DataBalancer, DataCutter, DataSplitter, Splitter
from .validator import CrossValidation, TrainValidationSplit

__all__ = ["BinaryClassificationModelSelector",
           "MultiClassificationModelSelector", "RegressionModelSelector"]


def _default_binary_models() -> List[Tuple[Predictor, List[Dict]]]:
    """(reference defaultModelsToUse = LR/RF/GBT/SVC,
    BinaryClassificationModelSelector.scala:57-60; grids follow
    DefaultSelectorParams — see models/registry.py)"""
    from ..models import registry
    return registry.default_binary_models()


def _binary_opt_in_models() -> List[Tuple[Predictor, List[Dict]]]:
    from ..models import registry
    return registry.default_binary_extra_models()


def _default_multiclass_models() -> List[Tuple[Predictor, List[Dict]]]:
    from ..models import registry
    return registry.default_multiclass_models()


def _multiclass_opt_in_models() -> List[Tuple[Predictor, List[Dict]]]:
    from ..models import registry
    return registry.default_multiclass_extra_models()


def _default_regression_models() -> List[Tuple[Predictor, List[Dict]]]:
    from ..models import registry
    return registry.default_regression_models()


def _regression_opt_in_models() -> List[Tuple[Predictor, List[Dict]]]:
    from ..models import registry
    return registry.default_regression_extra_models()


def _filter_models(models, opt_in_models, model_types_to_use):
    """No filter -> the default pool; with ``model_types_to_use`` pick
    from default + opt-in families (reference modelTypesToUse selects
    among the full modelsAndParams set)."""
    if model_types_to_use is None:
        return models
    allowed = {t.__name__ if isinstance(t, type) else str(t)
               for t in model_types_to_use}
    pool = list(models) + list(opt_in_models)
    kept = [(est, grid) for est, grid in pool
            if type(est).__name__ in allowed]
    if not kept:
        raise ValueError(f"No candidate models left after filtering to "
                         f"{sorted(allowed)}")
    return kept


class _SelectorFactory:
    problem_type = ""
    default_evaluator: Type[Evaluator] = Evaluator
    default_splitter: Type[Splitter] = Splitter

    @classmethod
    def _default_models(cls):
        raise NotImplementedError

    @classmethod
    def _opt_in_models(cls):
        return []

    @classmethod
    def _pool(cls, models, model_types_to_use):
        if models is not None:
            return _filter_models(list(models), [], model_types_to_use)
        return _filter_models(cls._default_models(), cls._opt_in_models(),
                              model_types_to_use)

    @classmethod
    def with_cross_validation(cls, num_folds: int = 3, seed: int = 42,
                              evaluator: Optional[Evaluator] = None,
                              splitter: Optional[Splitter] = None,
                              models: Optional[Sequence] = None,
                              model_types_to_use: Optional[Sequence] = None,
                              stratify: bool = False,
                              validation: str = "exact",
                              eta: Optional[int] = None,
                              min_fidelity: Optional[float] = None,
                              mesh="auto") -> ModelSelector:
        """(reference withCrossValidation:159; ``mesh`` shards the
        fold x grid candidate axis over chips — the default ``"auto"``
        resolves a mesh over every visible device at search time,
        ``None`` forces the local path; parallel/cv.resolve_search_mesh
        and docs/distributed.md).

        ``validation="racing"`` switches the search to multi-fidelity
        successive halving (docs/selection.md): the candidate pool is
        screened at low fidelity and only the top ``1/eta`` per rung
        trains on. The final rung is exact full CV for the survivors;
        ``min_fidelity`` sets the first rung's budget fraction
        (default ``1/eta**2``)."""
        ev = evaluator or cls.default_evaluator()
        return ModelSelector(
            models=cls._pool(models, model_types_to_use),
            validator=CrossValidation(ev, num_folds=num_folds, seed=seed,
                                      stratify=stratify, mesh=mesh),
            splitter=(splitter if splitter is not None
                      else cls.default_splitter(seed=seed)),
            validation=validation, eta=eta, min_fidelity=min_fidelity,
            problem_type=cls.problem_type)

    @classmethod
    def with_train_validation_split(cls, train_ratio: float = 0.75,
                                    seed: int = 42,
                                    evaluator: Optional[Evaluator] = None,
                                    splitter: Optional[Splitter] = None,
                                    models: Optional[Sequence] = None,
                                    model_types_to_use: Optional[Sequence]
                                    = None,
                                    stratify: bool = False,
                                    mesh="auto") -> ModelSelector:
        ev = evaluator or cls.default_evaluator()
        return ModelSelector(
            models=cls._pool(models, model_types_to_use),
            validator=TrainValidationSplit(ev, train_ratio=train_ratio,
                                           seed=seed, stratify=stratify,
                                           mesh=mesh),
            splitter=(splitter if splitter is not None
                      else cls.default_splitter(seed=seed)),
            problem_type=cls.problem_type)


class BinaryClassificationModelSelector(_SelectorFactory):
    problem_type = "BinaryClassification"
    default_evaluator = BinaryClassificationEvaluator
    default_splitter = DataBalancer

    @classmethod
    def _default_models(cls):
        return _default_binary_models()

    @classmethod
    def _opt_in_models(cls):
        return _binary_opt_in_models()


class MultiClassificationModelSelector(_SelectorFactory):
    problem_type = "MultiClassification"
    default_evaluator = MultiClassificationEvaluator
    default_splitter = DataCutter

    @classmethod
    def _default_models(cls):
        return _default_multiclass_models()

    @classmethod
    def _opt_in_models(cls):
        return _multiclass_opt_in_models()


class RegressionModelSelector(_SelectorFactory):
    problem_type = "Regression"
    default_evaluator = RegressionEvaluator
    default_splitter = DataSplitter

    @classmethod
    def _default_models(cls):
        return _default_regression_models()

    @classmethod
    def _opt_in_models(cls):
        return _regression_opt_in_models()
