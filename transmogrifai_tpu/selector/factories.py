"""Problem-typed selector factories with default model grids.

TPU-native ports of the reference factories
(core/src/main/scala/com/salesforce/op/stages/impl/classification/
BinaryClassificationModelSelector.scala:47, MultiClassificationModelSelector
.scala:47, .../regression/RegressionModelSelector.scala:47, default grids
DefaultSelectorParams.scala:38-60). Model families appear in the default
pool as they land in the zoo; ``model_types_to_use`` narrows the pool the
same way the reference's ``modelTypesToUse`` does.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Type

from ..evaluators import (BinaryClassificationEvaluator, Evaluator,
                          MultiClassificationEvaluator, RegressionEvaluator)
from ..models import (LinearRegression, LinearSVC, LogisticRegression,
                      Predictor)
from .selector import ModelSelector
from .splitters import DataBalancer, DataCutter, DataSplitter, Splitter
from .validator import CrossValidation, TrainValidationSplit

__all__ = ["BinaryClassificationModelSelector",
           "MultiClassificationModelSelector", "RegressionModelSelector"]


def _default_binary_models() -> List[Tuple[Predictor, List[Dict]]]:
    """(reference BinaryClassificationModelSelector defaults :68-128;
    grids follow DefaultSelectorParams)"""
    from ..models import registry
    models: List[Tuple[Predictor, List[Dict]]] = [
        (LogisticRegression(),
         [{"reg_param": r, "elastic_net_param": e}
          for r in (0.01, 0.1, 0.2) for e in (0.0, 0.5)]),
        (LinearSVC(), [{"reg_param": r} for r in (0.01, 0.1)]),
    ]
    models.extend(registry.default_binary_extra_models())
    return models


def _default_multiclass_models() -> List[Tuple[Predictor, List[Dict]]]:
    from ..models import registry
    models: List[Tuple[Predictor, List[Dict]]] = [
        (LogisticRegression(),
         [{"reg_param": r, "elastic_net_param": e}
          for r in (0.01, 0.1, 0.2) for e in (0.0, 0.5)]),
    ]
    models.extend(registry.default_multiclass_extra_models())
    return models


def _default_regression_models() -> List[Tuple[Predictor, List[Dict]]]:
    from ..models import registry
    models: List[Tuple[Predictor, List[Dict]]] = [
        (LinearRegression(),
         [{"reg_param": r, "elastic_net_param": e}
          for r in (0.001, 0.01, 0.1) for e in (0.0, 0.5)]),
    ]
    models.extend(registry.default_regression_extra_models())
    return models


def _filter_models(models, model_types_to_use):
    if model_types_to_use is None:
        return models
    allowed = {t.__name__ if isinstance(t, type) else str(t)
               for t in model_types_to_use}
    kept = [(est, grid) for est, grid in models
            if type(est).__name__ in allowed]
    if not kept:
        raise ValueError(f"No candidate models left after filtering to "
                         f"{sorted(allowed)}")
    return kept


class _SelectorFactory:
    problem_type = ""
    default_evaluator: Type[Evaluator] = Evaluator
    default_splitter: Type[Splitter] = Splitter

    @classmethod
    def _default_models(cls):
        raise NotImplementedError

    @classmethod
    def with_cross_validation(cls, num_folds: int = 3, seed: int = 42,
                              evaluator: Optional[Evaluator] = None,
                              splitter: Optional[Splitter] = None,
                              models: Optional[Sequence] = None,
                              model_types_to_use: Optional[Sequence] = None,
                              stratify: bool = False) -> ModelSelector:
        """(reference withCrossValidation:159)"""
        ev = evaluator or cls.default_evaluator()
        return ModelSelector(
            models=_filter_models(list(models or cls._default_models()),
                                  model_types_to_use),
            validator=CrossValidation(ev, num_folds=num_folds, seed=seed,
                                      stratify=stratify),
            splitter=(splitter if splitter is not None
                      else cls.default_splitter(seed=seed)),
            problem_type=cls.problem_type)

    @classmethod
    def with_train_validation_split(cls, train_ratio: float = 0.75,
                                    seed: int = 42,
                                    evaluator: Optional[Evaluator] = None,
                                    splitter: Optional[Splitter] = None,
                                    models: Optional[Sequence] = None,
                                    model_types_to_use: Optional[Sequence]
                                    = None,
                                    stratify: bool = False) -> ModelSelector:
        ev = evaluator or cls.default_evaluator()
        return ModelSelector(
            models=_filter_models(list(models or cls._default_models()),
                                  model_types_to_use),
            validator=TrainValidationSplit(ev, train_ratio=train_ratio,
                                           seed=seed, stratify=stratify),
            splitter=(splitter if splitter is not None
                      else cls.default_splitter(seed=seed)),
            problem_type=cls.problem_type)


class BinaryClassificationModelSelector(_SelectorFactory):
    problem_type = "BinaryClassification"
    default_evaluator = BinaryClassificationEvaluator
    default_splitter = DataBalancer

    @classmethod
    def _default_models(cls):
        return _default_binary_models()


class MultiClassificationModelSelector(_SelectorFactory):
    problem_type = "MultiClassification"
    default_evaluator = MultiClassificationEvaluator
    default_splitter = DataCutter

    @classmethod
    def _default_models(cls):
        return _default_multiclass_models()


class RegressionModelSelector(_SelectorFactory):
    problem_type = "Regression"
    default_evaluator = RegressionEvaluator
    default_splitter = DataSplitter

    @classmethod
    def _default_models(cls):
        return _default_regression_models()
