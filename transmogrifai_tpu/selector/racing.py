"""Multi-fidelity racing search: successive-halving validation.

``RacingCrossValidation`` stops paying full cross-validation cost for
losing candidates: the whole family x grid pool is first evaluated at a
LOW fidelity (a subset of folds and/or a row-subsampled train mask),
the top ``1/eta`` by the evaluator's device metric re-enter the next
rung at ``eta``x the fidelity, and only the final survivors are
evaluated under the EXACT full-CV fold protocol (successive halving /
ASHA; cf. Li et al., arxiv 1810.05934). Each rung reuses the family
``eval_fold_grid_arrays`` batched kernels, so a rung is a handful of
fused fit+metric XLA programs — candidate parameters never reach the
host; only the (folds, candidates) metric matrix does.

Fidelity axes are DYNAMIC arguments, not statics:

- row fidelity: single-fold screening rungs SLICE the subsampled train
  rows (deterministic kept-row counts -> stable rung shapes across
  runs, one compile per rung ever — the serving plan's shape-bucketing
  idiom; a zero-mask would keep full-shape FLOPs and save nothing);
  multi-fold rungs edit 0/1 values into the shared train mask (same
  shape — no retrace),
- fold fidelity slices the leading mask/validation axes (one compile
  per rung shape, cached across runs),
- candidate subsetting flows through ``cand_idx`` index vectors into
  the kernels' traced hyperparameter vectors (values stay dynamic; see
  lint rule TX-J07 for the anti-pattern this avoids).

Exactness contract (asserted in tests/test_racing.py): the final rung
evaluates survivors under the same folds, same train masks and same
metric kernel as exact full CV — a racing winner's reported metric is
directly comparable to a full-CV one. Families without a device metric
path (custom evaluators, non-traceable grids, preconditions violated)
drop out of the race and are validated at full fidelity through the
ordinary exact paths; their results join the final comparison.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.base import Predictor, pad_cand_idx
from ..observability import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.errors import BUG, classify_error
from ..runtime.faults import maybe_inject
from .validator import (_QUARANTINED, BestEstimator, CrossValidation,
                        ValidationResult)

__all__ = ["RacingCrossValidation", "search_compiles"]

_log = logging.getLogger(__name__)

#: (family, folds, rows, candidates, spec) signatures dispatched — each
#: is at most a few XLA programs; repeated same-shape searches add no
#: new keys (the compile-count diagnostic, mirroring
#: models/trees.tree_kernel_compiles and serving.plan_compiles)
_RUNG_KEYS: set = set()


def _note_rung_programs(family: str, folds: int, n_rows: int,
                        n_cands: int, spec: tuple) -> None:
    _RUNG_KEYS.add((family, folds, n_rows, n_cands, spec))


def search_compiles() -> int:
    """Distinct racing rung program signatures requested so far in this
    process. A repeated same-shape search leaves this unchanged — the
    rung kernels are memoized per (config, shape), so zero new XLA
    programs are built (the ``plan_compiles()``-style counter the
    acceptance gate reads)."""
    return len(_RUNG_KEYS)


class _Racer:
    """Bookkeeping for one raced candidate (family index, grid index)."""

    __slots__ = ("fam", "gi", "alive", "rung", "budget", "pruned_at",
                 "metrics")

    def __init__(self, fam: int, gi: int):
        self.fam = fam
        self.gi = gi
        self.alive = True
        self.rung: Optional[int] = None
        self.budget = 0.0
        self.pruned_at: Optional[int] = None
        self.metrics: List[float] = []

    def mean(self) -> float:
        arr = np.asarray(self.metrics, dtype=np.float64)
        return float(np.mean(arr)) if arr.size else float("nan")


class RacingCrossValidation(CrossValidation):
    """Successive-halving k-fold search (``validation="racing"``).

    eta          : promotion ratio — each rung keeps the top ``1/eta``
    min_fidelity : budget fraction of the first rung (full CV = 1.0);
                   default ``1/eta**2`` gives the classic 3-rung ladder
                   (e.g. eta=3 -> 1/9, 1/3, 1). The ladder always ends
                   at exactly 1.0: the final rung IS full CV for the
                   survivors.

    When NEITHER is given, the schedule comes from the TuningPolicy
    (tuning/policy.py): the persisted ``family:*`` compile-vs-execute
    records pick the ladder that amortizes recorded compile cost
    (docs/autotuning.md). A cold/absent store or ``TX_TUNE=off``
    resolves to exactly the classic (eta=3, 1/9) ladder — bitwise the
    old defaults. Explicit arguments always win (``caller`` source).
    """

    validation_type = "RacingCrossValidation"

    def __init__(self, evaluator, num_folds: int = 3,
                 eta: Optional[int] = None,
                 min_fidelity: Optional[float] = None, seed: int = 42,
                 stratify: bool = False, mesh="auto"):
        super().__init__(evaluator, num_folds=num_folds, seed=seed,
                         stratify=stratify, mesh=mesh)
        #: the TuningDecision records behind this schedule ([] when the
        #: caller pinned it); bench/tx tune surface them
        self.tuning_decisions: List = []
        if eta is None and min_fidelity is None:
            try:
                from ..tuning.policy import TuningPolicy
                eta, min_fidelity, self.tuning_decisions = \
                    TuningPolicy().racing_schedule()
            except (ImportError, OSError, ValueError,
                    KeyError, TypeError):
                # pragma: no cover - unreadable/malformed store:
                # fall through to the static schedule below
                pass
        if eta is None:
            from ..tuning.registry import STATIC_DEFAULTS
            eta = int(STATIC_DEFAULTS["search.eta"])
        if eta < 2:
            raise ValueError("eta must be >= 2")
        self.eta = int(eta)
        mf = (1.0 / (eta * eta)) if min_fidelity is None else float(
            min_fidelity)
        if not 0.0 < mf <= 1.0:
            raise ValueError("min_fidelity must be in (0, 1]")
        self.min_fidelity = mf
        #: telemetry of the last validate() call (rungs, budgets,
        #: pruned counts) — the selector copies it into
        #: ModelSelectorSummary.racing; bench.py emits it
        self.last_report: Dict = {}

    @classmethod
    def from_cross_validation(cls, cv: CrossValidation,
                              eta: Optional[int] = None,
                              min_fidelity: Optional[float] = None
                              ) -> "RacingCrossValidation":
        """Racing twin of an exact CV validator (same folds, same seed,
        same evaluator — only the schedule changes)."""
        return cls(cv.evaluator, num_folds=cv.num_folds, eta=eta,
                   min_fidelity=min_fidelity, seed=cv.seed,
                   stratify=cv.stratify, mesh=cv.mesh)

    def get_params(self):
        out = super().get_params()
        out.update({"eta": self.eta, "minFidelity": self.min_fidelity,
                    "validation": "racing"})
        return out

    # -- schedule ----------------------------------------------------------
    def _rung_budgets(self) -> List[float]:
        """Ascending budget fractions ending at exactly 1.0 (the full-CV
        rung): min_fidelity * eta^r, capped."""
        budgets: List[float] = []
        b = self.min_fidelity
        while b < 1.0 - 1e-12:
            budgets.append(b)
            b *= self.eta
        budgets.append(1.0)
        return budgets

    def _eval_rung_cands(self, est, grid, X_r, y_r, rung_masks, Xv_r,
                         yv_r, spec, alive: Sequence[int], shards: int):
        """One family's rung evaluation with the candidate axis padded
        to a multiple of the mesh's ``models`` shard count
        (models/base.pad_cand_idx): rung program SHAPES stay on the
        shard lattice — alive counts that differ only by pruning
        trajectory reuse one compiled program — and the padded columns
        (duplicates of the last alive candidate) are sliced off HERE,
        before anything is journaled, ranked or reported, so the prune
        decision sees the identical candidate set on every device
        count."""
        padded, n_valid = pad_cand_idx(alive, shards)
        mm = self._try_device_eval(
            est, grid, X_r, y_r, rung_masks, Xv_r, yv_r, spec,
            cand_idx=np.asarray(padded, dtype=np.int64))
        if mm is None:
            return None
        return np.asarray(mm, dtype=np.float64)[:, :n_valid]

    def _prune_rung(self, contenders: List[_Racer], rung: int) -> int:
        """The rung-boundary prune as ONE COLLECTIVE decision.

        Every family kernel returns its metric shard through
        ``parallel/mesh.to_host`` — on a multi-process mesh that is a
        ``process_allgather``, so every host holds the identical global
        (folds, candidates) table when it reaches this point. The
        global top-``1/eta`` is then computed once from that gathered
        table with a fully deterministic ordering (metric descending by
        the evaluator's sign; non-finite last; (family, grid) index as
        the tie-break) — no RNG, no wall-clock, no device-count
        dependence — so every host, and a resume on ANY mesh topology,
        prunes the exact same candidates (tests/test_sharded_search.py
        asserts rung decisions bitwise across 1/2/8 devices).

        Returns the promoted (kept) count."""
        sign = 1.0 if self.evaluator.is_larger_better else -1.0
        scored = sorted(
            contenders,
            key=lambda rc: (-(sign * rc.mean())
                            if np.isfinite(rc.mean())
                            else np.inf, rc.fam, rc.gi))
        keep = max(1, int(np.ceil(len(scored) / self.eta)))
        for rc in scored[keep:]:
            rc.alive = False
            rc.pruned_at = rung
        return keep

    def _fidelity(self, budget: float, n_folds: int) -> Tuple[int, float]:
        """(folds, train-row fraction) realizing a budget fraction.
        Budget is measured in full-CV units: folds * row_fraction =
        budget * num_folds fold-fit equivalents."""
        fold_units = budget * n_folds
        folds = min(n_folds, max(1, int(round(fold_units))))
        return folds, min(1.0, fold_units / folds)

    def _rung_masks(self, masks: np.ndarray, y: np.ndarray, rung: int,
                    folds: int, row_frac: float) -> np.ndarray:
        """Per-rung train masks: the first ``folds`` folds of the FULL
        CV protocol, with a deterministic row subsample (stratified when
        the splits are) zeroed INTO the mask. Single-fold rungs then
        slice the kept rows out (see validate) so low fidelity costs
        proportionally less compute; multi-fold rungs use the mask
        as-is — same shape, dynamic values, no retrace."""
        sub = np.array(masks[:folds], copy=True)
        if row_frac >= 1.0:
            return sub
        for f in range(folds):
            rng = np.random.default_rng(
                [int(self.seed), 104729, int(rung), f])
            idx = np.nonzero(sub[f] > 0)[0]
            if self.stratify:
                kept = [rng.permutation(ci)[:max(1, int(round(
                    len(ci) * row_frac)))]
                    for cls in np.unique(y[idx])
                    for ci in [idx[y[idx] == cls]]]
                keep = np.concatenate(kept)
            else:
                keep = rng.permutation(idx)[
                    :max(1, int(round(len(idx) * row_frac)))]
            sub[f, np.setdiff1d(idx, keep)] = 0.0
        return sub

    # -- the racing loop ---------------------------------------------------
    def validate(self,
                 models: Sequence[Tuple[Predictor, Sequence[Dict]]],
                 X: np.ndarray, y: np.ndarray) -> BestEstimator:
        t0 = time.perf_counter()
        models = [(est, list(grid) or [{}]) for est, grid in models]
        _, masks, fold_data, spec, X_val_st, y_val_st = \
            self._build_fold_arrays(X, y)
        F = masks.shape[0]
        budgets = self._rung_budgets()
        n_total = sum(len(grid) for _, grid in models)
        if spec is None or X_val_st is None or len(budgets) < 2 \
                or n_total <= 1:
            # nothing to race (no device metric / unequal folds /
            # min_fidelity=1 / single candidate): exact full CV
            _log.info("racing disabled for this search (no device "
                      "metric path or degenerate schedule); running "
                      "exact full CV")
            best = super().validate(models, X, y)
            self.last_report = {
                "raced": False, "eta": self.eta,
                "minFidelity": self.min_fidelity, "rungs": [],
                "candidatesTotal": n_total, "candidatesPruned": 0,
                "budgetSpentFoldFits": float(n_total * F),
                "budgetFullCvFoldFits": float(n_total * F),
                "searchSeconds": round(time.perf_counter() - t0, 3)}
            return best

        ctx = self._begin_runtime(models, X, y)
        try:
            return self._validate_raced(models, X, y, masks, fold_data,
                                        spec, X_val_st, y_val_st,
                                        budgets, n_total, ctx, t0)
        finally:
            ctx.close_journal()

    def _validate_raced(self, models, X, y, masks, fold_data, spec,
                        X_val_st, y_val_st, budgets, n_total, ctx, t0
                        ) -> BestEstimator:
        from ..parallel.cv import mesh_model_shards
        shards = mesh_model_shards(self.mesh)
        F = masks.shape[0]
        racers: Dict[Tuple[int, int], _Racer] = {
            (fi, gi): _Racer(fi, gi)
            for fi, (_, grid) in enumerate(models)
            for gi in range(len(grid))}
        host_fams: List[int] = []       # families validated exactly
        quarantined_fams: set = set()   # families out of the search
        rung_rows: List[Dict] = []
        for r, b in enumerate(budgets):
            # the rung-boundary kill-point: a simulated preemption here
            # loses NOTHING — every completed rung below is journaled
            # (fsync'd), so a resume replays rungs 0..r-1 and dispatches
            # only from here on (tests/test_resilience.py)
            maybe_inject("rung", str(r), "boundary")
            final = r == len(budgets) - 1
            folds_r, row_frac = self._fidelity(b, F)
            X_r, y_r = X, y
            if final:
                # the exactness invariant: the last rung IS full CV
                assert folds_r == F and row_frac >= 1.0
                rung_masks = masks
            else:
                rung_masks = self._rung_masks(masks, y, r, folds_r,
                                              row_frac)
                if folds_r == 1 and row_frac < 1.0:
                    # single-fold screening rungs SLICE the subsampled
                    # train rows out instead of zero-masking them:
                    # masked rows still cost full FLOPs (the shapes
                    # don't change), a slice makes low fidelity
                    # genuinely cheap. The kept-row count is
                    # deterministic per (seed, rung, fold sizes), so
                    # rung shapes are stable across runs — one compile
                    # per rung ever (the serving plan's shape-bucketing
                    # idiom applied to the search). Multi-fold rungs
                    # keep the mask-edit dynamics: their folds need the
                    # shared train matrix.
                    kept = np.nonzero(rung_masks[0] > 0)[0]
                    X_r, y_r = X[kept], y[kept]
                    rung_masks = np.ones((1, len(kept)))
            Xv_r, yv_r = X_val_st[:folds_r], y_val_st[:folds_r]
            fam_idx: List[Tuple[int, List[int]]] = []
            for fi, (est, grid) in enumerate(models):
                if fi in host_fams:
                    continue
                alive = [gi for gi in range(len(grid))
                         if racers[(fi, gi)].alive]
                if alive:
                    fam_idx.append((fi, alive))
            if not fam_idx:
                break
            tasks = []
            for fi, alive in fam_idx:
                est, grid = models[fi]
                # program signature uses the PADDED candidate count:
                # that is the traced shape (the shard lattice), and the
                # reason repeated searches with different pruning
                # trajectories request zero new programs
                _note_rung_programs(type(est).__name__, folds_r,
                                    rung_masks.shape[1],
                                    len(pad_cand_idx(alive, shards)[0]),
                                    spec)
                tasks.append((
                    type(est).__name__, self._family_key(fi, est),
                    tuple(alive),
                    lambda e=est, g=grid, a=alive: self._eval_rung_cands(
                        e, g, X_r, y_r, rung_masks, Xv_r, yv_r, spec,
                        a, shards)))
            # one span per racing rung: the family dispatches below
            # parent to it, so a trace shows rung -> family -> compile
            # sections (docs/observability.md)
            with _trace.span("search.rung", rung=r, final=final,
                             folds=folds_r,
                             budget=round(float(b), 4),
                             families=len(fam_idx),
                             alive=sum(len(a) for _, a in fam_idx)):
                mats = self._dispatch_device_evals(
                    tasks, X_r, rung_masks, Xv_r, yv_r, spec, ctx=ctx,
                    rung=r, rung_label=f"rung{r}")
            n_evaluated = 0
            for (fi, alive), mm in zip(fam_idx, mats):
                est, grid = models[fi]
                if mm is _QUARANTINED:
                    # the family is out of THIS search entirely: no
                    # results, no exact fallback — the quarantine
                    # ledger (ModelSelectorSummary.quarantined) records
                    # why, and the race continues with survivors
                    quarantined_fams.add(fi)
                    for gi in range(len(grid)):
                        racers[(fi, gi)].alive = False
                    continue
                if mm is None:
                    # family can't race (non-traceable grid, labels,
                    # precondition): validate it exactly at full
                    # fidelity through the ordinary paths instead
                    _log.info("family %s leaves the race at rung %d; "
                              "validating it under exact full CV",
                              type(est).__name__, r)
                    host_fams.append(fi)
                    for gi in range(len(grid)):
                        racers[(fi, gi)].alive = False
                    continue
                mm = np.asarray(mm, dtype=np.float64)
                n_evaluated += len(alive)
                for j, gi in enumerate(alive):
                    racer = racers[(fi, gi)]
                    racer.rung = r
                    racer.budget += folds_r * row_frac
                    racer.metrics = [float(v) for v in mm[:, j]]
            contenders = [rc for rc in racers.values() if rc.alive]
            promoted = len(contenders)
            if not final and contenders:
                # collective rung-boundary decision over the gathered
                # global metric table — identical on every host and
                # every device count (_prune_rung)
                promoted = self._prune_rung(contenders, r)
            rung_rows.append({
                "rung": r, "budgetFraction": round(b, 6),
                "folds": folds_r, "rowFraction": round(row_frac, 6),
                "candidates": n_evaluated, "promoted": promoted})
        # exact validation for the families that left the race
        # (journaled under "exact" — a resume replays them too, and a
        # classified failure here quarantines instead of dying)
        host_results: Dict[int, List[ValidationResult]] = {}
        for fi in host_fams:
            est, grid = models[fi]
            key = self._family_key(fi, est)
            cands = tuple(range(len(grid)))
            cached = ctx.journal_lookup(key, "exact", cands)
            if cached is not None:
                host_results[fi] = self._results_from_journal(
                    est, grid, cached)
                continue
            try:
                mm = self._try_device_eval(est, grid, X, y, masks,
                                           X_val_st, y_val_st, spec)
                host_results[fi] = (
                    self._results_from_matrix(est, grid, mm)
                    if mm is not None else
                    self._family_host_results(est, grid, X, y, masks,
                                              fold_data))
            except Exception as e:
                kind = classify_error(e)
                if kind == BUG:
                    raise
                ctx.quarantine(type(est).__name__,
                               f"{type(e).__name__}: {e}", kind=kind,
                               error_type=type(e).__name__)
                quarantined_fams.add(fi)
                host_results[fi] = []
                continue
            _telemetry.note_dispatch(key, "exact", cands, F)
            ctx.journal_record(
                key, "exact", cands,
                [r.metric_values for r in host_results[fi]], F)
        # assemble results in the exact-path family/grid order
        results: List[ValidationResult] = []
        rank_pool: List[ValidationResult] = []
        for fi, (est, grid) in enumerate(models):
            if fi in quarantined_fams:
                continue
            if fi in host_fams:
                results.extend(host_results[fi])
                # full-fidelity metrics: they compete with finalists
                rank_pool.extend(host_results[fi])
                continue
            for gi, params in enumerate(grid):
                rc = racers[(fi, gi)]
                res = ValidationResult(
                    model_name=type(est).__name__, model_uid=est.uid,
                    grid_index=gi, params=dict(params),
                    metric_values=list(rc.metrics),
                    rung=rc.rung if rc.rung is not None else 0,
                    budget_spent=round(rc.budget, 6),
                    pruned_at=rc.pruned_at)
                results.append(res)
                if rc.pruned_at is None and rc.rung is not None:
                    rank_pool.append(res)
        spent = sum(rc.budget for rc in racers.values()) \
            + float(sum(len(models[fi][1]) for fi in host_fams
                        if fi not in quarantined_fams)) * F
        self.last_report = {
            "raced": True, "eta": self.eta,
            "minFidelity": self.min_fidelity, "rungs": rung_rows,
            "candidatesTotal": n_total,
            "candidatesPruned": sum(
                1 for rc in racers.values() if rc.pruned_at is not None),
            "budgetSpentFoldFits": round(spent, 3),
            "budgetFullCvFoldFits": float(n_total * F),
            "searchSeconds": round(time.perf_counter() - t0, 3)}
        return self._pick_best(models, results, rank_pool=rank_pool,
                               ctx=ctx)
