"""RandomParamBuilder: random-search hyperparameter grids.

TPU-native port of the reference RandomParamBuilder
(core/src/main/scala/com/salesforce/op/stages/impl/selector/
RandomParamBuilder.scala): declare per-parameter sampling distributions
(uniform float/int, log-uniform "exponential", or a subset choice) and
draw N independent param dicts to feed a ModelSelector's grid — random
search over the same candidate machinery grid search uses.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

__all__ = ["RandomParamBuilder"]


class RandomParamBuilder:
    """(reference RandomParamBuilder.scala:51)"""

    def __init__(self, seed: int = 42):
        self._rng = np.random.default_rng(seed)
        self._params: List[Tuple[str, str, Any]] = []

    def uniform(self, name: str, low: float, high: float,
                integer: bool = False) -> "RandomParamBuilder":
        """Uniformly distributed values in [low, high]
        (reference uniform for Double/Float/Int/Long params)."""
        if not low < high:
            raise ValueError("low must be less than high")
        self._params.append((name, "uniform", (low, high, integer)))
        return self

    def exponential(self, name: str, low: float, high: float
                    ) -> "RandomParamBuilder":
        """Log-uniformly distributed values in [low, high] — the right
        prior for regularization strengths (reference exponential)."""
        if not 0 < low < high:
            raise ValueError("exponential requires 0 < low < high")
        self._params.append((name, "exponential", (low, high)))
        return self

    def subset(self, name: str, choices: Sequence[Any]
               ) -> "RandomParamBuilder":
        """Uniform choice from a finite set (reference subset)."""
        if not choices:
            raise ValueError("subset requires at least one choice")
        self._params.append((name, "subset", list(choices)))
        return self

    def build(self, n: int) -> List[Dict[str, Any]]:
        """Draw ``n`` independent param dicts
        (reference build(numberOfParams))."""
        if not self._params:
            raise ValueError("no parameters registered")
        out: List[Dict[str, Any]] = []
        for _ in range(n):
            d: Dict[str, Any] = {}
            for name, kind, spec in self._params:
                if kind == "uniform":
                    low, high, integer = spec
                    if integer:
                        d[name] = int(self._rng.integers(int(low),
                                                         int(high) + 1))
                    else:
                        d[name] = float(self._rng.uniform(low, high))
                elif kind == "exponential":
                    low, high = spec
                    d[name] = float(np.exp(self._rng.uniform(
                        np.log(low), np.log(high))))
                else:
                    d[name] = spec[int(self._rng.integers(len(spec)))]
            out.append(d)
        return out
