"""ModelSelector: automated model selection — pillar #3.

TPU-native port of core/src/main/scala/com/salesforce/op/stages/impl/
selector/{ModelSelector.scala:74,136, ModelSelectorSummary.scala:59}. The
selector is an estimator over (label, features): it prepares the data
with an optional splitter (balance / cut), validates every candidate
(family x grid point) under CV or TVS, refits the winner on the full
prepared training set, and emits a ``SelectedModel`` carrying the full
``ModelSelectorSummary`` (every model x grid x metric).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..evaluators.base import EvaluationMetrics, Evaluator
from ..features.columns import PredictionColumn
from ..models.base import PredictionModel, Predictor
from .splitters import Splitter, SplitterSummary
from .validator import BestEstimator, CrossValidation, ValidationResult, \
    _ValidatorBase

__all__ = ["ModelSelector", "SelectedModel", "ModelSelectorSummary"]


def _is_device_array(x) -> bool:
    try:
        import jax
        return isinstance(x, jax.Array)
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        return False


@dataclass
class ModelSelectorSummary:
    """Full validation record (reference ModelSelectorSummary.scala:59)."""
    validation_type: str = ""
    validation_parameters: Dict = field(default_factory=dict)
    data_prep_parameters: Dict = field(default_factory=dict)
    data_prep_results: Dict = field(default_factory=dict)
    evaluation_metric: str = ""
    problem_type: str = ""
    best_model_name: str = ""
    best_model_uid: str = ""
    best_model_params: Dict = field(default_factory=dict)
    best_validation_metric: float = 0.0
    validation_results: List[ValidationResult] = field(default_factory=list)
    train_evaluation: Optional[EvaluationMetrics] = None
    holdout_evaluation: Optional[EvaluationMetrics] = None
    metric_larger_better: bool = True
    #: multi-fidelity racing telemetry (selector/racing.py
    #: RacingCrossValidation.last_report): rung schedule, budgets,
    #: pruned counts. Empty — and absent from the JSON — under exact
    #: validation, keeping default summaries byte-identical.
    racing: Dict = field(default_factory=dict)
    #: quarantine ledger (runtime/errors.QuarantineRecord.to_json rows):
    #: families removed from this search and why (OOM, XlaRuntimeError,
    #: poisoned metrics, deadline). Empty — and absent from the JSON —
    #: on a fault-free search, keeping default summaries byte-identical
    #: to pre-runtime output.
    quarantined: List[Dict] = field(default_factory=list)

    def to_json(self) -> dict:
        out = {
            "validationType": self.validation_type,
            "validationParameters": self.validation_parameters,
            "dataPrepParameters": self.data_prep_parameters,
            "dataPrepResults": self.data_prep_results,
            "evaluationMetric": self.evaluation_metric,
            "problemType": self.problem_type,
            "bestModelName": self.best_model_name,
            "bestModelUID": self.best_model_uid,
            "bestModelParams": self.best_model_params,
            "bestValidationMetric": self.best_validation_metric,
            "validationResults": [r.to_json()
                                  for r in self.validation_results],
            "metricLargerBetter": self.metric_larger_better,
            "trainEvaluation": (self.train_evaluation.to_json()
                                if self.train_evaluation else None),
            # RawMetrics fallbacks re-record the ORIGINAL class name so
            # a later load with the class importable rebuilds the type
            "trainEvaluationClass": (
                getattr(self.train_evaluation, "class_name", "")
                or type(self.train_evaluation).__name__
                if self.train_evaluation else None),
            "holdoutEvaluation": (self.holdout_evaluation.to_json()
                                  if self.holdout_evaluation else None),
            "holdoutEvaluationClass": (
                getattr(self.holdout_evaluation, "class_name", "")
                or type(self.holdout_evaluation).__name__
                if self.holdout_evaluation else None),
        }
        if self.racing:
            out["racing"] = self.racing
        if self.quarantined:
            out["quarantined"] = self.quarantined
        return out

    @classmethod
    def from_json(cls, d: dict) -> "ModelSelectorSummary":
        """Inverse of :meth:`to_json` (model save/load)."""
        from ..evaluators.base import metrics_from_json

        def metrics(which: str):
            payload = d.get(which)
            name = d.get(which + "Class")
            return (metrics_from_json(name, payload)
                    if payload is not None and name else None)

        return cls(
            validation_type=d.get("validationType", ""),
            validation_parameters=d.get("validationParameters") or {},
            data_prep_parameters=d.get("dataPrepParameters") or {},
            data_prep_results=d.get("dataPrepResults") or {},
            evaluation_metric=d.get("evaluationMetric", ""),
            problem_type=d.get("problemType", ""),
            best_model_name=d.get("bestModelName", ""),
            best_model_uid=d.get("bestModelUID", ""),
            best_model_params=d.get("bestModelParams") or {},
            best_validation_metric=d.get("bestValidationMetric", 0.0),
            validation_results=[ValidationResult.from_json(r)
                                for r in d.get("validationResults", [])],
            train_evaluation=metrics("trainEvaluation"),
            holdout_evaluation=metrics("holdoutEvaluation"),
            metric_larger_better=d.get("metricLargerBetter", True),
            racing=d.get("racing") or {},
            quarantined=d.get("quarantined") or [],
        )

    def pretty(self) -> str:
        """Human summary (reference summaryPretty,
        OpWorkflowModel.scala:204)."""
        lines = [
            f"Selected model: {self.best_model_name} "
            f"({self.evaluation_metric}={self.best_validation_metric:.4f} "
            f"under {self.validation_type})",
            f"Best params: {self.best_model_params}",
            "Validation results (mean metric per grid point):",
        ]
        sign = -1.0 if self.metric_larger_better else 1.0

        def rank(r):  # non-finite metrics sort last
            m = r.mean_metric
            return sign * m if np.isfinite(m) else np.inf

        for r in sorted(self.validation_results, key=rank):
            # racing records annotate their trajectory (a pruned
            # candidate's low-fidelity mean is not comparable to a
            # full-CV one); exact records render exactly as before
            racing = ""
            if r.rung is not None:
                racing = (f"  [pruned@rung{r.pruned_at}]"
                          if r.pruned_at is not None
                          else "  [finalist]")
            lines.append(f"  {r.model_name}[{r.grid_index}] "
                         f"{r.params} -> {r.mean_metric:.4f}{racing}")
        if self.quarantined:
            lines.append("Quarantined families (search degraded to "
                         "survivors; docs/resilience.md):")
            for q in self.quarantined:
                retries = (f" after {q.get('retries')} retries"
                           if q.get("retries") else "")
                lines.append(f"  {q.get('family')}: [{q.get('kind')}] "
                             f"{q.get('reason')}{retries}")
        return "\n".join(lines)


class SelectedModel(PredictionModel):
    """The winning fitted model + selection summary (reference
    SelectedModel, ModelSelector.scala:214). Delegates prediction to the
    wrapped inner model."""

    def __init__(self, inner: PredictionModel = None,
                 summary: Optional[ModelSelectorSummary] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="modelSelector", uid=uid)
        self.inner = inner
        self.summary = summary

    def predict_arrays(self, X: np.ndarray) -> PredictionColumn:
        return self.inner.predict_arrays(X)

    # compiled-serving lowering delegates to the winning model so the
    # fused program embeds ITS kernel (serving/plan.py)
    def raw_arrays(self, X):
        return self.inner.raw_arrays(X)

    def supports_arrays(self) -> bool:
        return self.inner is not None and self.inner.supports_arrays()

    def prediction_from_raw(self, raw: np.ndarray) -> PredictionColumn:
        return self.inner.prediction_from_raw(raw)


def models_x_folds(model) -> int:
    """Total (candidate, fold) evaluations recorded by the selector(s)
    in a fitted workflow model — the unit of the north-star throughput
    metric (BASELINE.md). Shared by bench.py and
    examples/multicore_bench.py so their rows stay comparable."""
    return sum(
        len(r.metric_values)
        for s in model.stages()
        if isinstance(s, SelectedModel) and s.summary is not None
        for r in s.summary.validation_results)


class ModelSelector(Predictor):
    """Run candidates x grids under a validator, pick the winner
    (reference ModelSelector.scala:74)."""

    def __init__(self,
                 models: Sequence[Tuple[Predictor, Sequence[Dict]]] = (),
                 validator: Optional[_ValidatorBase] = None,
                 splitter: Optional[Splitter] = None,
                 problem_type: str = "",
                 validation: str = "exact",
                 eta: Optional[int] = None,
                 min_fidelity: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 retry_policy=None,
                 family_deadline: Optional[float] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        if validation not in ("exact", "racing"):
            raise ValueError(
                f"validation must be 'exact' or 'racing', got "
                f"{validation!r}")
        if validation == "racing" and validator is not None:
            # multi-fidelity successive halving (selector/racing.py):
            # same folds/seed/evaluator as the exact validator, but
            # losing candidates stop training early. Opt-in — the
            # default stays exact full CV with a bit-identical winner.
            from .racing import RacingCrossValidation
            if isinstance(validator, RacingCrossValidation):
                pass
            elif isinstance(validator, CrossValidation):
                validator = RacingCrossValidation.from_cross_validation(
                    validator, eta=eta, min_fidelity=min_fidelity)
            else:
                raise ValueError(
                    "validation='racing' requires a CrossValidation "
                    "validator (train/validation split has a single "
                    "fold — nothing to race)")
        self.models = list(models)
        self.validator = validator
        self.splitter = splitter
        self.problem_type = problem_type
        #: fault-tolerant runtime knobs (runtime/, docs/resilience.md):
        #: journal completed (family, cands, rung) evaluations under
        #: this directory so an interrupted search resumes via
        #: ``Workflow.train(resume_from=...)`` with zero re-dispatch
        self.checkpoint_dir = checkpoint_dir
        #: RetryPolicy for transient (preemption/RESOURCE_EXHAUSTED-
        #: shaped) dispatch failures; None = TX_RETRY_* env defaults
        self.retry_policy = retry_policy
        #: per-family dispatch deadline in wall-clock seconds (None =
        #: off; also TX_FAMILY_DEADLINE_S)
        self.family_deadline = family_deadline
        #: pre-computed winner from workflow-level CV (reference
        #: findBestEstimator, ModelSelector.scala:113): when set, fit
        #: skips validation and refits this estimator on the full data
        self.best_estimator: Optional[BestEstimator] = None
        #: (train_idx, test_idx) reserved by workflow-level CV BEFORE
        #: the fold search — consumed by fit so search and final fit
        #: share ONE split structurally (not by re-derivation)
        self.preset_split = None

    def fit_columns(self, cols) -> SelectedModel:
        """Overrides the Predictor boundary: a feature matrix the
        compiled prepare plan left on device (plans/prepare.py) feeds
        the search AS-IS — the fold gathers, stacked validation arrays
        and family kernels all consume it without a host round-trip
        (the label is tiny and host-side by construction)."""
        y = np.asarray(cols[0].data, dtype=np.float64)
        data = cols[1].data
        X = data if _is_device_array(data) \
            else np.asarray(data, dtype=np.float64)
        model = self.fit_arrays(X, y)
        model.vector_metadata = cols[1].metadata
        return model

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> SelectedModel:
        if not self.models:
            raise ValueError("ModelSelector has no candidate models")
        if self.validator is None:
            raise ValueError("ModelSelector requires a validator")

        # 1. data prep (reference splitter.split + splitter.prepare,
        # ModelSelector.scala:140-152, tuning/Splitter.scala:56,64):
        # reserve a holdout first, then resample the training portion.
        prep_params: Dict = {}
        prep_results: Dict = {}
        X_hold = y_hold = None
        if self.splitter is not None:
            if self.preset_split is not None:
                # workflow-level CV already reserved the holdout; reuse
                # its exact indices (and its estimated resampling plan)
                train_idx, test_idx = self.preset_split
                self.preset_split = None
            else:
                # a fresh fit must not recycle a plan estimated on some
                # earlier dataset (reused selector instances re-validate)
                self.splitter.reset_plan()
                train_idx, test_idx = self.splitter.split(y)
            if len(test_idx):
                X_hold, y_hold = X[test_idx], y[test_idx]
            X_tr, y_tr = X[train_idx], y[train_idx]
            idx = self.splitter.prepare(y_tr)
            Xp, yp = X_tr[idx], y_tr[idx]
            kept = getattr(self.splitter, "labels_kept", None)
            if kept is not None and X_hold is not None:
                # score the holdout only on labels the cutter kept —
                # the refit model cannot predict dropped classes
                hold_mask = np.isin(y_hold, kept)
                X_hold, y_hold = X_hold[hold_mask], y_hold[hold_mask]
                if not len(y_hold):
                    X_hold = y_hold = None
            summ = self.splitter.summary or SplitterSummary()
            prep_params = summ.parameters
            prep_results = summ.results
        else:
            Xp, yp = X, y

        # 2. validation (reference validator.validate) — unless workflow-
        # level CV already found the winner (ModelSelector.scala:136
        # bestEstimator.getOrElse{...}). The preset is CONSUMED so a
        # reused selector instance re-validates on its new data instead
        # of silently recycling a stale winner.
        best: BestEstimator
        if self.best_estimator is not None:
            best, self.best_estimator = self.best_estimator, None
        else:
            # thread the fault-tolerance knobs into the validator for
            # THIS search (runtime/): journal + retry + deadline
            v = self.validator
            if self.checkpoint_dir is not None:
                v.checkpoint_dir = self.checkpoint_dir
            if self.retry_policy is not None:
                v.retry_policy = self.retry_policy
            if self.family_deadline is not None:
                v.family_deadline = self.family_deadline
            best = self.validator.validate(self.models, Xp, yp)
        rt = getattr(self.validator, "last_runtime", None)
        quarantined = ([r.to_json() for r in rt.quarantined]
                       if rt is not None else [])

        # 3. refit winner on the full prepared train set
        # (reference ModelSelector.scala:163) — behind the retry
        # policy: a preemption during the refit must not discard the
        # whole (journaled) search
        from ..runtime.retry import RetryPolicy
        retry = (self.retry_policy
                 or getattr(self.validator, "retry_policy", None)
                 or RetryPolicy.from_env())
        inner = retry.call(lambda: best.estimator.fit_arrays(Xp, yp),
                           description=f"winner-refit:{best.name}")

        # 4. training-set evaluation (reference :172)
        evaluator = self.validator.evaluator
        train_eval = evaluator.evaluate_arrays(
            yp, inner.predict_arrays(Xp))
        holdout_eval = None
        if X_hold is not None:
            holdout_eval = evaluator.evaluate_arrays(
                y_hold, inner.predict_arrays(X_hold))

        summary = ModelSelectorSummary(
            validation_type=type(self.validator).__name__,
            validation_parameters=self.validator.get_params(),
            racing=dict(getattr(self.validator, "last_report", {}) or {}),
            quarantined=quarantined,
            data_prep_parameters=prep_params,
            data_prep_results=prep_results,
            evaluation_metric=evaluator.default_metric,
            problem_type=self.problem_type,
            best_model_name=best.name,
            best_model_uid=best.estimator.uid,
            best_model_params=best.params,
            best_validation_metric=best.metric,
            validation_results=best.results,
            train_evaluation=train_eval,
            holdout_evaluation=holdout_eval,
            metric_larger_better=evaluator.is_larger_better,
        )
        return SelectedModel(inner=inner, summary=summary)
