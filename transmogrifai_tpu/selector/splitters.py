"""Data splitters: holdout reserve, class balancing, label cutting.

TPU-native ports of the reference tuning splitters
(core/src/main/scala/com/salesforce/op/stages/impl/tuning/
{Splitter.scala:56, DataSplitter.scala:62, DataBalancer.scala:72,
DataCutter.scala:74}). All splitters are pure index computations over the
label vector — the feature matrix itself never moves; downstream fits
gather rows by index (cheap on host, one device transfer after).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["SplitterSummary", "Splitter", "DataSplitter", "DataBalancer",
           "DataCutter", "stratified_split"]


def stratified_split(y: np.ndarray, test_fraction: float,
                     rng: np.random.Generator
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(train_idx, test_idx) with per-class proportional sampling — the
    one stratified-split implementation shared by holdout reservation and
    TrainValidationSplit."""
    n = len(y)
    mask = np.zeros(n, dtype=bool)
    for cls in np.unique(y):
        idx = rng.permutation(np.nonzero(y == cls)[0])
        mask[idx[:int(round(len(idx) * test_fraction))]] = True
    return np.nonzero(~mask)[0], np.nonzero(mask)[0]


def _sample_frac(idx: np.ndarray, fraction: float,
                 rng: np.random.Generator) -> np.ndarray:
    """Without-replacement sample of ``round(fraction * len)`` rows —
    the numpy stand-in for Spark ``Dataset.sample(false, fraction)``."""
    if fraction >= 1.0 or not len(idx):
        return idx
    return rng.choice(idx, int(round(fraction * len(idx))), replace=False)


@dataclass
class SplitterSummary:
    """Data-prep record attached to ModelSelectorSummary
    (reference SplitterSummary in Splitter.scala)."""
    splitter: str = ""
    parameters: Dict = field(default_factory=dict)
    results: Dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"splitter": self.splitter, "parameters": self.parameters,
                "results": self.results}


class Splitter:
    """Base: optionally reserve a test fraction, then prepare (resample)
    the training portion (reference Splitter.scala:56,64)."""

    def __init__(self, reserve_test_fraction: float = 0.0, seed: int = 42):
        if not 0.0 <= reserve_test_fraction < 1.0:
            raise ValueError("reserve_test_fraction must be in [0, 1)")
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed
        self.summary: Optional[SplitterSummary] = None

    def split(self, y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(train_idx, test_idx) — stratified on the label."""
        n = len(y)
        if self.reserve_test_fraction <= 0.0:
            return np.arange(n), np.zeros(0, dtype=np.int64)
        rng = np.random.default_rng(self.seed)
        return stratified_split(y, self.reserve_test_fraction, rng)

    def prepare(self, y: np.ndarray) -> np.ndarray:
        """Row indices (possibly resampled) to train on."""
        self.summary = SplitterSummary(splitter=type(self).__name__)
        return np.arange(len(y))

    def reset_plan(self) -> None:
        """Forget any stored resampling plan so the next fit estimates
        fresh from ITS data. The plan intentionally persists across the
        prepares of ONE selector fit (global estimate -> per-fold
        prepares -> final refit, reference isSet semantics); a REUSED
        selector instance must not recycle it across datasets — the
        selector calls this at the top of every fit."""

    def get_params(self) -> Dict:
        return {"reserve_test_fraction": self.reserve_test_fraction,
                "seed": self.seed}


class DataSplitter(Splitter):
    """Plain splitter for regression problems
    (reference DataSplitter.scala:62)."""

    def split(self, y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        # no stratification for continuous labels
        n = len(y)
        if self.reserve_test_fraction <= 0.0:
            return np.arange(n), np.zeros(0, dtype=np.int64)
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        return np.sort(perm[n_test:]), np.sort(perm[:n_test])


class DataBalancer(Splitter):
    """Binary-label balancer: up-sample the minority / down-sample the
    majority until the minority fraction reaches ``sample_fraction``
    (reference DataBalancer.scala:72,125).

    Sampling proportions are a reusable *plan* (reference param state,
    DataBalancer.scala:132-137 ``isSet`` guards): :meth:`estimate`
    computes them once from global label counts and every subsequent
    :meth:`prepare` — including the per-fold calls inside the
    workflow-level-CV search (OpValidator.scala:250-252) — applies the
    same plan, so fold resampling matches the final-refit resampling."""

    def __init__(self, sample_fraction: float = 0.1,
                 max_training_sample: int = 1_000_000,
                 reserve_test_fraction: float = 0.0, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        if not 0.0 < sample_fraction < 0.5:
            raise ValueError("sample_fraction must be in (0, 0.5)")
        self.sample_fraction = sample_fraction
        self.max_training_sample = max_training_sample
        #: (is_positive_small, down, up, already_balanced_fraction) —
        #: set by estimate(); None until then
        self._plan: Optional[Tuple[bool, float, float,
                                   Optional[float]]] = None

    def reset_plan(self) -> None:
        self._plan = None

    def _proportions(self, small: int, big: int
                     ) -> Tuple[float, float]:
        """(downSample, upSample) fractions
        (reference getProportions, DataBalancer.scala:86-117): prefer
        integer up-sampling of the minority, capped so the balanced set
        stays under max_training_sample; otherwise down-sample both."""
        f = self.sample_fraction
        max_train = self.max_training_sample

        def up_ok(m: int) -> bool:
            return (m * small * (1.0 - f) < f * big
                    and max_train * f > small * m)

        if small < max_train * f:
            up = next((float(m) for m in (100, 50, 10, 5, 4, 3, 2)
                       if up_ok(m)), 1.0)
            return (small * up / f - small * up) / big, up
        up = (max_train * f) / small
        return (1.0 - f) * max_train / big, up

    def estimate(self, y: np.ndarray) -> None:
        """Compute and store the sampling plan from label counts
        (reference estimate, DataBalancer.scala:319-358). Called once on
        the full training labels before per-fold prepares."""
        n_pos = int(np.sum(y == 1))
        n_neg = int(len(y) - n_pos)
        total = max(n_pos + n_neg, 1)
        is_pos_small = n_pos < n_neg
        small, big = ((n_pos, n_neg) if is_pos_small else (n_neg, n_pos))
        if big == 0 or small / total >= self.sample_fraction:
            frac = (self.max_training_sample / total
                    if self.max_training_sample < total else 1.0)
            self._plan = (is_pos_small, frac, 0.0, frac)
            up, down = 0.0, frac
        else:
            down, up = self._proportions(small, big)
            self._plan = (is_pos_small, down, up, None)
        self.summary = SplitterSummary(
            splitter="DataBalancer", parameters=self.get_params(),
            results={"positiveCount": n_pos, "negativeCount": n_neg,
                     "desiredFraction": self.sample_fraction,
                     "upSamplingFraction": up,
                     "downSamplingFraction": down,
                     "balanced": self._plan[3] is None})

    def prepare(self, y: np.ndarray) -> np.ndarray:
        """Resampled row indices. Uses the stored plan when
        :meth:`estimate` already ran (reference ``isSet`` guard,
        DataBalancer.scala:132); estimates from ``y`` otherwise. The
        returned indices may repeat (minority up-sampling is WITH
        replacement, reference rebalance, DataBalancer.scala:263-268)."""
        if self._plan is None:
            self.estimate(y)
        is_pos_small, down, up, already_frac = self._plan
        rng = np.random.default_rng(self.seed)
        pos_idx = np.nonzero(y == 1)[0]
        neg_idx = np.nonzero(y != 1)[0]
        if already_frac is not None:
            # per-class subsample (reference sampleBalancedData)
            if already_frac >= 1.0:
                return np.arange(len(y))
            return np.sort(np.concatenate([
                _sample_frac(neg_idx, already_frac, rng),
                _sample_frac(pos_idx, already_frac, rng)]))
        small, big = ((pos_idx, neg_idx) if is_pos_small
                      else (neg_idx, pos_idx))
        big_take = _sample_frac(big, min(down, 1.0), rng)
        if up > 1.0:
            small_take = rng.choice(
                small, int(round(up * len(small))), replace=True) \
                if len(small) else small
        elif up == 1.0:
            small_take = small
        else:
            small_take = _sample_frac(small, up, rng)
        return np.sort(np.concatenate([small_take, big_take]))

    def get_params(self) -> Dict:
        p = super().get_params()
        p.update({"sample_fraction": self.sample_fraction,
                  "max_training_sample": self.max_training_sample})
        return p


class DataCutter(Splitter):
    """Multiclass label cutter: drop labels with too few instances and cap
    the number of label categories (reference DataCutter.scala:74,85)."""

    def __init__(self, min_label_fraction: float = 0.0,
                 max_label_categories: int = 100,
                 reserve_test_fraction: float = 0.0, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        if not 0.0 <= min_label_fraction < 0.5:
            raise ValueError("min_label_fraction must be in [0, 0.5)")
        self.min_label_fraction = min_label_fraction
        self.max_label_categories = max_label_categories
        self.labels_kept: Optional[np.ndarray] = None

    def reset_plan(self) -> None:
        self.labels_kept = None

    def estimate(self, y: np.ndarray) -> None:
        """Decide which labels survive, from global label counts
        (reference estimate, DataCutter.scala:85 — called once via
        prepareStratification before per-fold prepares)."""
        labels, counts = np.unique(y, return_counts=True)
        frac = counts / max(len(y), 1)
        keep = labels[frac >= self.min_label_fraction]
        if len(keep) > self.max_label_categories:
            order = np.argsort(-counts[np.isin(labels, keep)])
            keep = keep[order[:self.max_label_categories]]
        if len(keep) == 0:
            raise ValueError(
                f"DataCutter dropped every label: no class reaches "
                f"min_label_fraction={self.min_label_fraction} "
                f"(label fractions: {dict(zip(labels.tolist(), np.round(frac, 4).tolist()))})")
        self.labels_kept = np.sort(keep)
        dropped = sorted(set(labels.tolist()) - set(keep.tolist()))
        self.summary = SplitterSummary(
            splitter="DataCutter",
            parameters={"min_label_fraction": self.min_label_fraction,
                        "max_label_categories": self.max_label_categories},
            results={"labelsKept": self.labels_kept.tolist(),
                     "labelsDropped": dropped})

    def prepare(self, y: np.ndarray) -> np.ndarray:
        """Row indices of surviving labels. Reuses the labels picked by
        a prior :meth:`estimate` so per-fold cuts agree with the final
        refit cut; estimates from ``y`` when none ran."""
        if self.labels_kept is None:
            self.estimate(y)
        return np.nonzero(np.isin(y, self.labels_kept))[0]
