"""Data splitters: holdout reserve, class balancing, label cutting.

TPU-native ports of the reference tuning splitters
(core/src/main/scala/com/salesforce/op/stages/impl/tuning/
{Splitter.scala:56, DataSplitter.scala:62, DataBalancer.scala:72,
DataCutter.scala:74}). All splitters are pure index computations over the
label vector — the feature matrix itself never moves; downstream fits
gather rows by index (cheap on host, one device transfer after).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["SplitterSummary", "Splitter", "DataSplitter", "DataBalancer",
           "DataCutter", "stratified_split"]


def stratified_split(y: np.ndarray, test_fraction: float,
                     rng: np.random.Generator
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(train_idx, test_idx) with per-class proportional sampling — the
    one stratified-split implementation shared by holdout reservation and
    TrainValidationSplit."""
    n = len(y)
    mask = np.zeros(n, dtype=bool)
    for cls in np.unique(y):
        idx = rng.permutation(np.nonzero(y == cls)[0])
        mask[idx[:int(round(len(idx) * test_fraction))]] = True
    return np.nonzero(~mask)[0], np.nonzero(mask)[0]


@dataclass
class SplitterSummary:
    """Data-prep record attached to ModelSelectorSummary
    (reference SplitterSummary in Splitter.scala)."""
    splitter: str = ""
    parameters: Dict = field(default_factory=dict)
    results: Dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"splitter": self.splitter, "parameters": self.parameters,
                "results": self.results}


class Splitter:
    """Base: optionally reserve a test fraction, then prepare (resample)
    the training portion (reference Splitter.scala:56,64)."""

    def __init__(self, reserve_test_fraction: float = 0.0, seed: int = 42):
        if not 0.0 <= reserve_test_fraction < 1.0:
            raise ValueError("reserve_test_fraction must be in [0, 1)")
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed
        self.summary: Optional[SplitterSummary] = None

    def split(self, y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(train_idx, test_idx) — stratified on the label."""
        n = len(y)
        if self.reserve_test_fraction <= 0.0:
            return np.arange(n), np.zeros(0, dtype=np.int64)
        rng = np.random.default_rng(self.seed)
        return stratified_split(y, self.reserve_test_fraction, rng)

    def prepare(self, y: np.ndarray) -> np.ndarray:
        """Row indices (possibly resampled) to train on."""
        self.summary = SplitterSummary(splitter=type(self).__name__)
        return np.arange(len(y))

    def get_params(self) -> Dict:
        return {"reserve_test_fraction": self.reserve_test_fraction,
                "seed": self.seed}


class DataSplitter(Splitter):
    """Plain splitter for regression problems
    (reference DataSplitter.scala:62)."""

    def split(self, y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        # no stratification for continuous labels
        n = len(y)
        if self.reserve_test_fraction <= 0.0:
            return np.arange(n), np.zeros(0, dtype=np.int64)
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        return np.sort(perm[n_test:]), np.sort(perm[:n_test])


class DataBalancer(Splitter):
    """Binary-label balancer: up-sample the minority / down-sample the
    majority until the positive fraction reaches ``sample_fraction``
    (reference DataBalancer.scala:72,125)."""

    def __init__(self, sample_fraction: float = 0.1,
                 max_training_sample: int = 1_000_000,
                 reserve_test_fraction: float = 0.0, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        if not 0.0 < sample_fraction < 0.5:
            raise ValueError("sample_fraction must be in (0, 0.5)")
        self.sample_fraction = sample_fraction
        self.max_training_sample = max_training_sample

    def prepare(self, y: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        pos_idx = np.nonzero(y == 1)[0]
        neg_idx = np.nonzero(y != 1)[0]
        n_pos, n_neg = len(pos_idx), len(neg_idx)
        small, big = ((pos_idx, neg_idx) if n_pos <= n_neg
                      else (neg_idx, pos_idx))
        frac = len(small) / max(len(y), 1)
        already_balanced = frac >= self.sample_fraction
        if already_balanced:
            idx = np.arange(len(y))
            if len(idx) > self.max_training_sample:
                idx = rng.choice(idx, self.max_training_sample,
                                 replace=False)
            self.summary = SplitterSummary(
                splitter="DataBalancer",
                parameters=self.get_params(),
                results={"positiveCount": n_pos, "negativeCount": n_neg,
                         "balanced": False})
            return np.sort(idx)
        # down-sample the majority class so the minority reaches the
        # target fraction (reference keeps all minority rows)
        target_big = int(len(small) * (1.0 - self.sample_fraction)
                         / self.sample_fraction)
        big_sampled = rng.choice(big, min(target_big, len(big)),
                                 replace=False)
        idx = np.concatenate([small, big_sampled])
        if len(idx) > self.max_training_sample:
            idx = rng.choice(idx, self.max_training_sample, replace=False)
        self.summary = SplitterSummary(
            splitter="DataBalancer", parameters=self.get_params(),
            results={"positiveCount": n_pos, "negativeCount": n_neg,
                     "balanced": True,
                     "downSampleFraction": len(big_sampled) / max(len(big), 1)})
        return np.sort(idx)

    def get_params(self) -> Dict:
        p = super().get_params()
        p.update({"sample_fraction": self.sample_fraction,
                  "max_training_sample": self.max_training_sample})
        return p


class DataCutter(Splitter):
    """Multiclass label cutter: drop labels with too few instances and cap
    the number of label categories (reference DataCutter.scala:74,85)."""

    def __init__(self, min_label_fraction: float = 0.0,
                 max_label_categories: int = 100,
                 reserve_test_fraction: float = 0.0, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        if not 0.0 <= min_label_fraction < 0.5:
            raise ValueError("min_label_fraction must be in [0, 0.5)")
        self.min_label_fraction = min_label_fraction
        self.max_label_categories = max_label_categories
        self.labels_kept: Optional[np.ndarray] = None

    def prepare(self, y: np.ndarray) -> np.ndarray:
        labels, counts = np.unique(y, return_counts=True)
        frac = counts / max(len(y), 1)
        keep = labels[frac >= self.min_label_fraction]
        if len(keep) > self.max_label_categories:
            order = np.argsort(-counts[np.isin(labels, keep)])
            keep = keep[order[:self.max_label_categories]]
        if len(keep) == 0:
            raise ValueError(
                f"DataCutter dropped every label: no class reaches "
                f"min_label_fraction={self.min_label_fraction} "
                f"(label fractions: {dict(zip(labels.tolist(), np.round(frac, 4).tolist()))})")
        self.labels_kept = np.sort(keep)
        dropped = sorted(set(labels.tolist()) - set(keep.tolist()))
        self.summary = SplitterSummary(
            splitter="DataCutter",
            parameters={"min_label_fraction": self.min_label_fraction,
                        "max_label_categories": self.max_label_categories},
            results={"labelsKept": self.labels_kept.tolist(),
                     "labelsDropped": dropped})
        return np.nonzero(np.isin(y, self.labels_kept))[0]
