"""Hyperparameter validation: cross-validation and train/validation split.

TPU-native port of the reference validators
(core/src/main/scala/com/salesforce/op/tuning/{OpValidator.scala:94,
OpCrossValidation.scala:40, OpTrainValidationSplit.scala}). The
reference's per-fold / per-family ``Future`` task parallelism maps to:

- one jitted XLA fit per (family, grid point, fold); hyperparameters are
  traced scalars so a whole grid reuses one compiled program per family,
- mesh execution BY DEFAULT: the validator resolves a
  ``("models", "data")`` mesh over the visible devices at search time
  (``parallel/cv.resolve_search_mesh``; ``TX_SEARCH_MESH`` policies it,
  a single visible device keeps the local path) and families exposing a
  mesh kernel (see parallel/cv.py) train all fold x grid candidates in
  one SPMD program, candidate axis sharded over chips. Candidate-axis
  sharding keeps every candidate's arithmetic identical to the local
  program, so the winner is BITWISE invariant across device counts
  (docs/distributed.md; tests/test_sharded_search.py).
"""
from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_log = logging.getLogger(__name__)

#: per-family dispatch accounting (wall + compile seconds, call count),
#: accumulated across every search in this process — bench.py reads it
#: to tell a compile-bound search from a compute-bound one family by
#: family (the thread is named ``tx-family-<Name>`` while the family's
#: kernels run, so profiler lanes carry the same attribution)
_FAMILY_PROFILE: Dict[str, Dict[str, float]] = {}


def family_profile() -> List[dict]:
    """Per-family device-dispatch profile rows, slowest first:
    ``{"family", "seconds", "compileSeconds", "executeSeconds",
    "calls"}``. compileSeconds is the XLA trace+lower+compile time
    observed on the family's dispatch thread (utils/compile_time.py) —
    a warm process pays only executeSeconds."""
    return [
        {"family": k, "seconds": round(v["seconds"], 4),
         "compileSeconds": round(min(v["compile"], v["seconds"]), 4),
         "executeSeconds": round(
             max(0.0, v["seconds"] - v["compile"]), 4),
         "calls": int(v["calls"])}
        for k, v in sorted(_FAMILY_PROFILE.items(),
                           key=lambda kv: -kv[1]["seconds"])]


def reset_family_profile() -> None:
    _FAMILY_PROFILE.clear()

from ..evaluators.base import Evaluator
from ..models.base import (FamilyPreconditionError,
                           PredictionModel, Predictor)
from ..observability import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.context import RuntimeContext
from ..runtime.errors import (AllFamiliesFailedError, BUG,
                              classify_error)
from ..runtime.faults import maybe_inject

__all__ = ["ValidationResult", "BestEstimator", "CrossValidation",
           "TrainValidationSplit"]

#: sentinel a dispatch returns for a quarantined family — distinct from
#: None (= no device path; fall through to the host evaluation)
_QUARANTINED = object()


def _async_dispatch_bytes(X, masks, X_val_st, y_val_st) -> int:
    """Bytes concurrent family dispatch keeps resident on device AT
    ONCE: the train matrix, the fold masks and (when the device fast
    path is active) the stacked per-fold validation arrays. The async
    HBM guard must sum all of them — counting X alone under-estimates
    peak HBM for many-fold searches near the threshold."""
    total = int(getattr(X, "nbytes", 0)) + int(masks.nbytes)
    if X_val_st is not None:
        total += int(X_val_st.nbytes) + int(y_val_st.nbytes)
    return total


@dataclass
class ValidationResult:
    """Metric record for one (model family, grid point)
    (reference ValidatedModel, OpValidator.scala:72).

    The racing scheduler (selector/racing.py) annotates each record with
    its multi-fidelity trajectory: ``rung`` is the highest rung the
    candidate was evaluated at, ``budget_spent`` the fold-fit
    equivalents consumed (full CV = num_folds per candidate), and
    ``pruned_at`` the rung where the racer dropped it (None = survived
    to the final full-fidelity rung). All three stay None/0 — and OUT of
    the JSON — under exact validation, so default summaries are
    byte-identical to pre-racing ones."""
    model_name: str
    model_uid: str
    grid_index: int
    params: Dict
    metric_values: List[float] = field(default_factory=list)
    rung: Optional[int] = None
    budget_spent: float = 0.0
    pruned_at: Optional[int] = None

    @property
    def mean_metric(self) -> float:
        return float(np.mean(self.metric_values))

    def to_json(self) -> dict:
        out = {"modelName": self.model_name, "modelUID": self.model_uid,
               "gridIndex": self.grid_index, "params": self.params,
               "metricValues": [float(v) for v in self.metric_values],
               "meanMetric": self.mean_metric}
        if self.rung is not None:
            out["rung"] = self.rung
            out["budgetSpent"] = float(self.budget_spent)
            out["prunedAt"] = self.pruned_at
        return out

    @classmethod
    def from_json(cls, d: dict) -> "ValidationResult":
        return cls(model_name=d["modelName"], model_uid=d["modelUID"],
                   grid_index=d["gridIndex"], params=dict(d["params"]),
                   metric_values=list(d["metricValues"]),
                   rung=d.get("rung"),
                   budget_spent=d.get("budgetSpent", 0.0),
                   pruned_at=d.get("prunedAt"))


@dataclass
class BestEstimator:
    """Winner of validation (reference BestEstimator,
    OpValidator.scala:62)."""
    estimator: Predictor
    name: str
    params: Dict
    metric: float
    results: List[ValidationResult] = field(default_factory=list)


def _batched_fold_raw(fitted_fold_models, X_val):
    """Raw predictions for every tree-family candidate of one fold in
    one device program (models/trees.batch_predict_raw); {} on a
    backend-shaped failure so the per-candidate path takes over. A
    genuine kernel bug PROPAGATES (r4 narrowed the former blanket
    ``except Exception`` to the runtime's transient/family classifier
    — silently degrading every search to the slow path used to hide
    real defects; lint rule TX-R01 now flags that pattern)."""
    try:
        from ..models.trees import batch_predict_raw
        return batch_predict_raw(fitted_fold_models, X_val)
    except NotImplementedError:
        return {}
    except Exception as e:
        if classify_error(e) == BUG:
            raise
        _log.warning("batched fold evaluation failed (%s: %s); falling "
                     "back to per-candidate predicts",
                     type(e).__name__, e)
        return {}


class _ValidatorBase:
    def __init__(self, evaluator: Evaluator, seed: int = 42,
                 stratify: bool = False, mesh="auto"):
        self.evaluator = evaluator
        self.seed = seed
        self.stratify = stratify
        #: ("models", "data") jax.sharding.Mesh, a policy string, or
        #: None. The default ``"auto"`` resolves LAZILY at search time
        #: (parallel/cv.resolve_search_mesh — constructing a selector
        #: must never initialize a backend): with >1 visible device the
        #: fold x grid candidate axis of every kernel-capable family
        #: shards over chips as ONE SPMD program (parallel/cv.py);
        #: ``None`` forces the local single-device path; results are
        #: bitwise identical either way (docs/distributed.md).
        self.mesh = mesh
        #: fault-tolerance knobs (runtime/; docs/resilience.md) — set
        #: directly or via ModelSelector(checkpoint_dir=..., ...):
        #: journal completed family evaluations here and replay them on
        #: a resumed search
        self.checkpoint_dir: Optional[str] = None
        #: RetryPolicy for transient dispatch failures (None = env
        #: defaults, runtime/retry.py)
        self.retry_policy = None
        #: wall-clock seconds one family's threaded dispatch may take
        #: before it is abandoned + quarantined (None = no deadline)
        self.family_deadline: Optional[float] = None
        #: RuntimeContext of the most recent validate() call — the
        #: selector reads the quarantine ledger from here
        self.last_runtime: Optional[RuntimeContext] = None

    # -- mesh resolution ---------------------------------------------------
    def _resolve_mesh(self):
        """Resolve a mesh policy ("auto"/int/None/Mesh) into a concrete
        mesh ONCE, at search time. Idempotent; the resolved mesh is
        stored back so every dispatch of this search (and the next)
        shares one mesh object — the lru_cache'd family kernels key on
        it."""
        from ..parallel.cv import resolve_search_mesh
        if isinstance(self.mesh, (str, int)):
            self.mesh = resolve_search_mesh(self.mesh)
        return self.mesh

    def mesh_topology(self) -> dict:
        """Topology descriptor of the resolved search mesh — journal
        header metadata (a resume on a different device count replays
        the same metrics; runtime/journal.py)."""
        mesh = self._resolve_mesh()
        if mesh is None:
            return {"devices": 1, "mesh": None}
        return {"devices": int(mesh.size),
                "mesh": {str(k): int(v) for k, v in mesh.shape.items()},
                "platform": mesh.devices.flat[0].platform}

    def _dispatch_workers(self, n_tasks: int) -> int:
        """Concurrent family-dispatch thread budget. Without a mesh:
        one per family up to the core count (threads overlap host
        orchestration + transfers with on-chip compute). With the
        search mesh active every family's kernel is itself an SPMD
        program over the WHOLE mesh — extra host threads would queue
        full-mesh programs against the same chips the sharded rungs
        already occupy (oversubscription buys queueing, not overlap) —
        so the budget is 1 + the device slots the mesh leaves free. A
        family deadline still forces >= 2 workers: deadline abandonment
        only works from the threaded path."""
        workers = min(n_tasks, os.cpu_count() or 1)
        mesh = self._resolve_mesh()
        if mesh is not None:
            import jax
            free = max(0, len(jax.devices()) - int(mesh.size))
            workers = min(workers, 1 + free)
        return workers

    # -- fault-tolerant runtime --------------------------------------------
    @staticmethod
    def _family_key(fi: int, estimator) -> str:
        """Journal/dispatch identity of one family in THIS pool: the
        pool index disambiguates two instances of the same class."""
        return f"{fi}:{type(estimator).__name__}"

    def _begin_runtime(self, models, X, y) -> RuntimeContext:
        """Open this search's RuntimeContext (quarantine ledger + retry
        + optional journal). The journal is keyed by the search
        fingerprint — grid x splits x seed x data — so a stale
        checkpoint from a different search is rotated aside instead of
        mis-replayed."""
        ctx = RuntimeContext(retry=self.retry_policy,
                             family_deadline=self.family_deadline)
        if self.checkpoint_dir and X is not None:
            from ..runtime.journal import search_fingerprint
            params = dict(self.get_params(),
                          validationType=type(self).__name__)
            # mesh topology rides along as header METADATA — it is NOT
            # part of the fingerprint, so a search preempted on one
            # device count resumes on another to the bitwise-identical
            # winner (docs/distributed.md)
            ctx.open_journal(self.checkpoint_dir,
                             search_fingerprint(models, params, X, y),
                             topology=self.mesh_topology())
        self.last_runtime = ctx
        return ctx

    def _results_from_journal(self, estimator, grid, metric_rows
                              ) -> List["ValidationResult"]:
        """ValidationResults rebuilt from journaled per-candidate fold
        vectors — bit-exact (JSON doubles round-trip via repr)."""
        return [
            ValidationResult(
                model_name=type(estimator).__name__,
                model_uid=estimator.uid, grid_index=gi,
                params=dict(params),
                metric_values=[float(v) for v in metric_rows[gi]])
            for gi, params in enumerate(grid)]

    # -- split construction ------------------------------------------------
    def _splits(self, y: np.ndarray
                ) -> List[Tuple[np.ndarray, np.ndarray]]:
        raise NotImplementedError

    def _assignments(self, y: np.ndarray, k: int) -> np.ndarray:
        """Fold id per row; -1 = dropped. Folds are exactly equal-sized
        (up to k-1 remainder rows are dropped): every fold's train set
        then has the same static shape, so one XLA program per family
        covers all folds instead of recompiling per fold — the
        TPU-native replacement for MLUtils.kFold's uneven splits
        (documented deviation; at most k-1 of n rows are unused)."""
        rng = np.random.default_rng(self.seed)
        assign = np.full(len(y), -1, dtype=np.int64)

        def round_robin(idx: np.ndarray):
            m = (len(idx) // k) * k
            perm = rng.permutation(idx)
            assign[perm[:m]] = np.arange(m) % k

        if self.stratify:
            for cls in np.unique(y):
                round_robin(np.nonzero(y == cls)[0])
        else:
            round_robin(np.arange(len(y)))
        return assign

    def _use_batched_kernel(self, estimator) -> bool:
        """Whether to hand this family's grid to its batched fold
        kernel: it must expose one. (r3's ``fold_grid_needs_mesh``
        escape hatch is gone — the MLP's fixed-trip mini-batch solver
        removed the last family whose batched kernel lost to the
        sequential path on one device.)"""
        return hasattr(estimator, "fit_fold_grid_arrays")

    def _try_device_eval(self, estimator, grid, X, y, masks,
                         X_val_st, y_val_st, spec, cand_idx=None):
        """(F, G) metric matrix from the family's fused fit+metric
        device kernel, or None to fall through to the host paths.
        This is the device-resident search: candidates' fitted
        parameters never reach the host — only these floats do (the
        winner is refit from scratch by the selector afterwards).
        ``cand_idx`` (racing rungs) evaluates only that candidate
        subset: the returned matrix is then (F, len(cand_idx))."""
        if (X_val_st is None or spec is None
                or not hasattr(estimator, "eval_fold_grid_arrays")
                or not self._use_batched_kernel(estimator)):
            return None
        kwargs = {} if cand_idx is None else {"cand_idx": cand_idx}
        try:
            return estimator.eval_fold_grid_arrays(
                X, y, masks, grid, X_val_st, y_val_st, spec,
                mesh=self._resolve_mesh(), **kwargs)
        except NotImplementedError:
            return None         # grid/labels not traceable -> host path
        except FamilyPreconditionError as e:
            # family precondition violated (e.g. NaiveBayes on negative
            # features): the sequential path below raises it per fold,
            # dropping the family with NaN metrics instead of failing.
            # Deliberately NOT a blanket ValueError catch — a genuine
            # kernel bug must propagate, not silently degrade every
            # search to the host path.
            _log.warning("device eval kernel for %s rejected the "
                         "data: %s", type(estimator).__name__, e)
            return None

    def _results_from_matrix(self, estimator, grid, mm
                             ) -> List[ValidationResult]:
        return [
            ValidationResult(
                model_name=type(estimator).__name__,
                model_uid=estimator.uid, grid_index=gi,
                params=dict(params),
                metric_values=[float(v) for v in mm[:, gi]])
            for gi, params in enumerate(grid)]

    # -- shared fold/array preparation -------------------------------------
    def _build_fold_arrays(self, X: np.ndarray, y: np.ndarray):
        """(splits, masks, fold_data, spec, X_val_st, y_val_st) — the
        arrays every validation strategy (exact and racing) shares.
        fold_data is materialized ONCE per search; stable array identity
        also lets the tree family's host-side binning memoize per
        fold. This is also where the search mesh resolves: from here on
        every family kernel places the flattened fold x grid candidate
        axis on the mesh's ``models`` axis (parallel/cv.py et al.)."""
        self._resolve_mesh()
        splits = self._splits(y)
        masks = np.zeros((len(splits), len(y)))
        for f, (train_idx, _) in enumerate(splits):
            masks[f, train_idx] = 1.0
        # a feature matrix the compiled prepare plan left on device
        # (plans/prepare.py) stages its folds with device gathers and a
        # device stack — the matrices the search consumes never
        # round-trip through the host (y is host-side by construction)
        xp = np
        if not isinstance(X, (np.ndarray, type(None))) \
                and type(X).__module__.partition(".")[0] != "numpy":
            import jax.numpy as jnp
            xp = jnp
        fold_data = [(X[tr], y[tr], X[va], y[va]) for tr, va in splits]
        # stacked validation folds for the device-resident fast path
        # (fold sizes are equal by _assignments construction)
        spec = self.evaluator.device_metric_spec()
        X_val_st = y_val_st = None
        if spec is not None and len({len(va) for _, va in splits}) == 1:
            X_val_st = xp.stack([fd[2] for fd in fold_data])
            y_val_st = np.stack([fd[3] for fd in fold_data])
        return splits, masks, fold_data, spec, X_val_st, y_val_st

    def _dispatch_device_evals(self, tasks, X, masks, X_val_st, y_val_st,
                               spec, ctx: Optional[RuntimeContext] = None,
                               rung: Optional[int] = None,
                               rung_label: str = "exact"):
        """Run per-family device-eval thunks, threaded when profitable.

        ``tasks`` is [(family_name, family_key, cand_indices, thunk),
        ...]; returns per-task results in order: an (F, G) metric
        matrix, None (no device path — host evaluation takes over), or
        the ``_QUARANTINED`` sentinel.

        Dispatch every family's device kernel BEFORE fetching any
        result: each kernel ends in a blocking device->host fetch, so a
        sequential loop would stall family B's dispatch on family A's
        transfer. Threads overlap host orchestration + transfers with
        on-chip compute (the chip still serializes the programs); JAX
        tracing/dispatch is thread-safe and the shared binning memo in
        models/trees serializes under its own lock.
        size guard: concurrent dispatch keeps EVERY family's input
        buffers + intermediates resident at once — at search sizes
        that's noise, but a huge matrix could push peak HBM past the
        chip where the sequential loop (family A freed before B
        uploads) would have fit. Beyond the cap, dispatch sequentially.
        Workers are capped at os.cpu_count() (more threads than cores
        only adds GIL churn) and each task renames its worker thread to
        ``tx-family-<Name>`` so profiler lanes and the compile-time
        accumulator (utils/compile_time.py) attribute work to a
        family.

        Fault tolerance (runtime/, docs/resilience.md), active when a
        RuntimeContext is supplied:

        - journaled (family, cands, rung) evaluations replay from the
          checkpoint without dispatching anything;
        - transient backend errors (preemption / RESOURCE_EXHAUSTED
          shapes) retry under ``ctx.retry`` with backoff; persistent or
          family-fatal errors quarantine the family (the sentinel) and
          the search continues with survivors — only a classified BUG
          propagates;
        - with ``ctx.family_deadline`` set, a family whose dispatch
          outlives the deadline is abandoned on its thread and
          quarantined, so one hung backend cannot stall the rung
          barrier forever."""
        import threading

        from ..utils import compile_time
        compile_time.install()
        folds = int(masks.shape[0])

        def named(name, fn):
            th = threading.current_thread()
            label = f"tx-family-{name}"
            prev, th.name = th.name, label
            t0 = time.perf_counter()
            c0 = compile_time.compile_seconds_by_thread().get(label, 0.0)
            try:
                return fn()
            finally:
                rec = _FAMILY_PROFILE.setdefault(
                    name, {"seconds": 0.0, "compile": 0.0, "calls": 0})
                rec["seconds"] += time.perf_counter() - t0
                rec["compile"] += (compile_time.compile_seconds_by_thread()
                                   .get(label, 0.0) - c0)
                rec["calls"] += 1
                th.name = prev

        # family spans run on pool worker threads where the context-var
        # stack is empty: parent them explicitly to whatever span was
        # open at dispatch time (the train root / rung span)
        span_parent = _trace.current_ref()

        def run_task(name, key, cands, thunk):
            with _trace.span("search.family", parent=span_parent,
                             family=name, rung=rung_label,
                             cands=len(cands), folds=folds):
                return run_task_traced(name, key, cands, thunk)

        def run_task_traced(name, key, cands, thunk):
            if ctx is not None:
                cached = ctx.journal_lookup(key, rung_label, cands)
                if cached is not None:
                    # journal stores per-candidate fold vectors; the
                    # dispatch contract is (folds, candidates)
                    _trace.add_event("journal.replay", family=name,
                                     rung=rung_label, cands=len(cands))
                    return np.asarray(cached, dtype=np.float64).T

            def attempt():
                maybe_inject("family", name, "dispatch")
                return thunk()

            retries = [0]
            try:
                if ctx is not None:
                    mm = named(name, lambda: ctx.retry.call(
                        attempt, description=f"dispatch:{name}",
                        on_retry=lambda a, e: retries.__setitem__(
                            0, a + 1)))
                else:
                    mm = named(name, attempt)
            except Exception as e:
                kind = classify_error(e)
                if ctx is None or kind == BUG:
                    raise
                ctx.quarantine(
                    name, f"{type(e).__name__}: {e}", kind=kind,
                    error_type=type(e).__name__, rung=rung,
                    retries=retries[0])
                return _QUARANTINED
            if mm is None:
                return None
            if maybe_inject("family", name, "metric") == "nan":
                mm = np.full_like(np.asarray(mm, dtype=np.float64),
                                  np.nan)
            arr = np.asarray(mm, dtype=np.float64)
            if ctx is not None and arr.size:
                bad = 1.0 - float(np.mean(np.isfinite(arr)))
                if bad >= ctx.nan_quarantine_fraction:
                    ctx.quarantine(
                        name,
                        f"{bad:.0%} of device metrics non-finite",
                        kind="metrics", rung=rung)
                    return _QUARANTINED
            _telemetry.note_dispatch(key, rung_label, tuple(cands),
                                     folds)
            if ctx is not None:
                ctx.journal_record(key, rung_label, cands,
                                   arr.T.tolist(), folds)
            return arr

        async_cap = int(os.environ.get("TX_ASYNC_FAMILIES_MAX_BYTES",
                                       256 * 1024 * 1024))
        dispatch_bytes = _async_dispatch_bytes(X, masks, X_val_st,
                                               y_val_st)
        deadline = ctx.family_deadline if ctx is not None else None
        # mesh-slot cap: with the sharded search active, each family's
        # kernel already spans the whole mesh — see _dispatch_workers.
        # A deadline forces the threaded path regardless: abandonment
        # of a hung family only works from a worker thread.
        workers = self._dispatch_workers(len(tasks))
        if deadline is not None:
            workers = min(len(tasks), max(2, workers))
        if (len(tasks) > 1 and workers > 1 and spec is not None
                and dispatch_bytes <= async_cap
                and os.environ.get("TX_ASYNC_FAMILIES", "1") != "0"):
            from concurrent.futures import ThreadPoolExecutor
            from concurrent.futures import TimeoutError as _FutTimeout
            from concurrent.futures import wait as _fut_wait
            ex = ThreadPoolExecutor(max_workers=workers,
                                    thread_name_prefix="tx-family")
            futures = [ex.submit(run_task, *t) for t in tasks]
            t_submit = time.monotonic()
            results, kill = [], None
            for (name, _, _, _), f in zip(tasks, futures):
                try:
                    timeout = (None if deadline is None else max(
                        0.05, deadline - (time.monotonic() - t_submit)))
                    results.append(f.result(timeout=timeout))
                except _FutTimeout:
                    ctx.quarantine(
                        name,
                        f"family dispatch exceeded the {deadline:g}s "
                        f"deadline (backend hung or wedged); thread "
                        f"abandoned", kind="deadline", rung=rung)
                    results.append(_QUARANTINED)
                except BaseException as e:
                    # only classified bugs and KillPoints reach here —
                    # run_task absorbs everything quarantinable. Drain
                    # the remaining in-flight families first so their
                    # journal records land (a resumed search must not
                    # lose work that actually completed), then re-raise.
                    kill = e
                    results.append(_QUARANTINED)
            if kill is not None:
                _fut_wait(futures, timeout=deadline or 30.0)
                ex.shutdown(wait=False)
                raise kill
            # with a deadline, an abandoned thread may still be running:
            # do not join it — the whole point is not to wait forever
            ex.shutdown(wait=deadline is None)
            return results
        return [run_task(*t) for t in tasks]

    def _device_matrices(self, models, X, y, masks, X_val_st, y_val_st,
                         spec, ctx: Optional[RuntimeContext] = None):
        """Per-family (F, G) device metric matrices (None entries fall
        through to the host paths; ``_QUARANTINED`` entries are out of
        the search)."""
        tasks = [
            (type(est).__name__, self._family_key(fi, est),
             tuple(range(len(grid))),
             (lambda e=est, g=grid: self._try_device_eval(
                 e, g, X, y, masks, X_val_st, y_val_st, spec)))
            for fi, (est, grid) in enumerate(models)]
        return self._dispatch_device_evals(tasks, X, masks, X_val_st,
                                           y_val_st, spec, ctx=ctx)

    def _family_host_results(self, estimator, grid, X, y, masks,
                             fold_data) -> List[ValidationResult]:
        """Host evaluation of one family: batched fold x grid kernel when
        available, per-candidate sequential fits otherwise."""
        results: List[ValidationResult] = []
        # fast path: families exposing a fold x grid kernel train all
        # candidates in ONE batched XLA program (mesh-sharded when
        # self.mesh is set) instead of len(grid) x folds fits
        fitted = None
        if self._use_batched_kernel(estimator):
            try:
                fitted = estimator.fit_fold_grid_arrays(
                    X, y, masks, grid, mesh=self._resolve_mesh())
            except NotImplementedError:
                fitted = None   # grid not traceable -> sequential
            except FamilyPreconditionError as e:
                # family precondition violated (e.g. NaiveBayes on
                # negative features): the sequential path raises it
                # per fold below, dropping the family out of the
                # race with NaN metrics instead of failing the search
                _log.warning("batched kernel for %s rejected the "
                             "data: %s", type(estimator).__name__, e)
                fitted = None
        # batched evaluation: all tree-family candidates of a fold
        # predict in ONE device program (others fall through to the
        # per-candidate path)
        fold_raw = ([_batched_fold_raw(fitted[f], fold_data[f][2])
                     for f in range(len(fold_data))]
                    if fitted is not None else None)
        for gi, params in enumerate(grid):
            candidate = (None if fitted is not None
                         else estimator.with_params(**params))
            res = ValidationResult(
                model_name=type(estimator).__name__,
                model_uid=estimator.uid, grid_index=gi,
                params=dict(params))
            for f, (X_tr, y_tr, X_val, y_val) in enumerate(fold_data):
                try:
                    if fitted is not None:
                        model: PredictionModel = fitted[f][gi]
                        raw = fold_raw[f].get(gi)
                        pred = (model.prediction_from_raw(raw)
                                if raw is not None
                                else model.predict_arrays(X_val))
                    else:
                        model = candidate.fit_arrays_guarded(X_tr, y_tr)
                        pred = model.predict_arrays(X_val)
                    metrics = self.evaluator.evaluate_arrays(
                        y_val, pred)
                    res.metric_values.append(
                        self.evaluator.metric_from(metrics))
                except (ValueError, FloatingPointError) as e:
                    # a family whose preconditions the data violates
                    # (e.g. NaiveBayes on negative features) drops out
                    # of the race instead of failing the whole search
                    _log.warning("candidate %s%s failed on a fold: %s",
                                 res.model_name, params, e)
                    res.metric_values.append(float("nan"))
            results.append(res)
        return results

    def _host_results_journaled(self, fi, estimator, grid, X, y, masks,
                                fold_data, ctx: RuntimeContext
                                ) -> List[ValidationResult]:
        """Host evaluation of one family behind the runtime: journal
        replay first, quarantine-on-classified-failure, journal append
        on success. Label ``"exact-host"`` keeps host metric vectors
        from ever replaying into the device-matrix path (they are
        float-identical in theory, but the journal's contract is
        bit-exactness, not theory)."""
        key = self._family_key(fi, estimator)
        cands = tuple(range(len(grid)))
        cached = ctx.journal_lookup(key, "exact-host", cands)
        if cached is not None:
            _trace.add_event("journal.replay",
                             family=type(estimator).__name__,
                             rung="exact-host", cands=len(cands))
            return self._results_from_journal(estimator, grid, cached)
        try:
            with _trace.span("search.family",
                             family=type(estimator).__name__,
                             rung="exact-host", path="host",
                             cands=len(cands), folds=len(fold_data)):
                host = self._family_host_results(estimator, grid, X, y,
                                                 masks, fold_data)
        except Exception as e:
            kind = classify_error(e)
            if kind == BUG:
                raise
            ctx.quarantine(type(estimator).__name__,
                           f"{type(e).__name__}: {e}", kind=kind,
                           error_type=type(e).__name__)
            return []
        _telemetry.note_dispatch(key, "exact-host", cands,
                                 len(fold_data))
        ctx.journal_record(key, "exact-host", cands,
                           [r.metric_values for r in host],
                           len(fold_data))
        return host

    # -- main loop (reference getSummary, OpValidator.scala:270-310) -------
    def validate(self,
                 models: Sequence[Tuple[Predictor, Sequence[Dict]]],
                 X: np.ndarray, y: np.ndarray) -> BestEstimator:
        models = [(est, list(grid) or [{}]) for est, grid in models]
        ctx = self._begin_runtime(models, X, y)
        try:
            _, masks, fold_data, spec, X_val_st, y_val_st = \
                self._build_fold_arrays(X, y)
            results: List[ValidationResult] = []
            device_mm = self._device_matrices(models, X, y, masks,
                                              X_val_st, y_val_st, spec,
                                              ctx=ctx)
            for fi, ((estimator, grid), mm) in enumerate(
                    zip(models, device_mm)):
                if mm is _QUARANTINED:
                    continue
                if mm is not None:
                    results.extend(self._results_from_matrix(
                        estimator, grid, mm))
                    continue
                results.extend(self._host_results_journaled(
                    fi, estimator, grid, X, y, masks, fold_data, ctx))
        finally:
            ctx.close_journal()
        return self._pick_best(models, results, ctx=ctx)

    def validate_prepared(self,
                          models: Sequence[Tuple[Predictor, Sequence[Dict]]],
                          folds: Sequence[Tuple[np.ndarray, np.ndarray,
                                                np.ndarray, np.ndarray]]
                          ) -> BestEstimator:
        """Validate over pre-materialized per-fold data — the
        workflow-level-CV entry point (reference OpValidator.applyDAG:228
        + getSummary): each fold's in-CV DAG segment was refit on that
        fold's train rows, so feature matrices may differ across folds
        (even in width). ``folds`` is [(X_tr, y_tr, X_val, y_val), ...].
        Grid batching still applies per fold via the family kernels.

        Fault tolerance: a family whose evaluation raises a classified
        transient/family error is quarantined (the workflow-CV search
        degrades to survivors exactly like the array-level path); the
        per-fold journal is NOT written here — fold matrices differ per
        refit DAG segment, so there is no stable fingerprint to key a
        resume on (docs/resilience.md)."""
        spec = self.evaluator.device_metric_spec()
        self._resolve_mesh()
        models = [(est, list(grid) or [{}]) for est, grid in models]
        ctx = self._begin_runtime(models, None, None)
        results: List[ValidationResult] = []
        for estimator, grid in models:
            try:
                fam = self._prepared_family_results(
                    estimator, grid, folds, spec)
            except Exception as e:
                kind = classify_error(e)
                if kind == BUG:
                    raise
                ctx.quarantine(type(estimator).__name__,
                               f"{type(e).__name__}: {e}", kind=kind,
                               error_type=type(e).__name__)
                continue
            results.extend(fam)
        return self._pick_best(models, results, ctx=ctx)

    def _prepared_family_results(self, estimator, grid, folds, spec
                                 ) -> List[ValidationResult]:
        """One family's results over pre-materialized folds (the body
        validate_prepared quarantines as a unit)."""
        results: List[ValidationResult] = []
        # device-resident fast path, one fold at a time (fold
        # matrices may differ in shape after per-fold DAG refits,
        # so they cannot stack into one kernel call)
        mm = None
        if spec is not None:
            rows = []
            for X_tr, y_tr, X_val, y_val in folds:
                row = self._try_device_eval(
                    estimator, grid, X_tr, y_tr,
                    np.ones((1, len(y_tr))), X_val[None],
                    np.asarray(y_val)[None], spec)
                if row is None:
                    break
                rows.append(row[0])
            else:
                mm = np.stack(rows) if rows else None
        if mm is not None:
            return self._results_from_matrix(estimator, grid, mm)
        fitted = None
        if self._use_batched_kernel(estimator):
            try:
                fitted = [
                    estimator.fit_fold_grid_arrays(
                        X_tr, y_tr, np.ones((1, len(y_tr))), grid,
                        mesh=self._resolve_mesh())[0]
                    for X_tr, y_tr, _, _ in folds]
            except NotImplementedError:
                fitted = None
            except FamilyPreconditionError as e:
                _log.warning("batched kernel for %s rejected the "
                             "data: %s", type(estimator).__name__, e)
                fitted = None
        fold_raw = ([_batched_fold_raw(fitted[f], folds[f][2])
                     for f in range(len(folds))]
                    if fitted is not None else None)
        for gi, params in enumerate(grid):
            candidate = (None if fitted is not None
                         else estimator.with_params(**params))
            res = ValidationResult(
                model_name=type(estimator).__name__,
                model_uid=estimator.uid, grid_index=gi,
                params=dict(params))
            for f, (X_tr, y_tr, X_val, y_val) in enumerate(folds):
                try:
                    model = (fitted[f][gi] if fitted is not None
                             else candidate.fit_arrays_guarded(X_tr, y_tr))
                    raw = (fold_raw[f].get(gi)
                           if fitted is not None else None)
                    pred = (model.prediction_from_raw(raw)
                            if raw is not None
                            else model.predict_arrays(X_val))
                    metrics = self.evaluator.evaluate_arrays(y_val, pred)
                    res.metric_values.append(
                        self.evaluator.metric_from(metrics))
                except (ValueError, FloatingPointError) as e:
                    _log.warning("candidate %s%s failed on a fold: %s",
                                 res.model_name, params, e)
                    res.metric_values.append(float("nan"))
            results.append(res)
        return results

    def _pick_best(self, models, results: List[ValidationResult],
                   rank_pool: Optional[List[ValidationResult]] = None,
                   ctx: Optional[RuntimeContext] = None
                   ) -> BestEstimator:
        """Winner among ``rank_pool`` (default: all results). Racing
        passes only full-fidelity finalists — a pruned candidate's
        low-fidelity metric is not comparable to a full-CV one — while
        every record still lands in ``BestEstimator.results``."""
        sign = 1.0 if self.evaluator.is_larger_better else -1.0
        pool = results if rank_pool is None else rank_pool
        finite = [r for r in pool if np.isfinite(r.mean_metric)]
        if not finite:
            if ctx is not None and ctx.quarantined:
                # nothing survived the quarantine ledger: ONE aggregated
                # error naming every family and reason, instead of
                # whichever family died first
                raise AllFamiliesFailedError(
                    ctx.quarantined,
                    detail="no family produced a finite validation "
                           "metric")
            raise ValueError(
                "all validation metrics are non-finite; cannot select a "
                "model (check for degenerate folds — e.g. a fold with a "
                "single class; stratify=True may help)")
        best = max(finite, key=lambda r: sign * r.mean_metric)
        by_uid = {est.uid: est for est, _ in models}
        winner = by_uid[best.model_uid].with_params(**best.params)
        return BestEstimator(estimator=winner, name=best.model_name,
                             params=best.params, metric=best.mean_metric,
                             results=results)


class CrossValidation(_ValidatorBase):
    """k-fold CV (reference OpCrossValidation.scala:40,71)."""

    validation_type = "CrossValidation"

    def __init__(self, evaluator: Evaluator, num_folds: int = 3,
                 seed: int = 42, stratify: bool = False, mesh="auto"):
        super().__init__(evaluator, seed, stratify, mesh=mesh)
        if num_folds < 2:
            raise ValueError("num_folds must be >= 2")
        self.num_folds = num_folds

    def _splits(self, y):
        assign = self._assignments(y, self.num_folds)
        return [(np.nonzero((assign != f) & (assign >= 0))[0],
                 np.nonzero(assign == f)[0])
                for f in range(self.num_folds)]

    def get_params(self):
        return {"numFolds": self.num_folds, "seed": self.seed,
                "stratify": self.stratify}


class TrainValidationSplit(_ValidatorBase):
    """Single random split (reference OpTrainValidationSplit.scala:48)."""

    validation_type = "TrainValidationSplit"

    def __init__(self, evaluator: Evaluator, train_ratio: float = 0.75,
                 seed: int = 42, stratify: bool = False, mesh="auto"):
        super().__init__(evaluator, seed, stratify, mesh=mesh)
        if not 0.0 < train_ratio < 1.0:
            raise ValueError("train_ratio must be in (0, 1)")
        self.train_ratio = train_ratio

    def _splits(self, y):
        # exact single split honoring train_ratio (stratified on request)
        from .splitters import stratified_split
        rng = np.random.default_rng(self.seed)
        if self.stratify:
            train_idx, val_idx = stratified_split(
                y, 1.0 - self.train_ratio, rng)
        else:
            perm = rng.permutation(len(y))
            n_val = int(round(len(y) * (1.0 - self.train_ratio)))
            train_idx, val_idx = np.sort(perm[n_val:]), np.sort(perm[:n_val])
        if len(val_idx) == 0 or len(train_idx) == 0:
            raise ValueError(
                f"train_ratio={self.train_ratio} leaves an empty train or "
                f"validation set for n={len(y)} rows")
        return [(train_idx, val_idx)]

    def get_params(self):
        return {"trainRatio": self.train_ratio, "seed": self.seed,
                "stratify": self.stratify}
