"""Compiled serving: fuse a fitted workflow DAG into batched, jitted,
shape-bucketed XLA scoring programs (docs/serving.md)."""
from .plan import (PlanCompileError, PlanCoverage, ScoringPlan,
                   bucket_for, plan_compiles)

__all__ = ["ScoringPlan", "PlanCoverage", "PlanCompileError",
           "plan_compiles", "bucket_for"]
