"""Compiled serving: fuse a fitted workflow DAG into batched, jitted,
shape-bucketed XLA scoring programs (docs/serving.md), with optional
serving guardrails — schema admission, per-row quarantine, output
guards, a scoring circuit breaker and an online drift sentinel
(docs/serving_guardrails.md) — and an async micro-batching serving
loop that coalesces live requests into compiled bucket dispatches
under latency SLOs (docs/serving_loop.md), a reconnecting TCP client,
and a self-healing model lifecycle — drift-triggered background
retraining with canary validation, atomic hot-swap, and instant
rollback (docs/self_healing.md) — plus preemption tolerance: graceful
drain on SIGTERM and a warm-state snapshot that a restart restores
behind a readiness gate (docs/serving_restart.md), and a fleet layer — N supervised replicas
behind a fault-tolerant placement router with warm takeover and
fleet-coherent overload control (docs/fleet.md)."""
from .client import ServingUnavailable, TcpServingClient
from .fleet import ReplicaManager, ReplicaSpec, wait_port_ready
from .router import (BackendUnavailable, FleetRouter, ReplicaHandle,
                     RouterConfig, merge_admission)
from .guard import (AdmissionPolicy, BreakerOpenError, CircuitBreaker,
                    GuardedScoreResult, GuardReason, OutputGuard,
                    SchemaGuard, ServingGuard)
from .lifecycle import LifecycleConfig, ModelLifecycle
from .plan import (EncodedScoreBatch, PlanCompileError, PlanCoverage,
                   ScoringPlan, bucket_for, plan_compiles)
from .sentinel import (DriftSentinel, DriftThresholds,
                       FeatureFingerprint, FingerprintSchemaError,
                       compute_fingerprints, load_fingerprint_doc,
                       load_fingerprints, save_fingerprints)
from .admission import (AdmissionConfig, AdmissionController,
                        ServeShed)
from .server import (PlanCache, ServeConfig, ServeDraining,
                     ServeRejected, ServingClient, ServingServer,
                     serve_in_process)
from .state import (SNAPSHOT_SCHEMA, ServingStateSnapshot,
                    StateManager)

__all__ = ["ScoringPlan", "EncodedScoreBatch", "PlanCoverage",
           "PlanCompileError", "plan_compiles", "bucket_for",
           "AdmissionPolicy", "SchemaGuard", "OutputGuard",
           "CircuitBreaker", "BreakerOpenError", "ServingGuard",
           "GuardReason", "GuardedScoreResult",
           "DriftSentinel", "DriftThresholds", "FeatureFingerprint",
           "FingerprintSchemaError", "compute_fingerprints",
           "save_fingerprints", "load_fingerprints",
           "load_fingerprint_doc",
           "ServeConfig", "ServingServer", "ServingClient", "PlanCache",
           "ServeRejected", "ServeDraining", "ServeShed",
           "AdmissionConfig", "AdmissionController",
           "serve_in_process",
           "LifecycleConfig", "ModelLifecycle",
           "ServingStateSnapshot", "StateManager", "SNAPSHOT_SCHEMA",
           "TcpServingClient", "ServingUnavailable",
           "FleetRouter", "RouterConfig", "ReplicaHandle",
           "BackendUnavailable", "merge_admission",
           "ReplicaManager", "ReplicaSpec", "wait_port_ready"]
