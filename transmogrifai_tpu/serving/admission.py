"""Overload-robust admission control for the serving loop
(docs/admission.md).

The coalescing loop (serving/server.py) survives crashes, bad rows and
drift — but not *load*: before this module, lane queues grew without
bound, a burst above device capacity stretched every queued request's
latency, and one flooding tenant starved every other lane behind the
shared dispatch semaphore. The overload-control literature's answer
(PAPERS.md: SLO-aware serving admission a la Clipper/INFaaS) is to
admit-or-shed AT THE DOOR using a predicted-cost budget — serve fewer
requests on time instead of serving everyone late. Four mechanisms,
one :class:`AdmissionController` on the enqueue edge:

- **Bounded lanes + machine-readable shed.** Every (model, tenant)
  lane queue is bounded at ``serving.admission_queue_rows`` (a tuning
  knob). Overflow raises :class:`ServeShed`, which the TCP front end
  turns into ``{"ok": false, "shed": true, "retry_after_ms": N}`` —
  the hint derived from the CURRENT queue's predicted drain time, so
  a well-behaved client (serving/client.py) backs off exactly as long
  as the backlog needs.
- **Cost-model deadline admission.** With a tenant deadline budget
  configured, a request is admitted only if its predicted completion
  — queue wait (backlog rows / measured drain rate) + coalesce wait +
  predicted encode+dispatch for the target bucket (the PR-13
  :class:`~..tuning.model.CostModel`) — fits the budget. Under
  overload the loop sheds EARLY, at enqueue, instead of paying queue
  time on a request that was already doomed to miss its SLO.
- **Weighted deficit-round-robin fair queuing.** Dispatch grants are
  scheduled across tenant lanes by classic DRR (deficit += quantum x
  weight per round, a lane dispatches when its deficit covers the
  batch's rows), with a per-tenant token bucket refilled at the
  tenant's weighted share of the measured drain rate. The bucket is
  enforced ONLY under contention — a lone tenant takes the whole
  device (idle shares redistribute), a noisy neighbor is capped at
  its share the moment a victim shows up.
- **Brownout state machine.** Sustained pressure (busiest lane's
  backlog vs its bound) walks ``ok -> brownout -> shed`` with
  hysteresis dwells on every edge. Brownout cuts the coalescer's
  max-wait (smaller, sooner batches: the loop trades occupancy for
  latency headroom) and sheds the LOWEST-weight tenants first; shed
  refuses all new work until pressure clears the exit threshold for
  the exit dwell. Every transition lands in telemetry
  (``serve_brownout_transitions``), a span (``serve.admission_state``)
  and ``metrics_snapshot()["admission"]``.

Determinism: the controller takes an injectable ``clock`` (the fake-
clock hysteresis tests pin it), fires no timers of its own (state is
re-evaluated on enqueue/dispatch events), and the ``burst`` fault
(``TX_FAULT_PLAN="admission:<model>:enqueue:1=burst:512"``,
runtime/faults.py) registers a phantom arrival spike against a lane so
every shed/brownout path is drillable without real load.

``ServeConfig(admission_control=None)`` — the default, and the
``tx serve --admission=off`` escape hatch — constructs no controller:
the enqueue edge, dispatch semaphore and answers are byte-identical
to a build without this module.
"""
from __future__ import annotations

import asyncio
import collections
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..observability import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.faults import maybe_inject

__all__ = ["AdmissionConfig", "AdmissionController", "ServeShed",
           "OK", "BROWNOUT", "SHED"]

#: brownout states (docs/admission.md — the state machine)
OK = "ok"
BROWNOUT = "brownout"
SHED = "shed"

#: drain-rate fallback before any dispatch has been measured and the
#: cost model has no score:b* records (rows/second; only shapes the
#: retry_after_ms HINT, never an admit/shed verdict on its own)
_FALLBACK_DRAIN_ROWS_PER_S = 500.0

#: EWMA smoothing for the measured drain rate
_DRAIN_ALPHA = 0.3

#: retry_after_ms hint clamp
_RETRY_MIN_MS, _RETRY_MAX_MS = 1, 5000

#: per-lane shed-event log throttle (seconds): during a shed storm at
#: most one serve_request_shed event per lane per window is formatted,
#: carrying a ``suppressed`` count for the rest
_SHED_LOG_INTERVAL_S = 0.25


class ServeShed(RuntimeError):
    """A request was shed by admission control (queue bound, deadline
    budget, quota, or brownout). Carries the machine-readable retry
    hint the TCP front end echoes (``"shed": true,
    "retry_after_ms": N``). The message is RESOURCE_EXHAUSTED-shaped
    so ``classify_error`` triages it transient — shed is the server
    protecting its SLO, not a verdict on the request."""

    def __init__(self, model: str, tenant: str, reason: str,
                 retry_after_ms: int):
        self.model = model
        self.tenant = tenant
        self.reason = reason
        self.retry_after_ms = int(retry_after_ms)
        super().__init__(
            f"RESOURCE_EXHAUSTED: lane {model}/{tenant} shed under "
            f"overload ({reason}); retry after "
            f"{self.retry_after_ms}ms")


@dataclass
class AdmissionConfig:
    """Knobs of the admission controller (docs/admission.md). ``None``
    numeric fields resolve through the tuning policy
    (tuning/registry.py + tuning/policy.py) — with an empty store or
    ``TX_TUNE=off`` they land bitwise on the static defaults."""
    #: tenant name -> DRR weight / quota share (unlisted tenants: 1.0)
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    #: per-request completion budget (ms): a float applies to every
    #: tenant, a dict maps tenant -> budget (missing tenants
    #: unbudgeted). None disables deadline admission.
    tenant_deadline_ms: Union[None, float, Dict[str, float]] = None
    #: per-lane queue bound in rows; None -> serving.admission_queue_rows
    queue_rows: Optional[int] = None
    #: DRR quantum in rows; None -> serving.admission_quantum
    quantum_rows: Optional[int] = None
    #: brownout enter: busiest-lane pressure >= this for enter_seconds
    brownout_enter_ratio: float = 0.75
    #: brownout exit: pressure <= this for exit_seconds (hysteresis)
    brownout_exit_ratio: float = 0.35
    #: shed escalation: pressure >= this (the lane bound itself)
    shed_enter_ratio: float = 1.0
    brownout_enter_seconds: float = 0.25
    brownout_exit_seconds: float = 0.5
    #: coalescer max-wait multiplier while browned out (smaller,
    #: sooner batches)
    brownout_wait_factor: float = 0.25
    #: token-bucket burst, in multiples of the refill share per second
    token_burst_seconds: float = 0.25
    #: injectable time source (tests pin a fake clock)
    clock: Optional[Callable[[], float]] = None


class _TenantState:
    """Per-tenant accounting: admitted/shed counters + token bucket."""

    __slots__ = ("admitted", "shed", "tokens", "refilled_at")

    def __init__(self, now: float):
        self.admitted = 0
        self.shed = 0
        self.tokens: Optional[float] = None   # armed on first refill
        self.refilled_at = now


class AdmissionController:
    """The enqueue-edge gatekeeper + dispatch-grant scheduler. One per
    :class:`~.server.ServingServer`; every method runs on the server's
    event loop (single-threaded — no locks needed)."""

    def __init__(self, config: AdmissionConfig,
                 tuning: Optional[Any] = None,
                 max_batch: int = 256,
                 max_wait_ms: float = 5.0):
        self.config = config
        self.clock = config.clock or time.monotonic
        now = self.clock()
        #: knob resolution (override -> model -> static); the decision
        #: records surface in metrics_snapshot()["admission"]
        self.decisions: List[Any] = []
        queue_rows, quantum = config.queue_rows, config.quantum_rows
        dispatch_s = None
        if tuning is not None:
            qd = tuning.admission_queue_rows(max_batch)
            nd = tuning.admission_quantum()
            self.decisions = [qd, nd]
            if queue_rows is None:
                queue_rows = int(qd.chosen)
            if quantum is None:
                quantum = int(nd.chosen)
            known = tuning.model.recorded_buckets("score")
            rates = [(b / max(e.execute or e.wall or 0.0, 1e-9), e)
                     for b, e in known.items()
                     if b <= max_batch and (e.execute or e.wall)]
            if rates:
                # seed the drain-rate estimate from cross-run history
                rate, est = max(rates, key=lambda p: p[0])
                self._drain_rows_per_s = rate
                dispatch_s = est.execute or est.wall
        if not hasattr(self, "_drain_rows_per_s"):
            self._drain_rows_per_s = _FALLBACK_DRAIN_ROWS_PER_S
        from ..tuning.registry import STATIC_DEFAULTS as _D
        self.queue_rows = int(queue_rows if queue_rows is not None
                              else _D["serving.admission_queue_rows"])
        self.quantum = int(quantum if quantum is not None
                           else _D["serving.admission_quantum"])
        #: predicted per-batch encode+dispatch seconds (deadline math)
        self._dispatch_seconds = dispatch_s
        self.max_wait_ms = float(max_wait_ms)
        #: brownout FSM
        self.state = OK
        self.transitions = 0
        self._state_since = now
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._pressure = 0.0
        #: per-tenant accounting
        self._tenants: Dict[str, _TenantState] = {}
        #: burst-fault phantom backlog: lane key -> (rows, stamped at)
        self._phantom: Dict[Tuple[str, str], Tuple[float, float]] = {}
        #: DRR dispatch-grant gate (replaces the off-path semaphore)
        self._busy = False
        self._waiters: "collections.OrderedDict[str, collections.deque]" \
            = collections.OrderedDict()
        self._ring: "collections.deque[str]" = collections.deque()
        self._deficit: Dict[str, float] = {}
        self._waiting = 0
        #: has the ring head received its per-visit quantum credit yet
        self._head_credited = False
        #: wall-clock drain learning (note_dispatch): previous dispatch
        #: completion stamp and the backlog that existed at it
        self._prev_dispatch_at: Optional[float] = None
        self._prev_backlog_rows = 0
        #: shed-storm log throttle: lane -> (last event stamp,
        #: sheds suppressed since) — a 10k/s shed storm must not turn
        #: into 10k/s of event-log formatting on the event loop thread
        self._shed_logged: Dict[Tuple[str, str], Tuple[float, int]] = {}

    # -- weights / budgets -------------------------------------------------
    def weight(self, tenant: str) -> float:
        return float(self.config.tenant_weights.get(tenant, 1.0))

    def _deadline_ms(self, tenant: str) -> Optional[float]:
        d = self.config.tenant_deadline_ms
        if d is None:
            return None
        if isinstance(d, dict):
            v = d.get(tenant, d.get("default"))
            return None if v is None else float(v)
        return float(d)

    def _tenant(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = _TenantState(self.clock())
        return st

    # -- drain-rate / cost predictions -------------------------------------
    def note_dispatch(self, rows: int, seconds: float,
                      total_queued_rows: int = 0) -> None:
        """Feed one completed dispatch: updates the measured drain
        rate (EWMA) the retry hints and deadline math use, and
        re-evaluates the brownout FSM as the backlog drains."""
        now = self.clock()
        if seconds > 1e-9 and rows > 0:
            # while a backlog existed across the gap, the WALL time
            # since the previous dispatch is the honest drain
            # denominator — busy seconds alone ignore encode waits,
            # grant waits, and host contention, so they overestimate
            # capacity exactly when the loop is overloaded and the
            # deadline gate most needs the truth
            denom = seconds
            if self._prev_dispatch_at is not None \
                    and self._prev_backlog_rows > 0:
                denom = max(seconds, now - self._prev_dispatch_at)
            rate = rows / denom
            self._drain_rows_per_s = (
                (1 - _DRAIN_ALPHA) * self._drain_rows_per_s
                + _DRAIN_ALPHA * rate)
            if self._dispatch_seconds is None:
                self._dispatch_seconds = seconds
            else:
                self._dispatch_seconds = (
                    (1 - _DRAIN_ALPHA) * self._dispatch_seconds
                    + _DRAIN_ALPHA * seconds)
        self._prev_dispatch_at = now
        self._prev_backlog_rows = int(total_queued_rows)
        self._observe(total_queued_rows / max(self.queue_rows, 1))

    def _drain_ms(self, rows: float) -> float:
        return 1000.0 * max(rows, 0.0) \
            / max(self._drain_rows_per_s, 1e-6)

    def retry_after_ms(self, backlog_rows: float) -> int:
        """Predicted time for ``backlog_rows`` to drain at the current
        measured rate — the shed answer's machine-readable hint."""
        return int(min(max(round(self._drain_ms(backlog_rows)),
                           _RETRY_MIN_MS), _RETRY_MAX_MS))

    def _phantom_rows(self, key: Tuple[str, str], now: float) -> float:
        """Remaining rows of an injected ``burst`` spike against this
        lane, draining at the measured rate since injection."""
        rec = self._phantom.get(key)
        if rec is None:
            return 0.0
        rows, t0 = rec
        left = rows - (now - t0) * self._drain_rows_per_s
        if left <= 0:
            del self._phantom[key]
            return 0.0
        return left

    # -- the brownout FSM --------------------------------------------------
    def _observe(self, pressure: float) -> None:
        """Walk ok -> brownout -> shed on sustained pressure (busiest
        lane backlog / lane bound), with hysteresis dwells both ways.
        Called on every enqueue attempt and dispatch completion — the
        FSM owns no timer."""
        now = self.clock()
        self._pressure = pressure
        cfg = self.config
        if pressure >= cfg.brownout_enter_ratio:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            sustained = now - self._above_since \
                >= cfg.brownout_enter_seconds
            if self.state == OK and sustained:
                self._set_state(BROWNOUT, now)
            if self.state == BROWNOUT and sustained \
                    and pressure >= cfg.shed_enter_ratio:
                self._set_state(SHED, now)
        elif pressure <= cfg.brownout_exit_ratio:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if self.state != OK and now - self._below_since \
                    >= cfg.brownout_exit_seconds:
                # recovery steps DOWN one level per dwell — shed
                # re-enters brownout first, never snaps straight to ok
                self._set_state(BROWNOUT if self.state == SHED else OK,
                                now)
                self._below_since = now
        else:
            # the hysteresis band: neither dwell accumulates
            self._above_since = self._below_since = None

    def _set_state(self, new_state: str, now: float) -> None:
        old = self.state
        if new_state == old:
            return
        if _trace.enabled():
            _trace.add_span("serve.admission_state", self._state_since,
                            now, attrs={"state": old, "to": new_state,
                                        "pressure": round(
                                            self._pressure, 4)})
        self.state = new_state
        self.transitions += 1
        self._state_since = now
        _telemetry.count("serve_brownout_transitions")
        _telemetry.event("serve_brownout_transition", prev=old,
                         state=new_state,
                         pressure=round(self._pressure, 4))

    def effective_max_wait_ms(self, base_ms: float) -> float:
        """The coalescer's deadline under the current state: browned
        out, the loop dispatches smaller batches sooner."""
        if self.state == OK:
            return base_ms
        return base_ms * self.config.brownout_wait_factor

    def _brownout_sheds(self, tenant: str) -> bool:
        """Brownout sheds the LOWEST-priority tenants first: any
        tenant weighted strictly below the heaviest registered weight.
        With uniform weights no tenant outranks another and brownout
        relies on the queue bound + deadline budget alone."""
        weights = self.config.tenant_weights
        if not weights:
            return False
        top = max(max(weights.values()), 1.0)
        return self.weight(tenant) < top

    # -- the enqueue-edge verdict ------------------------------------------
    def admit(self, model: str, tenant: str, queued_rows: int,
              tenant_backlog: Optional[Dict[str, int]] = None) -> None:
        """Admit-or-shed for ONE arriving request. ``queued_rows`` is
        this lane's current depth; ``tenant_backlog`` maps tenant ->
        queued rows across all lanes (contention detection + quota
        shares). Raises :class:`ServeShed` with the retry hint, or
        returns None (admitted)."""
        now = self.clock()
        key = (model, tenant)
        fault = maybe_inject("admission", model, "enqueue")
        if fault and fault.startswith("burst"):
            # an injected arrival spike: phantom rows queue against
            # this lane so shed/brownout paths fire without real load
            _, _, n = fault.partition(":")
            rows = float(n or "256")
            prev = self._phantom_rows(key, now)
            self._phantom[key] = (prev + rows, now)
            _telemetry.count("serve_burst_injected")
            _telemetry.event("serve_burst_injected", model=model,
                             tenant=tenant, rows=rows)
        eff_rows = queued_rows + self._phantom_rows(key, now)
        self._observe(eff_rows / max(self.queue_rows, 1))
        st = self._tenant(tenant)
        backlog = tenant_backlog or {}
        # 1) brownout / shed state gating (lowest-priority first)
        if self.state == SHED or (
                self.state == BROWNOUT and self._brownout_sheds(tenant)):
            self._shed(st, model, tenant,
                       f"{self.state} state (pressure "
                       f"{self._pressure:.2f})", eff_rows)
        # 2) the lane queue bound
        if eff_rows >= self.queue_rows:
            self._shed(st, model, tenant,
                       f"lane queue at its {self.queue_rows}-row "
                       f"admission bound", eff_rows)
        # 3) cost-model deadline budget
        budget_ms = self._deadline_ms(tenant)
        if budget_ms is not None:
            wait_ms = self._drain_ms(eff_rows)
            batch_ms = 1000.0 * (self._dispatch_seconds
                                 if self._dispatch_seconds is not None
                                 else self.max_wait_ms / 1000.0)
            predicted = wait_ms + self.max_wait_ms + batch_ms
            if predicted > budget_ms:
                self._shed(st, model, tenant,
                           f"predicted completion {predicted:.0f}ms "
                           f"exceeds the {budget_ms:.0f}ms deadline "
                           f"budget", eff_rows)
        # 4) token-bucket quota — enforced only under contention
        others = sum(v for t, v in backlog.items() if t != tenant)
        if others > 0 and self._waiting + len(backlog) > 1:
            share = self.weight(tenant) / max(
                sum(self.weight(t) for t, v in backlog.items()
                    if v > 0 or t == tenant), 1e-9)
            rate = share * self._drain_rows_per_s
            burst = max(rate * self.config.token_burst_seconds, 1.0)
            if st.tokens is None:
                st.tokens = burst
            else:
                st.tokens = min(
                    burst,
                    st.tokens + (now - st.refilled_at) * rate)
            st.refilled_at = now
            if st.tokens < 1.0:
                self._shed(st, model, tenant,
                           f"tenant over its {share:.0%} quota share "
                           f"under contention",
                           max(eff_rows, 1.0 / max(rate, 1e-6)
                               * self._drain_rows_per_s))
            st.tokens -= 1.0
        else:
            # no contention: the bucket re-arms at full burst — the
            # idle tenants' unused share redistributes to whoever is
            # actually sending
            st.tokens = None
            st.refilled_at = now
        st.admitted += 1
        _telemetry.count("serve_admitted")

    def _shed(self, st: _TenantState, model: str, tenant: str,
              reason: str, backlog_rows: float) -> None:
        st.shed += 1
        hint = self.retry_after_ms(backlog_rows)
        _telemetry.count("serve_admission_sheds")
        # loud but bounded: the FIRST shed of a storm logs immediately;
        # repeats within the throttle window aggregate into the next
        # event's ``suppressed`` count (the counter above still counts
        # every shed) — per-request log formatting would otherwise eat
        # the very drain capacity shedding is meant to protect
        key = (model, tenant)
        now = self.clock()
        last, pent = self._shed_logged.get(key, (None, 0))
        if last is None or now - last >= _SHED_LOG_INTERVAL_S:
            _telemetry.event("serve_request_shed", model=model,
                             tenant=tenant, reason=reason,
                             retry_after_ms=hint, state=self.state,
                             suppressed=pent)
            self._shed_logged[key] = (now, 0)
        else:
            self._shed_logged[key] = (last, pent + 1)
        raise ServeShed(model, tenant, reason, hint)

    # -- the DRR dispatch-grant gate ---------------------------------------
    async def acquire_grant(self, tenant: str, rows: int) -> None:
        """Take the single dispatch slot (the admission-on twin of the
        server's ``_dispatch_sem``). Uncontended lanes pass straight
        through; under contention waiters are served by weighted
        deficit round-robin, batch cost = its rows."""
        if not self._busy and self._waiting == 0:
            self._busy = True
            return
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        q = self._waiters.get(tenant)
        if q is None:
            q = self._waiters[tenant] = collections.deque()
            self._ring.append(tenant)
            self._deficit.setdefault(tenant, 0.0)
        q.append((max(int(rows), 1), fut))
        self._waiting += 1
        await fut

    def release_grant(self) -> None:
        """Release the dispatch slot; hands it to the next DRR waiter
        (the slot stays busy) or parks it free."""
        fut = self._next_waiter()
        if fut is None:
            self._busy = False
        else:
            fut.set_result(None)

    def _next_waiter(self):
        """Classic DRR over tenants with queued waiters: arriving at a
        tenant credits quantum x weight ONCE, then its batches are
        served (one per release) while the deficit covers their rows;
        when it runs short the ring rotates to the next tenant. A
        tenant leaving the active set forfeits its residue — only
        ACTIVE tenants split the device, so idle shares redistribute
        and a heavier weight drains proportionally more rows per
        round."""
        while self._ring:
            tenant = self._ring[0]
            q = self._waiters.get(tenant)
            if not q:
                self._ring.popleft()
                self._waiters.pop(tenant, None)
                self._deficit.pop(tenant, None)
                self._head_credited = False
                continue
            if not self._head_credited:
                self._deficit[tenant] = self._deficit.get(tenant, 0.0) \
                    + self.quantum * self.weight(tenant)
                self._head_credited = True
            cost, fut = q[0]
            if self._deficit[tenant] >= cost:
                q.popleft()
                self._waiting -= 1
                self._deficit[tenant] -= cost
                if not q:
                    self._ring.popleft()
                    self._waiters.pop(tenant, None)
                    self._deficit.pop(tenant, None)
                    self._head_credited = False
                if fut.cancelled():
                    continue
                _telemetry.count("serve_drr_grants")
                return fut
            self._ring.rotate(-1)
            self._head_credited = False
        return None

    def drain_waiters(self, exc: Optional[BaseException] = None) -> None:
        """Fail (or release) every parked grant waiter at shutdown."""
        for q in self._waiters.values():
            for _cost, fut in q:
                if not fut.done():
                    if exc is not None:
                        fut.set_exception(exc)
                    else:
                        fut.cancel()
        self._waiters.clear()
        self._ring.clear()
        self._deficit.clear()
        self._waiting = 0
        self._head_credited = False

    # -- introspection -----------------------------------------------------
    def snapshot(self, queue_depth: Optional[Dict[str, int]] = None
                 ) -> dict:
        """The ``"admission"`` block of ``metrics_snapshot()`` (schema
        4, docs/observability.md)."""
        return {
            "enabled": True,
            "state": self.state,
            "pressure": round(self._pressure, 4),
            "transitions": self.transitions,
            "queue_rows_limit": self.queue_rows,
            "quantum_rows": self.quantum,
            "drain_rows_per_s": round(self._drain_rows_per_s, 1),
            "waiting_grants": self._waiting,
            "tenants": {
                t: {
                    "weight": self.weight(t),
                    "admitted": st.admitted,
                    "shed": st.shed,
                    "deadline_ms": self._deadline_ms(t),
                } for t, st in sorted(self._tenants.items())},
            "queue_depth": dict(queue_depth or {}),
            "decisions": [d.to_json() for d in self.decisions],
        }
