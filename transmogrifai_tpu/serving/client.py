"""Reconnecting TCP client for the serving loop's line-JSON protocol.

``tx serve`` (cli/serve.py) speaks newline-delimited JSON over TCP.
A naive client dies the moment the server restarts — which is exactly
when a self-healing deployment is MOST interesting (hot-swap drills,
rolling restarts, breaker trips). :class:`TcpServingClient` survives
them: every connect and every request retries under the same bounded
exponential backoff policy the rest of the runtime uses
(:class:`~..runtime.retry.RetryPolicy` — deterministic jitter, capped
delays), reconnecting on any socket-level failure and counting each
reconnect in telemetry (``serve_client_reconnects``).

What it does NOT do: retry a request the server ANSWERED with an
error. An ``{"ok": false}`` response is an application verdict
(schema rejection, breaker open, ...) and is returned to the caller —
only transport failures (connection refused/reset, truncated stream)
trigger reconnect + resend. The single exception is the
machine-readable ``{"ok": false, "draining": true}`` answer a
gracefully-stopping server sends (docs/serving_restart.md): that is a
"retry against the next incarnation" instruction, not a verdict on
the request, so the client closes, backs off, and resends — which is
what makes a rolling restart invisible to callers
(``serve_client_drain_retries`` counts them). An overload shed answer
(``{"ok": false, "shed": true, "retry_after_ms": N}``,
docs/admission.md) is likewise a "come back later" instruction — but
from a server that is ALIVE: the client keeps the connection, sleeps
the server-provided hint (capped at the policy's ``max_delay``) and
resends, counting ``serve_client_shed_retries``; the last attempt
returns the shed answer to the caller as the verdict.

Resends are dedupe-safe: a request tagged with an ``id`` discards any
late reply echoing a DIFFERENT ``request_id`` (a leftover answer to
an earlier abandoned send racing the resend) instead of surfacing two
answers — counted as ``serve_client_duplicate_replies``.

>>> with TcpServingClient("127.0.0.1", 8190) as client:
...     row = client.score({"x": 1.0}, model="m")
...     snap = client.metrics()
"""
from __future__ import annotations

import json
import socket
import time
from typing import Any, Dict, Optional

from ..runtime import telemetry as _telemetry
from ..runtime.retry import RetryPolicy

__all__ = ["TcpServingClient", "ServingUnavailable"]


class ServingUnavailable(ConnectionError):
    """The serving endpoint stayed unreachable through every backoff
    attempt the retry policy allows."""


class TcpServingClient:
    """Line-JSON serving client with transparent reconnect.

    ``retry`` bounds BOTH the initial connect and per-request resend
    attempts; delays come from ``RetryPolicy.delay_for`` so tests can
    pin them with ``TX_RETRY_*`` env knobs.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8190,
                 retry: Optional[RetryPolicy] = None,
                 timeout: float = 10.0):
        self.host = host
        self.port = port
        self.retry = retry or RetryPolicy.from_env()
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._reader = None

    # -- connection management ---------------------------------------------
    def connect(self) -> "TcpServingClient":
        """Ensure a live connection, retrying with bounded exponential
        backoff. Raises :class:`ServingUnavailable` when every attempt
        fails."""
        if self._sock is not None:
            return self
        last: Optional[Exception] = None
        for attempt in range(1, self.retry.max_attempts + 1):
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
                sock.settimeout(self.timeout)
                self._sock = sock
                self._reader = sock.makefile("r", encoding="utf-8")
                return self
            except (OSError, ConnectionError) as e:
                last = e
                self._close()
                if attempt < self.retry.max_attempts:
                    time.sleep(self.retry.delay_for(
                        attempt, f"connect:{self.host}:{self.port}"))
        raise ServingUnavailable(
            f"serving endpoint {self.host}:{self.port} unreachable "
            f"after {self.retry.max_attempts} attempts: {last}"
        ) from last

    def _close(self) -> None:
        if self._reader is not None:
            try:
                self._reader.close()
            except OSError:
                pass
            self._reader = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self._close()

    def __enter__(self) -> "TcpServingClient":
        return self.connect()

    def __exit__(self, *exc) -> None:
        self.close()

    def _read_reply(self, rid: Optional[Any]) -> Dict[str, Any]:
        """Read the reply for request ``rid``, discarding any LATE
        reply a resend raced: when the caller tagged the request with
        an ``id``, a line echoing a DIFFERENT ``request_id`` is a
        leftover answer to an earlier abandoned send (e.g. the read
        timed out mid-reply, the request was resent, and both answers
        eventually land on the stream) — surfacing it would answer
        this request with a stale payload. Counted as
        ``serve_client_duplicate_replies``; untagged requests keep
        the first reply, exactly as before."""
        while True:
            answer = self._reader.readline()
            if not answer:
                raise ConnectionError(
                    "server closed the connection mid-request")
            doc = json.loads(answer)
            echoed = (doc.get("request_id")
                      if isinstance(doc, dict) else None)
            if rid is not None and echoed is not None \
                    and str(echoed) != str(rid):
                _telemetry.count("serve_client_duplicate_replies")
                continue
            return doc

    # -- requests ----------------------------------------------------------
    def request(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """One request/response round trip. A transport failure closes
        the socket, reconnects under backoff, and RESENDS; an answered
        ``{"ok": false}`` is returned as-is (application errors are not
        transport errors) — EXCEPT the ``"draining"`` answer (come
        back after the restart: reconnect + resend) and the ``"shed"``
        answer (come back in ``retry_after_ms``: sleep + resend on the
        live connection)."""
        line = json.dumps(payload, default=float) + "\n"
        last: Optional[Exception] = None
        for attempt in range(1, self.retry.max_attempts + 1):
            try:
                self.connect()
                self._sock.sendall(line.encode())
                doc = self._read_reply(payload.get("id"))
                if isinstance(doc, dict) and doc.get("draining"):
                    _telemetry.count("serve_client_drain_retries")
                    raise ConnectionError(
                        "server is draining for restart")
                if isinstance(doc, dict) and doc.get("shed"):
                    # overload shed (docs/admission.md): the server is
                    # ALIVE and told us exactly when to come back —
                    # honor retry_after_ms on the SAME connection (no
                    # reconnect), distinct from drain retries
                    if attempt >= self.retry.max_attempts:
                        return doc
                    _telemetry.count("serve_client_shed_retries")
                    hint_s = float(doc.get("retry_after_ms", 0) or 0) \
                        / 1000.0
                    time.sleep(min(max(hint_s, 0.0),
                                   self.retry.max_delay))
                    continue
                return doc
            except (OSError, ConnectionError, json.JSONDecodeError) as e:
                last = e
                self._close()
                _telemetry.count("serve_client_reconnects")
                if attempt < self.retry.max_attempts:
                    time.sleep(self.retry.delay_for(
                        attempt, f"request:{self.host}:{self.port}"))
        raise ServingUnavailable(
            f"request to {self.host}:{self.port} failed after "
            f"{self.retry.max_attempts} attempts: {last}") from last

    def score(self, record: Dict[str, Any],
              model: Optional[str] = None,
              tenant: Optional[str] = None,
              request_id: Optional[str] = None) -> Dict[str, Any]:
        """Score one record; returns the full response envelope
        (``{"ok": true, "result": row}`` or ``{"ok": false, ...}``)."""
        payload: Dict[str, Any] = {"record": record}
        if model is not None:
            payload["model"] = model
        if tenant is not None:
            payload["tenant"] = tenant
        if request_id is not None:
            payload["id"] = request_id
        return self.request(payload)

    def metrics(self) -> Dict[str, Any]:
        """The live metrics snapshot (schema: observability/metrics)."""
        answer = self.request({"metrics": True})
        return answer.get("metrics", answer)
