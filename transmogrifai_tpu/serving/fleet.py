"""Replica manager: N supervised serve children behind one router
(docs/fleet.md).

This generalizes the single-child ``tx serve --supervise`` supervisor
(cli/serve.py, docs/serving_restart.md) to a SET of child serving
processes. Each replica gets its own ``--state-dir`` (so warm-state
snapshots are per-incarnation), its own ephemeral port (``--port 0``,
bound port read back from the child's JSON banner), and — when the
model dir carries AOT artifacts (docs/aot_artifacts.md) — a
compile-free boot, which is what makes rolling deploys cheap.

The robustness contract, per replica:

- **Crash → warm takeover.** A child that dies with a non-zero exit
  is respawned with ``--resume-state <its state dir>`` and a bumped
  ``TX_SERVE_GENERATION``: the new incarnation replays the dead one's
  last warm-state snapshot (bucket prewarm, tenant guards — see
  docs/serving_restart.md), so takeover is WARM, not a cold start.
  While the replacement boots, the router has already re-placed the
  dead replica's lanes onto survivors — clients never see the gap.
  Each heal runs on its own thread: the watch loop keeps ticking the
  other replicas, so near-simultaneous crashes heal in parallel.
- **Crash-loop breaker.** Per-replica sliding-window crash counting,
  exactly like the PR-12 supervisor: more than ``max_restarts``
  crashes inside ``restart_window`` seconds marks the replica
  ``failed`` and stops respawning it (restarting is making it worse);
  the rest of the fleet keeps serving.
- **Rolling deploy.** :meth:`ReplicaManager.rolling_deploy` drains
  ONE replica at a time: tell the router to stop placing lanes there,
  SIGTERM the child (graceful drain + final snapshot), respawn with
  ``--resume-state``, wait for ``{"ready": true}``, then move on.
  At every instant N-1 replicas serve.

Deterministic drills: the watch loop probes
``maybe_inject("fleet", <replica>, "kill")`` each tick — a ``kill``
fault in ``TX_FAULT_PLAN`` (e.g. ``fleet:r1:kill:1=kill``) SIGKILLs
that child, turning the warm-takeover path into a reproducible test
(runtime/faults.py).

Everything here is plain threads + subprocesses — no coroutines. The
router runs the event loop; the manager talks to it only through its
``*_threadsafe`` entry points.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..runtime import telemetry as _telemetry
from ..runtime.faults import KillPoint, maybe_inject
from ..runtime.retry import RetryPolicy

__all__ = ["ReplicaManager", "ReplicaSpec", "ReplicaProcess",
           "wait_port_ready"]


def wait_port_ready(host: str, port: int, timeout: float = 120.0,
                    require_ready: bool = True) -> dict:
    """Poll a serving port with ``{"ready": true}`` probes until the
    server answers ready (readiness barrier for replica boots and the
    test harness). Returns the final readiness answer."""
    deadline = time.monotonic() + timeout
    last_err: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port),
                                          timeout=2.0) as sock:
                sock.sendall(b'{"ready": true}\n')
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = sock.recv(4096)
                    if not chunk:
                        raise ConnectionError("closed during probe")
                    buf += chunk
            doc = json.loads(buf)
            if not require_ready or doc.get("ready"):
                return doc
        except (OSError, ConnectionError,
                json.JSONDecodeError) as e:
            last_err = e
        time.sleep(0.05)
    raise TimeoutError(
        f"serving port {host}:{port} not ready within {timeout}s "
        f"(last error: {last_err})")


@dataclass
class ReplicaSpec:
    """Launch recipe for one replica."""
    name: str
    models: Sequence[str]          # "name=/model/dir" pairs
    state_dir: str
    host: str = "127.0.0.1"
    extra_args: Sequence[str] = field(default_factory=tuple)
    env: Dict[str, str] = field(default_factory=dict)


class ReplicaProcess:
    """One live child incarnation: the Popen handle, the bound port
    parsed from the child's banner line, and a stdout-pump thread that
    keeps the pipe drained (a full pipe would wedge the child's
    drain/final-snapshot prints)."""

    def __init__(self, spec: ReplicaSpec, proc: subprocess.Popen,
                 generation: int):
        self.spec = spec
        self.proc = proc
        self.generation = generation
        self.port: Optional[int] = None
        self.port_event = threading.Event()
        self.output: List[str] = []
        self._pump = threading.Thread(target=self._drain_stdout,
                                      daemon=True)
        self._pump.start()

    def _drain_stdout(self) -> None:
        for line in self.proc.stdout:
            self.output.append(line)
            if self.port is None:
                try:
                    doc = json.loads(line)
                except (ValueError, TypeError):
                    doc = None   # non-JSON child chatter, not a banner
                if isinstance(doc, dict) and doc.get("serving"):
                    self.port = int(doc.get("port", 0)) or None
                    if self.port:
                        self.port_event.set()

    def wait_port(self, timeout: float = 120.0) -> int:
        if not self.port_event.wait(timeout):
            rc = self.proc.poll()
            tail = "".join(self.output[-20:])
            raise TimeoutError(
                f"replica {self.spec.name} printed no serving banner "
                f"within {timeout}s (exit={rc})\n{tail}")
        return int(self.port)

    def alive(self) -> bool:
        return self.proc.poll() is None


class ReplicaManager:
    """Spawns, watches, heals and drains the replica set.

    Callbacks wire the manager to the router (all invoked from the
    manager's threads; the router marshals them onto its loop):

    - ``on_up(name, host, port, generation)`` — replica answered
      ready (first boot or a takeover respawn).
    - ``on_down(name, reason)`` — replica died; the router re-places
      its lanes NOW, before the replacement exists.
    - ``on_draining(name)`` — a drain is about to start; stop placing
      lanes there.
    """

    def __init__(self, models: Sequence[str], replicas: int,
                 state_root: str, host: str = "127.0.0.1",
                 serve_args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 retry: Optional[RetryPolicy] = None,
                 max_restarts: int = 5,
                 restart_window: float = 60.0,
                 ready_timeout: float = 180.0,
                 on_up: Optional[Callable] = None,
                 on_down: Optional[Callable] = None,
                 on_draining: Optional[Callable] = None):
        self.host = host
        self.retry = retry or RetryPolicy.from_env()
        self.max_restarts = max(int(max_restarts), 1)
        self.restart_window = max(float(restart_window), 0.001)
        self.ready_timeout = float(ready_timeout)
        self.on_up = on_up
        self.on_down = on_down
        self.on_draining = on_draining
        self.specs: Dict[str, ReplicaSpec] = {}
        for i in range(int(replicas)):
            name = f"r{i}"
            state_dir = os.path.join(state_root, name)
            os.makedirs(state_dir, exist_ok=True)
            self.specs[name] = ReplicaSpec(
                name=name, models=tuple(models),
                state_dir=state_dir, host=host,
                extra_args=tuple(serve_args),
                env=dict(env or {}))
        self.procs: Dict[str, ReplicaProcess] = {}
        #: "starting" | "ok" | "healing" | "draining" | "failed"
        #: | "stopped"
        self.states: Dict[str, str] = {n: "starting"
                                       for n in self.specs}
        self._crashes: Dict[str, deque] = {n: deque()
                                           for n in self.specs}
        self._generations: Dict[str, int] = {n: 0 for n in self.specs}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watch: Optional[threading.Thread] = None
        self._heals: Dict[str, threading.Thread] = {}
        self.kill_drills = 0

    # -- spawning ----------------------------------------------------------
    def _spawn(self, name: str, resume: bool) -> ReplicaProcess:
        spec = self.specs[name]
        self._generations[name] += 1
        generation = self._generations[name]
        cmd = [sys.executable, "-m", "transmogrifai_tpu.cli", "serve",
               "--host", spec.host, "--port", "0",
               "--state-dir", spec.state_dir]
        for m in spec.models:
            cmd += ["--model", m]
        if resume:
            cmd += ["--resume-state", spec.state_dir]
        cmd += list(spec.extra_args)
        env = dict(os.environ, **spec.env,
                   TX_SERVE_GENERATION=str(generation))
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=env)
        rp = ReplicaProcess(spec, proc, generation)
        self.procs[name] = rp
        _telemetry.event("fleet_replica_spawned", replica=name,
                         generation=generation, pid=proc.pid,
                         resume=resume)
        print(json.dumps({"fleet": "spawned", "replica": name,
                          "generation": generation,
                          "pid": proc.pid, "resume": resume}),
              flush=True)
        return rp

    def _boot(self, name: str, resume: bool) -> None:
        rp = self._spawn(name, resume=resume)
        port = rp.wait_port(self.ready_timeout)
        wait_port_ready(rp.spec.host, port, self.ready_timeout)
        with self._lock:
            self.states[name] = "ok"
        print(json.dumps({"fleet": "ready", "replica": name,
                          "port": port,
                          "generation": rp.generation}), flush=True)
        if self.on_up is not None:
            self.on_up(name, rp.spec.host, port, rp.generation)

    def start(self) -> None:
        """Boot every replica in parallel, barrier on readiness, then
        start the watch thread."""
        threads = [threading.Thread(target=self._boot,
                                    args=(name, False))
                   for name in self.specs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        booted = [n for n, s in self.states.items() if s == "ok"]
        if not booted:
            raise RuntimeError("no replica became ready")
        self._watch = threading.Thread(target=self._watch_loop,
                                       daemon=True)
        self._watch.start()

    # -- the watch loop ----------------------------------------------------
    def _watch_loop(self) -> None:
        while not self._stop.wait(0.1):
            for name in list(self.specs):
                self._tick(name)

    def _tick(self, name: str) -> None:
        with self._lock:
            state = self.states.get(name)
        if state not in ("ok", "draining"):
            return
        rp = self.procs.get(name)
        if rp is None:
            return
        if rp.alive():
            try:
                # fleet:<name>:kill — the deterministic kill drill:
                # SIGKILL this child as a real OOM-killer would
                maybe_inject("fleet", name, "kill")
            except KillPoint:
                self.kill_drills += 1
                _telemetry.count("fleet_kill_drills")
                print(json.dumps({"fleet": "kill_drill",
                                  "replica": name,
                                  "generation": rp.generation}),
                      flush=True)
                rp.proc.kill()
            return
        rc = rp.proc.returncode
        if state == "draining" or rc == 0:
            # graceful exits end the incarnation without healing;
            # rolling_deploy owns the respawn
            return
        # heal on a dedicated thread: _heal blocks on the backoff
        # sleep and then on the replacement's readiness gate (up to
        # ready_timeout), and the watch loop must keep ticking the
        # OTHER replicas meanwhile — near-simultaneous crashes heal
        # in parallel and kill drills keep firing. The "healing"
        # state keeps this tick from starting a second heal.
        with self._lock:
            self.states[name] = "healing"
        t = threading.Thread(target=self._heal, args=(name, rc),
                             daemon=True)
        self._heals[name] = t
        t.start()

    def _heal(self, name: str, rc: int) -> None:
        """Crash detected: count it against the sliding window, then
        either trip the per-replica crash-loop breaker or respawn
        with ``--resume-state`` (the warm takeover). Runs on its own
        thread, one per healing replica."""
        now = time.monotonic()
        crashes = self._crashes[name]
        crashes.append(now)
        while crashes and now - crashes[0] > self.restart_window:
            crashes.popleft()
        _telemetry.count("fleet_replica_crashes")
        print(json.dumps({"fleet": "crashed", "replica": name,
                          "code": rc,
                          "crashes_in_window": len(crashes)}),
              flush=True)
        if self.on_down is not None:
            self.on_down(name, f"exit {rc}")
        if len(crashes) > self.max_restarts:
            with self._lock:
                self.states[name] = "failed"
            _telemetry.count("fleet_crash_loop_breakers")
            print(json.dumps({"fleet": "crash_loop_breaker",
                              "replica": name,
                              "crashes": len(crashes),
                              "window_seconds": self.restart_window}),
                  flush=True)
            return
        if self._stop.wait(self.retry.delay_for(
                len(crashes), f"fleet-restart:{name}")):
            return   # manager is shutting down — no respawn
        try:
            self._boot(name, resume=True)
        except (OSError, TimeoutError, RuntimeError) as e:
            # respawn failed outright — harsher than another crash:
            # a replacement that cannot even reach ready has nothing
            # a restart window could ride out, so the replica is
            # marked failed immediately instead of looping forever
            _telemetry.event("fleet_respawn_failed", replica=name,
                             error=str(e)[:200])
            with self._lock:
                self.states[name] = "failed"
            print(json.dumps({"fleet": "respawn_failed",
                              "replica": name,
                              "error": str(e)[:200]}), flush=True)

    # -- drain / rolling deploy -------------------------------------------
    def drain_replica(self, name: str,
                      timeout: float = 60.0) -> int:
        """Gracefully stop one replica: router stops placing lanes
        there, then SIGTERM → drain → final snapshot → exit 0."""
        rp = self.procs.get(name)
        with self._lock:
            self.states[name] = "draining"
        if self.on_draining is not None:
            self.on_draining(name)
        if rp is None or not rp.alive():
            return 0
        rp.proc.terminate()
        try:
            rc = rp.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            rp.proc.kill()
            rc = rp.proc.wait(10)
        print(json.dumps({"fleet": "drained", "replica": name,
                          "code": rc}), flush=True)
        return rc

    def rolling_deploy(self) -> None:
        """Drain + respawn each replica sequentially — the zero-
        downtime deploy: at every instant all OTHER replicas serve,
        and each respawn resumes from its own final snapshot."""
        for name in sorted(self.specs):
            with self._lock:
                if self.states.get(name) not in ("ok", "draining"):
                    continue
            _telemetry.count("fleet_rolling_deploys")
            self.drain_replica(name)
            self._boot(name, resume=True)

    # -- teardown ----------------------------------------------------------
    def shutdown(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.join(5.0)
        for t in list(self._heals.values()):
            t.join(2.0)
        for name, rp in list(self.procs.items()):
            with self._lock:
                self.states[name] = "stopped"
            if rp.alive():
                rp.proc.terminate()
        deadline = time.monotonic() + timeout
        for rp in list(self.procs.values()):
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                rp.proc.wait(remaining)
            except subprocess.TimeoutExpired:
                rp.proc.kill()
                rp.proc.wait(10)
        # a heal thread that out-waited the joins above may have
        # slipped a fresh spawn past the terminate sweep — reap it
        for rp in list(self.procs.values()):
            if rp.alive():
                rp.proc.kill()

    def snapshot(self) -> dict:
        """Manager-side view for the fleet metrics document."""
        with self._lock:
            states = dict(self.states)
        return {
            "replicas": {
                name: {"state": states.get(name),
                       "generation": self._generations[name],
                       "port": (self.procs[name].port
                                if name in self.procs else None),
                       "alive": (self.procs[name].alive()
                                 if name in self.procs else False)}
                for name in sorted(self.specs)},
            "kill_drills": self.kill_drills,
        }
