"""Serving guardrails: schema admission, output guards, circuit breaker.

PR 4 made *training* degrade instead of die; this module does the same
for the serving path the north star actually cares about ("heavy
traffic from millions of users"). Three pieces, all **off by default**
— a plan without a guard runs the exact pre-guard code path, so
default ``score()`` output is byte-identical:

- :class:`SchemaGuard` — validates/coerces each incoming record
  against the model's raw-feature schema *before* vectorization.
  Malformed rows (missing required fields, uncoercible types, NaN/Inf
  numerics, out-of-vocab categoricals, unknown fields under a strict
  policy) are **quarantined with a machine-readable reason** while the
  rest of the batch scores normally: the bad rows are sanitized to
  placeholder values and masked out of the padded device batch — no
  shape change, no recompile.
- :class:`OutputGuard` — NaN/Inf/probability-range checks on the
  scored outputs. A bad row is **invalidated with a reason** (its
  outputs overwritten with NaN) instead of emitting garbage to the
  caller.
- :class:`CircuitBreaker` — classic closed -> open -> half-open
  breaker over device dispatch. Repeated device failures trip the
  breaker; while open, batches score through the host columnar
  fallback immediately (no device attempt, no retry latency); after a
  cooldown one probe batch tests recovery.

Telemetry (runtime/telemetry.py) counts ``serving_rows_scored`` /
``serving_rows_quarantined`` / ``serving_rows_invalidated`` and every
breaker transition (``breaker_trips`` / ``breaker_half_open`` /
``breaker_recoveries``), so the bench and tests assert behavior
instead of inferring it.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence, Set,
                    Tuple)

import numpy as np

from ..features.columns import (ColumnKind, Dataset, FeatureColumn,
                                PredictionColumn)
from ..runtime import telemetry as _telemetry
from ..types import FeatureType, OPNumeric, Prediction

__all__ = ["AdmissionPolicy", "SchemaGuard", "OutputGuard",
           "CircuitBreaker", "BreakerOpenError", "GuardReason",
           "GuardedScoreResult", "ServingGuard",
           "REASON_MISSING_FIELD", "REASON_WRONG_TYPE",
           "REASON_NON_FINITE", "REASON_OUT_OF_VOCAB",
           "REASON_EXTRA_FIELD", "REASON_OUTPUT_NON_FINITE",
           "REASON_PROBABILITY_RANGE"]

# -- machine-readable reason codes (the admission matrix the tests walk) --
REASON_MISSING_FIELD = "missing_field"
REASON_WRONG_TYPE = "wrong_type"
REASON_NON_FINITE = "non_finite"
REASON_OUT_OF_VOCAB = "out_of_vocab"
REASON_EXTRA_FIELD = "extra_field"
REASON_OUTPUT_NON_FINITE = "output_non_finite"
REASON_PROBABILITY_RANGE = "probability_out_of_range"


@dataclass(frozen=True)
class GuardReason:
    """Why one row was quarantined (admission) or invalidated
    (output guard) — ``code`` is machine-readable, ``detail`` human."""
    row: int
    code: str
    feature: str = ""
    detail: str = ""

    def to_json(self) -> dict:
        return {"row": self.row, "code": self.code,
                "feature": self.feature, "detail": self.detail}


@dataclass
class AdmissionPolicy:
    """Knobs for :class:`SchemaGuard` (docs/serving_guardrails.md).

    The defaults quarantine rows that would otherwise crash or
    silently mis-score (missing required fields, uncoercible values,
    non-finite numerics) and let the vectorizers' own OTHER/NULL
    handling absorb unseen categoricals and unknown record keys."""
    #: quarantine when a NON-NULLABLE predictor is missing/null
    require_fields: bool = True
    #: quarantine on NaN/±Inf in a numeric predictor value
    reject_non_finite: bool = True
    #: quarantine categorical values outside the model's fitted vocab
    #: (off: the one-hot OTHER column absorbs them, as at train time)
    reject_out_of_vocab: bool = False
    #: quarantine records carrying keys no raw feature extracts
    reject_extra_fields: bool = False
    #: cap on reasons recorded per batch (the ledger, not the masking —
    #: every bad row is masked regardless)
    max_reasons: int = 10_000


def _harvest_vocab(model) -> Dict[str, Set[str]]:
    """Fitted per-raw-feature category vocabularies, harvested from the
    one-hot family (``categories`` per input slot). Only raw features
    directly feeding a vectorizer get a vocab entry — derived columns
    are the model's own business."""
    vocab: Dict[str, Set[str]] = {}
    for stage in model.stages():
        cats = getattr(stage, "categories", None)
        if not isinstance(cats, list):
            continue
        for f, c in zip(getattr(stage, "input_features", ()), cats):
            if getattr(f, "is_raw", False) and isinstance(c, (list, set)):
                vocab.setdefault(f.name, set()).update(str(v) for v in c)
    return vocab


class SchemaGuard:
    """Admission control for one model's raw-feature schema."""

    def __init__(self, model, policy: Optional[AdmissionPolicy] = None):
        self.policy = policy or AdmissionPolicy()
        self.raw_features = model.raw_features()
        self.predictors = [f for f in self.raw_features
                           if not f.is_response]
        self.vocab = _harvest_vocab(model)
        self._known_keys = {f.name for f in self.raw_features}

    # -- record-level admission -------------------------------------------
    def admit_records(self, records: Sequence[Dict[str, Any]]
                      ) -> Tuple[Dataset, List[GuardReason]]:
        """Validate/coerce raw record dicts and materialize the raw
        Dataset in one pass. Every record survives — bad FIELDS are
        replaced with boxable placeholders (so vectorization cannot
        crash) and the row carries >= 1 machine-readable reason; the
        caller masks those rows out of the padded device batch."""
        from ..features.generator import FeatureGeneratorStage
        reasons: List[GuardReason] = []
        values: Dict[str, List[Any]] = {f.name: []
                                        for f in self.raw_features}
        for i, rec in enumerate(records):
            if self.policy.reject_extra_fields and isinstance(rec, dict):
                for k in sorted(rec):
                    if k not in self._known_keys:
                        self._note(reasons, GuardReason(
                            i, REASON_EXTRA_FIELD, k,
                            f"record key {k!r} matches no raw feature"))
            for f in self.raw_features:
                gen = f.origin_stage
                raw: Any = None
                failed: Optional[Tuple[str, str, bool]] = None
                if isinstance(gen, FeatureGeneratorStage):
                    try:
                        raw = gen.extract_fn(rec)
                    except Exception as e:
                        failed = _quarantine_reason(
                            REASON_WRONG_TYPE,
                            f"extract fn raised "
                            f"{type(e).__name__}: {e}")
                elif isinstance(rec, dict):
                    raw = rec.get(f.name)
                if f.is_response:
                    # label-free scoring: responses are never
                    # quarantine evidence; unextractable -> placeholder
                    values[f.name].append(
                        raw if failed is None else None)
                    continue
                if failed is not None:
                    code, detail = failed[0], failed[1]
                    stored = _placeholder_value(f)
                else:
                    stored, code, detail = self._admit_value(f, raw)
                if code is not None:
                    self._note(reasons, GuardReason(i, code, f.name,
                                                    detail))
                values[f.name].append(stored)
        cols = {f.name: _boxed_column(f, values[f.name])
                for f in self.raw_features}
        return Dataset(cols), reasons

    def _admit_value(self, f, raw: Any
                     ) -> Tuple[Any, Optional[str], str]:
        """One predictor value -> (stored value, reason code or None,
        detail). The stored value is safe for the column builder: a
        boxed FeatureType for admitted values, a missing-placeholder
        for rejected/sanitized ones."""
        numeric = issubclass(f.ftype, OPNumeric)
        value = raw.value if isinstance(raw, FeatureType) else raw
        if value is None:
            if not f.ftype.is_nullable:
                if self.policy.require_fields:
                    return (_placeholder_value(f), REASON_MISSING_FIELD,
                            f"required {f.ftype.__name__} field is "
                            f"missing")
                return _placeholder_value(f), None, ""   # lenient
            return None, None, ""
        if numeric and isinstance(value, (int, float, np.floating,
                                          np.integer)):
            fv = float(value)
            if math.isnan(fv):
                if f.ftype.is_nullable:
                    return None, None, ""    # NaN = missing, by column
                if self.policy.reject_non_finite:       # convention
                    return (_placeholder_value(f), REASON_NON_FINITE,
                            f"NaN in required {f.ftype.__name__} field")
                return _placeholder_value(f), None, ""
            if math.isinf(fv) and self.policy.reject_non_finite:
                return (_placeholder_value(f), REASON_NON_FINITE,
                        f"non-finite value {fv!r}")
        boxed = raw
        if not isinstance(raw, FeatureType):
            try:
                boxed = f.ftype.from_any(raw)
            except Exception as e:
                code, detail, _ = _quarantine_reason(
                    REASON_WRONG_TYPE,
                    f"cannot coerce {type(raw).__name__} to "
                    f"{f.ftype.__name__}: {e}")
                return _placeholder_value(f), code, detail
        if self.policy.reject_out_of_vocab:
            vocab = self.vocab.get(f.name)
            if vocab:
                for item in self._categorical_items(value):
                    if item not in vocab:
                        return (_placeholder_value(f),
                                REASON_OUT_OF_VOCAB,
                                f"value {item!r} not in the fitted "
                                f"vocabulary ({len(vocab)} categories)")
        return boxed, None, ""

    @staticmethod
    def _categorical_items(value: Any) -> List[str]:
        if isinstance(value, (set, frozenset, list, tuple)):
            return [str(v) for v in value]
        if isinstance(value, dict):
            return [str(k) for k in value]
        return [str(value)]

    # -- columnar admission ------------------------------------------------
    def admit_dataset(self, ds: Dataset
                      ) -> Tuple[Dataset, List[GuardReason]]:
        """Columnar admission over an already-materialized raw Dataset:
        non-finite numerics, missing non-nullables and out-of-vocab
        categoricals. Returns (sanitized dataset, reasons)."""
        reasons: List[GuardReason] = []
        cols = {n: ds[n] for n in ds.column_names}
        for f in self.predictors:
            if f.name not in cols:
                continue
            col = cols[f.name]
            if col.kind == ColumnKind.NUMERIC:
                data = np.asarray(col.data, dtype=np.float64)
                bad_inf = np.isinf(data) if self.policy.reject_non_finite \
                    else np.zeros(len(data), dtype=bool)
                bad_nan = (np.isnan(data)
                           if (self.policy.require_fields
                               and not f.ftype.is_nullable)
                           else np.zeros(len(data), dtype=bool))
                bad = bad_inf | bad_nan
                if bad.any():
                    for i in np.flatnonzero(bad):
                        code = (REASON_NON_FINITE if bad_inf[i]
                                else REASON_MISSING_FIELD)
                        detail = (f"non-finite value {data[i]!r}"
                                  if bad_inf[i] else
                                  f"required {f.ftype.__name__} field "
                                  f"is missing")
                        self._note(reasons, GuardReason(
                            int(i), code, f.name, detail))
                    data = data.copy()
                    data[bad] = np.nan
                    cols[f.name] = FeatureColumn(
                        ftype=col.ftype, data=data,
                        metadata=col.metadata)
            elif self.policy.reject_out_of_vocab \
                    and col.kind in (ColumnKind.TEXT, ColumnKind.OBJECT):
                vocab = self.vocab.get(f.name)
                if not vocab:
                    continue
                data = col.data
                bad_rows = []
                for i, v in enumerate(data):
                    if v is None:
                        continue
                    oov = [x for x in self._categorical_items(v)
                           if x not in vocab]
                    if oov:
                        bad_rows.append(i)
                        self._note(reasons, GuardReason(
                            i, REASON_OUT_OF_VOCAB, f.name,
                            f"value {oov[0]!r} not in the fitted "
                            f"vocabulary ({len(vocab)} categories)"))
                if bad_rows:
                    data = data.copy()
                    for i in bad_rows:
                        data[i] = None
                    cols[f.name] = FeatureColumn(
                        ftype=col.ftype, data=data,
                        metadata=col.metadata)
        return Dataset(cols), reasons

    def _note(self, reasons: List[GuardReason], r: GuardReason) -> None:
        if len(reasons) < self.policy.max_reasons:
            reasons.append(r)


def _quarantine_reason(code: str, detail: str,
                       sanitize: bool = True) -> Tuple[str, str, bool]:
    """One quarantine verdict for a swallowed per-field exception —
    the TX-R01/TX-R02 contract: an absorbed error must surface as a
    recorded, machine-readable reason, never vanish."""
    return code, detail, sanitize


def _placeholder_value(f) -> Any:
    """A value that boxes under ``f.ftype`` and reads as "missing":
    NaN for numerics (non-nullables cannot hold None), None otherwise."""
    if issubclass(f.ftype, OPNumeric):
        return math.nan
    return None


def _boxed_column(f, vals: List[Any]) -> FeatureColumn:
    """Mirror of ``FeatureGeneratorStage.extract_column`` over
    already-admitted values. Numeric columns are built directly
    (placeholder NaNs for quarantined non-nullables must not re-enter
    boxing, which rejects them); response columns degrade to all-NaN
    when the label cannot box (label-free scoring, same as
    ``_generate_raw_data``)."""
    from ..features.columns import ColumnKind, column_kind
    if column_kind(f.ftype) == ColumnKind.NUMERIC:
        data = np.empty(len(vals), dtype=np.float64)
        for i, v in enumerate(vals):
            if isinstance(v, FeatureType):
                v = v.value
            try:
                data[i] = math.nan if v is None else float(v)
            except (TypeError, ValueError):
                if not f.is_response:
                    raise
                data[i] = math.nan   # unboxable label: score label-free
        return FeatureColumn(ftype=f.ftype, data=data)
    try:
        return FeatureColumn.from_values(f.ftype, vals)
    except Exception:
        if f.is_response:
            return FeatureColumn(
                ftype=f.ftype,
                data=np.full(len(vals), np.nan, dtype=np.float64))
        raise


# ---------------------------------------------------------------------------
# output guard
# ---------------------------------------------------------------------------

class OutputGuard:
    """NaN/Inf/probability-range checks on scored result columns: a
    failing row is invalidated (outputs overwritten with NaN) with a
    recorded reason instead of being emitted as-is."""

    def __init__(self, probability_tolerance: float = 1e-6):
        self.probability_tolerance = float(probability_tolerance)

    def check(self, scored: Dataset, result_names: Sequence[str],
              skip_rows: Optional[np.ndarray] = None
              ) -> Tuple[Dataset, List[GuardReason]]:
        """Returns (scored with bad rows NaN'd, reasons). ``skip_rows``
        marks rows already quarantined at admission — their outputs are
        garbage by construction and are not double-reported."""
        reasons: List[GuardReason] = []
        n = scored.n_rows
        skip = (np.zeros(n, dtype=bool) if skip_rows is None
                else np.asarray(skip_rows, dtype=bool))
        bad = np.zeros(n, dtype=bool)
        tol = self.probability_tolerance
        for name in result_names:
            if name not in scored:
                continue
            col = scored[name]
            if isinstance(col, PredictionColumn):
                finite = np.isfinite(col.data)
                if col.raw_prediction.shape[1]:
                    finite &= np.isfinite(col.raw_prediction).all(axis=1)
                row_bad = ~finite & ~skip
                for i in np.flatnonzero(row_bad):
                    reasons.append(GuardReason(
                        int(i), REASON_OUTPUT_NON_FINITE, name,
                        "prediction is NaN/Inf"))
                if col.probability.shape[1]:
                    p = col.probability
                    pfinite = np.isfinite(p).all(axis=1)
                    in_range = pfinite & ((p >= -tol) & (p <= 1 + tol)
                                          ).all(axis=1)
                    prow_bad = ~in_range & ~skip & ~row_bad
                    for i in np.flatnonzero(~pfinite & ~skip & ~row_bad):
                        reasons.append(GuardReason(
                            int(i), REASON_OUTPUT_NON_FINITE, name,
                            "class probability is NaN/Inf"))
                    for i in np.flatnonzero(prow_bad & pfinite):
                        reasons.append(GuardReason(
                            int(i), REASON_PROBABILITY_RANGE, name,
                            f"class probability outside [0, 1]: "
                            f"{p[i].tolist()}"))
                    row_bad |= prow_bad
                bad |= row_bad
            elif col.kind == ColumnKind.NUMERIC \
                    and not issubclass(col.ftype, Prediction):
                data = np.asarray(col.data, dtype=np.float64)
                row_bad = np.isinf(data) & ~skip
                for i in np.flatnonzero(row_bad):
                    reasons.append(GuardReason(
                        int(i), REASON_OUTPUT_NON_FINITE, name,
                        f"non-finite output {data[i]!r}"))
                bad |= row_bad
        if bad.any():
            scored = _invalidate_rows(scored, result_names, bad)
        return scored, reasons


def _invalidate_rows(scored: Dataset, result_names: Sequence[str],
                     bad: np.ndarray) -> Dataset:
    """Overwrite result columns of flagged rows with NaN (the
    invalidate-with-reason policy: never emit garbage)."""
    for name in result_names:
        if name not in scored:
            continue
        col = scored[name]
        if isinstance(col, PredictionColumn):
            data = col.data.copy()
            data[bad] = np.nan
            prob = col.probability.copy()
            raw = col.raw_prediction.copy()
            if prob.shape[1]:
                prob[bad] = np.nan
            if raw.shape[1]:
                raw[bad] = np.nan
            scored = scored.with_column(name, PredictionColumn(
                ftype=col.ftype, data=data, metadata=col.metadata,
                probability=prob, raw_prediction=raw))
        elif col.kind == ColumnKind.NUMERIC:
            data = np.asarray(col.data, dtype=np.float64).copy()
            data[bad] = np.nan
            scored = scored.with_column(name, FeatureColumn(
                ftype=col.ftype, data=data, metadata=col.metadata))
        elif col.kind == ColumnKind.VECTOR:
            data = np.asarray(col.data, dtype=np.float64).copy()
            data[bad, :] = np.nan
            scored = scored.with_column(name, FeatureColumn(
                ftype=col.ftype, data=data, metadata=col.metadata))
        else:
            data = col.data.copy()
            for i in np.flatnonzero(bad):
                data[i] = None
            scored = scored.with_column(name, FeatureColumn(
                ftype=col.ftype, data=data, metadata=col.metadata))
    return scored


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class BreakerOpenError(RuntimeError):
    """Raised by :meth:`CircuitBreaker.before_dispatch` while the
    breaker is open — the caller routes to the host fallback without
    touching the device."""


class CircuitBreaker:
    """Closed -> open -> half-open breaker over device dispatch.

    - **closed**: every batch dispatches; ``failure_threshold``
      *consecutive* failures trip to open (telemetry
      ``breaker_trips``).
    - **open**: dispatch short-circuits to the host fallback for
      ``cooldown_seconds`` — no device attempt, no retry latency.
    - **half-open**: after the cooldown, ONE probe batch dispatches;
      success closes the breaker (``breaker_recoveries``), failure
      re-opens it and restarts the cooldown.

    ``clock`` is injectable so tests step time deterministically."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 3,
                 cooldown_seconds: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_seconds = float(cooldown_seconds)
        self.clock = clock
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        #: (from_state, to_state) transition log for tests/debugging
        self.transitions: List[Tuple[str, str]] = []

    def _move(self, to: str) -> None:
        if to != self.state:
            self.transitions.append((self.state, to))
            _telemetry.event("breaker", transition=f"{self.state}->{to}")
            if to == self.OPEN:
                _telemetry.count("breaker_trips")
            elif to == self.HALF_OPEN:
                _telemetry.count("breaker_half_open")
            elif to == self.CLOSED:
                _telemetry.count("breaker_recoveries")
            self.state = to

    def before_dispatch(self) -> None:
        """Gate one device dispatch. Raises :class:`BreakerOpenError`
        while open; transitions open -> half-open once the cooldown
        elapses (that call becomes the probe)."""
        if self.state == self.OPEN:
            if self.opened_at is not None and \
                    self.clock() - self.opened_at >= self.cooldown_seconds:
                self._move(self.HALF_OPEN)
                return
            raise BreakerOpenError(
                f"scoring circuit breaker is open "
                f"({self.consecutive_failures} consecutive device "
                f"failures); host fallback until the "
                f"{self.cooldown_seconds}s cooldown elapses")

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state in (self.HALF_OPEN, self.OPEN):
            self._move(self.CLOSED)

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN \
                or self.consecutive_failures >= self.failure_threshold:
            self.opened_at = self.clock()
            self._move(self.OPEN)

    def describe(self) -> dict:
        return {"state": self.state,
                "consecutiveFailures": self.consecutive_failures,
                "failureThreshold": self.failure_threshold,
                "cooldownSeconds": self.cooldown_seconds,
                "transitions": [list(t) for t in self.transitions]}


# ---------------------------------------------------------------------------
# the aggregate guard a plan carries
# ---------------------------------------------------------------------------

@dataclass
class GuardedScoreResult:
    """What a guarded ``score`` returns: the scored Dataset (full row
    count — quarantined/invalidated rows carry NaN outputs) plus the
    machine-readable ledger."""
    scored: Dataset
    quarantined: List[GuardReason] = field(default_factory=list)
    invalidated: List[GuardReason] = field(default_factory=list)
    #: True when this batch scored through the host columnar fallback
    #: (breaker open, or device dispatch failed after retries)
    used_host_fallback: bool = False
    breaker_state: str = CircuitBreaker.CLOSED

    @property
    def quarantined_rows(self) -> List[int]:
        return sorted({r.row for r in self.quarantined})

    @property
    def invalidated_rows(self) -> List[int]:
        return sorted({r.row for r in self.invalidated})

    @property
    def n_rows(self) -> int:
        return self.scored.n_rows

    @property
    def n_valid(self) -> int:
        return self.n_rows - len(set(self.quarantined_rows)
                                 | set(self.invalidated_rows))

    def to_json(self) -> dict:
        return {
            "nRows": self.n_rows,
            "nValid": self.n_valid,
            "quarantined": [r.to_json() for r in self.quarantined],
            "invalidated": [r.to_json() for r in self.invalidated],
            "usedHostFallback": self.used_host_fallback,
            "breakerState": self.breaker_state,
        }


class ServingGuard:
    """Aggregate guard a :class:`~..serving.ScoringPlan` carries:
    admission + output checks + breaker + per-batch deadline. Built via
    ``plan.with_guardrails(...)`` (serving/plan.py)."""

    def __init__(self, model,
                 admission: Optional[AdmissionPolicy] = None,
                 output_guard: Optional[OutputGuard] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 deadline_seconds: Optional[float] = None):
        self.schema = SchemaGuard(model, admission)
        self.output = output_guard or OutputGuard()
        self.breaker = breaker or CircuitBreaker()
        #: per-batch device-dispatch deadline (None = no deadline)
        self.deadline_seconds = deadline_seconds
