"""Self-healing serving: the detect -> retrain -> validate -> swap ->
rollback loop over the live micro-batching loop (docs/self_healing.md).

The PR-5 :class:`~.sentinel.DriftSentinel` detects trouble; this module
makes the serving loop RECOVER from it. Per (model, tenant):

- **detect** — every finished batch feeds a retained ring of recently
  admitted raw records and polls the tenant's sentinel. A feature
  escalated to ``degrade`` arms the loop (once; a cooldown guards
  against thrash).
- **retrain** — a background warm-start refit (runtime/refit.py) on the
  lifecycle worker thread: base records + the labeled live window,
  journal-resumed when the workflow carries a ModelSelector, retried
  under the runtime RetryPolicy, bounded by a wall-clock budget. A
  failed retrain QUARANTINES the lane (ledger + counters) and the old
  model keeps serving.
- **canary** — the candidate shadow-scores the retained ring against
  the live model: zero ``OutputGuard`` invalidations required, then the
  labeled-accuracy floor (candidate >= live - ``metric_slack``) or,
  unlabeled, prediction agreement >= ``min_agreement``. A rejected
  candidate is dropped; nothing changes on the serving path.
- **swap** — the candidate's ScoringPlan buckets are PRE-COMPILED,
  fresh drift fingerprints are computed from the live window (so the
  new sentinel measures drift against what the candidate was actually
  trained on), and the PlanCache entry is replaced atomically between
  batches (``PlanCache.swap_entry``): in-flight batches finish on the
  entry they captured, zero requests dropped, and under the default
  ``tenant`` swap policy every other tenant keeps the ORIGINAL entry
  object — bitwise unaffected.
- **watch / rollback** — the previous entry stays pinned for one watch
  window. A post-swap injected fault, breaker trip or fresh drift
  degrade rolls the pinned entry back instantly
  (``PlanCache.rollback``); a clean window commits the swap.

Every transition lands in telemetry counters (``lifecycle_*``),
``lifecycle`` events, the span tracer (``lifecycle.retrain`` /
``.canary`` / ``.swap`` / ``.rollback``) and
``ServingServer.metrics_snapshot()``. Every path is drillable through
``TX_FAULT_PLAN`` sites ``lifecycle:<model>:retrain|canary|postswap``.
Off by default: ``ServeConfig.lifecycle is None`` keeps the serving
loop byte-identical to a build without this module.
"""
from __future__ import annotations

import collections
import concurrent.futures as _cf
import itertools
import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.context import RuntimeContext
from ..runtime.errors import classify_error
from ..runtime.faults import maybe_inject
from ..runtime.refit import RefitSpec, run_refit
from .guard import OutputGuard

_log = logging.getLogger(__name__)

__all__ = ["LifecycleConfig", "ModelLifecycle",
           "ST_IDLE", "ST_RETRAINING", "ST_CANARY", "ST_WATCH"]

ST_IDLE = "idle"
ST_RETRAINING = "retraining"
ST_CANARY = "canary"
ST_WATCH = "watch"


@dataclass
class LifecycleConfig:
    """Knobs of the self-healing loop (``tx serve --auto-retrain``)."""
    enabled: bool = True
    #: wall-clock budget for one background retrain (None = unbounded)
    retrain_budget_seconds: Optional[float] = 120.0
    #: retained ring of recent admitted records per (model, tenant) —
    #: the canary validation set and the live refit window
    canary_rows: int = 64
    #: "tenant" swaps only the drifted tenant's entry (other tenants
    #: keep the original object, bitwise unaffected); "model" replaces
    #: the shared entry for every tenant of the model
    swap_policy: str = "tenant"
    #: canary metric floor: candidate labeled accuracy may trail the
    #: live model's by at most this much
    metric_slack: float = 0.02
    #: unlabeled canary floor: old/new prediction agreement
    min_agreement: float = 0.98
    #: batches the previous entry stays pinned after a swap; a fault in
    #: the window rolls back, a clean window commits
    watch_batches: int = 3
    #: seconds after a completed cycle before the same lane may arm
    #: again
    cooldown_seconds: float = 30.0
    #: default journal/save locations for models without a registered
    #: RefitSpec
    checkpoint_dir: Optional[str] = None
    save_dir: Optional[str] = None

    def __post_init__(self):
        if self.swap_policy not in ("tenant", "model"):
            raise ValueError(
                f"swap_policy must be 'tenant' or 'model', "
                f"got {self.swap_policy!r}")


class ModelLifecycle:
    """One server's lifecycle manager. Hot-path cost when idle: a dict
    lookup and a ring append per finished batch; everything heavy runs
    on the single dedicated worker thread."""

    def __init__(self, server, config: LifecycleConfig):
        self.server = server
        self.config = config
        self._pool = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tx-lifecycle")
        self._lock = threading.Lock()
        #: (model, tenant) -> ring of recent admitted raw records
        self._rings: Dict[Tuple[str, str],
                          "collections.deque[dict]"] = {}
        self._states: Dict[Tuple[str, str], str] = {}
        self._watch: Dict[Tuple[str, str], dict] = {}
        self._cooldown_until: Dict[Tuple[str, str], float] = {}
        self._specs: Dict[str, RefitSpec] = {}
        self._generations = itertools.count(1)
        #: high-water mark of issued generations — serialized by the
        #: warm-restart snapshot so a restarted process keeps counting
        #: where this one stopped (serving/state.py)
        self.last_generation = 0
        #: the retry/quarantine runtime the refits run under; failed
        #: retrains land in its quarantine ledger
        self.runtime = RuntimeContext()
        #: transition log (bounded), surfaced in metrics_snapshot()
        self.history: "collections.deque[dict]" = collections.deque(
            maxlen=64)

    # -- registration ------------------------------------------------------
    def register(self, name: str, spec: RefitSpec) -> None:
        self._specs[name] = spec

    def spec_for(self, name: str) -> RefitSpec:
        spec = self._specs.get(name)
        if spec is not None:
            return spec
        return RefitSpec(checkpoint_dir=self.config.checkpoint_dir,
                         save_dir=self.config.save_dir)

    # -- hot-path hook (device/fallback pool threads) ----------------------
    def note_batch(self, prep) -> None:
        """Called by ``ServingServer._finish_batch`` after the sentinel
        observed the batch. Feeds the ring, ticks an active post-swap
        watch, and arms the heal cycle on a degrade escalation."""
        key = (prep.model, prep.tenant)
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = collections.deque(
                maxlen=max(1, int(self.config.canary_rows)))
        qmask = prep.qmask
        for i, req in enumerate(prep.requests):
            if not qmask[i]:
                ring.append(dict(req.record))
        watch = self._watch.get(key)
        if watch is not None:
            self._watch_tick(key, prep, watch)
            return
        if self._states.get(key, ST_IDLE) != ST_IDLE:
            return
        sentinel = prep.guards.sentinel
        if sentinel is None:
            return
        reported = getattr(sentinel, "_reported", None) or {}
        if not any(s == "degrade" for s in reported.values()):
            return
        if time.monotonic() < self._cooldown_until.get(key, 0.0):
            return
        self._arm(key)

    def _arm(self, key: Tuple[str, str]) -> None:
        with self._lock:
            if self._states.get(key, ST_IDLE) != ST_IDLE:
                return
            self._states[key] = ST_RETRAINING
        name, tenant = key
        gen = self.last_generation = next(self._generations)
        self._note("detect", counter="lifecycle_detect", model=name,
                   tenant=tenant, generation=gen)
        entry = self.server.plans.entry_for(
            name, tenant,
            buckets=getattr(self.server, "plan_buckets", (None, None)),
            lattice=getattr(self.server, "plan_lattice", None))
        self._pool.submit(self._heal, key, entry, gen)

    # -- the heal cycle (lifecycle worker thread) --------------------------
    def _heal(self, key: Tuple[str, str], entry, gen: int) -> None:
        name, tenant = key
        cfg = self.config
        ring = [dict(r) for r in self._rings.get(key, ())]
        self._note("retrain_start", counter="lifecycle_retrain_started",
                   model=name, tenant=tenant, generation=gen)
        try:
            with _trace.span("lifecycle.retrain", model=name,
                             tenant=tenant, generation=gen):
                result = run_refit(
                    entry.model, ring, spec=self.spec_for(name),
                    budget_seconds=cfg.retrain_budget_seconds,
                    name=name, retry=self.runtime.retry,
                    generation=gen)
        except Exception as e:
            kind = classify_error(e)
            self.runtime.quarantine(
                f"{name}/{tenant}", kind=kind,
                reason=f"{type(e).__name__}: {e}",
                error_type=type(e).__name__)
            self._note("retrain_failed",
                       counter="lifecycle_retrain_failures",
                       model=name, tenant=tenant, generation=gen,
                       kind=kind, error=f"{type(e).__name__}: {e}")
            self._finish(key, "retrain_failed")
            return
        self._note("retrain_end", counter="lifecycle_retrain_completed",
                   model=name, tenant=tenant, generation=gen,
                   seconds=round(result.seconds, 3), rows=result.rows,
                   resumed=result.resumed)
        with self._lock:
            self._states[key] = ST_CANARY
        try:
            with _trace.span("lifecycle.canary", model=name,
                             tenant=tenant, generation=gen):
                verdict = self._canary(name, entry, result.model, ring)
        except Exception as e:
            verdict = {"pass": False, "kind": classify_error(e),
                       "reason": f"{type(e).__name__}: {e}"}
        if not verdict.get("pass"):
            self._note("canary_fail", counter="lifecycle_canary_fail",
                       model=name, tenant=tenant, generation=gen,
                       **{k: v for k, v in verdict.items()
                          if k != "pass"})
            self._finish(key, "canary_rejected")
            return
        self._note("canary_pass", counter="lifecycle_canary_pass",
                   model=name, tenant=tenant, generation=gen,
                   **{k: v for k, v in verdict.items() if k != "pass"})
        try:
            with _trace.span("lifecycle.swap", model=name,
                             tenant=tenant, generation=gen,
                             policy=cfg.swap_policy):
                new_entry = self._build_entry(key, result.model, ring)
                scope = tenant if cfg.swap_policy == "tenant" else None
                self.server.plans.swap_entry(
                    name, new_entry, tenant=scope,
                    buckets=getattr(self.server, "plan_buckets",
                                    (None, None)),
                    lattice=getattr(self.server, "plan_lattice", None))
        except Exception as e:
            # a candidate that cannot compile/prewarm is REJECTED like
            # a canary failure — the classified reason is recorded and
            # the old model keeps serving
            self._note("swap_failed", counter="lifecycle_swap_failures",
                       model=name, tenant=tenant, generation=gen,
                       kind=classify_error(e),
                       error=f"{type(e).__name__}: {e}")
            self._finish(key, "swap_failed")
            return
        with self._lock:
            self._states[key] = ST_WATCH
            self._watch[key] = {
                "batches_left": max(1, int(cfg.watch_batches)),
                "generation": gen, "scope": scope}
        self._note("swap", counter="lifecycle_swaps", model=name,
                   tenant=tenant, generation=gen,
                   policy=cfg.swap_policy)

    # -- canary validation -------------------------------------------------
    def _canary(self, name: str, entry, candidate,
                ring: List[dict]) -> dict:
        """Shadow-score the retained ring through the live and the
        candidate model (host columnar — the candidate's device plan is
        only compiled after a PASS) and compare under the OutputGuard +
        the metric floor."""
        # the deterministic canary drill site
        maybe_inject("lifecycle", name, "canary")
        if not ring:
            return {"pass": False, "reason": "empty canary ring"}
        names = [f.name for f in candidate.result_features]
        new_scored = candidate.score([dict(r) for r in ring])
        old_scored = entry.model.score([dict(r) for r in ring])
        _, invalidated = OutputGuard().check(new_scored, names)
        if invalidated:
            return {"pass": False, "rows": len(ring),
                    "invalidated": len({r.row for r in invalidated}),
                    "reason": "candidate rows failed the output guard"}
        pred = names[0]
        new_vals = np.asarray(new_scored[pred].data, dtype=np.float64)
        old_vals = np.asarray(old_scored[pred].data, dtype=np.float64)
        responses = [f.name for f in candidate.raw_features()
                     if f.is_response]
        labels = None
        if len(responses) == 1:
            vals = [r.get(responses[0]) for r in ring]
            if all(v is not None for v in vals):
                labels = np.asarray(vals, dtype=np.float64)
        if labels is not None:
            old_acc = float(np.mean(np.round(old_vals) == labels))
            new_acc = float(np.mean(np.round(new_vals) == labels))
            ok = new_acc >= old_acc - self.config.metric_slack
            return {"pass": ok, "rows": len(ring),
                    "old_metric": round(old_acc, 4),
                    "new_metric": round(new_acc, 4),
                    **({} if ok else
                       {"reason": "candidate accuracy below the "
                                  "metric floor"})}
        agreement = float(np.mean(np.round(new_vals)
                                  == np.round(old_vals)))
        ok = agreement >= self.config.min_agreement
        return {"pass": ok, "rows": len(ring),
                "agreement": round(agreement, 4),
                **({} if ok else
                   {"reason": "old/new prediction agreement below "
                              "min_agreement"})}

    # -- candidate entry: prewarm + fresh guards ---------------------------
    def _build_entry(self, key: Tuple[str, str], candidate,
                     ring: List[dict]):
        from ..artifacts.loader import load_or_compile
        from .server import _CacheEntry, _TenantGuards
        name, tenant = key
        # the retrain just saved the candidate (run_refit -> save_model
        # exports its AOT artifacts): reuse them, so canary prewarm and
        # everything post-swap stays at ZERO serve-process compiles —
        # plan_compiles() is flat across a swap
        # (tests/test_aot_artifacts.py asserts it)
        kwargs = {}
        pb = getattr(self.server, "plan_buckets", (None, None))
        if pb[0] is not None:
            kwargs["min_bucket"] = pb[0]
        if pb[1] is not None:
            kwargs["max_bucket"] = pb[1]
        lat = getattr(self.server, "plan_lattice", None)
        if lat is not None:
            kwargs["lattice"] = lat
        plan = load_or_compile(candidate, **kwargs)
        self._prewarm(plan, ring)
        entry = _CacheEntry(
            model=candidate, plan=plan,
            result_names=[f.name for f in candidate.result_features])
        guards = _TenantGuards(candidate, self.server.config)
        if guards.sentinel is not None and ring:
            fresh = self._live_fingerprints(candidate, ring)
            if fresh:
                from .sentinel import DriftSentinel
                sentinel = DriftSentinel(
                    fresh,
                    thresholds=self.server.config.drift_thresholds)
                sentinel.generation = getattr(
                    candidate, "trained_generation", 0)
                guards.sentinel = sentinel
        entry.guards[tenant] = guards
        return entry

    def _prewarm(self, plan, ring: List[dict]) -> None:
        """Compile every bucket program a post-swap batch can hit
        BEFORE the swap, so steady state stays at zero compiles."""
        rows = [dict(r) for r in ring] or [{}]
        cap = int(self.server.config.max_batch)
        for bucket in plan.buckets():
            if bucket > cap:
                break
            batch = list(itertools.islice(itertools.cycle(rows),
                                          bucket))
            plan.score(batch)

    def _live_fingerprints(self, candidate, ring: List[dict]):
        """Fresh drift fingerprints from the live window — the new
        sentinel compares future traffic against the distribution the
        candidate was actually validated on, not stale train-time
        fingerprints (satellite: versioned fingerprints make the stale
        comparison a hard error, sentinel.py)."""
        from ..workflow.workflow import _generate_raw_data
        from .sentinel import compute_fingerprints
        try:
            ds = _generate_raw_data(candidate.raw_features(),
                                    [dict(r) for r in ring],
                                    require_responses=False)
            return compute_fingerprints(candidate.raw_features(), ds)
        except Exception as e:
            # no fingerprints is a degraded (loud) sentinel, not a
            # failed swap
            _log.warning("live-window fingerprints unavailable "
                         "(%s: %s)", type(e).__name__,
                         classify_error(e))
            return None

    # -- post-swap watch (device/fallback pool threads) --------------------
    def _watch_tick(self, key: Tuple[str, str], prep, watch: dict
                    ) -> None:
        name, tenant = key
        fault = None
        try:
            # the deterministic post-swap drill site
            maybe_inject("lifecycle", name, "postswap")
        except Exception as e:
            fault = f"{type(e).__name__}: {e} " \
                    f"({classify_error(e)})"
        breaker = prep.guards.breaker
        tripped = breaker is not None and breaker.state == "open"
        sentinel = prep.guards.sentinel
        reported = getattr(sentinel, "_reported", None) or {}
        regressed = any(s == "degrade" for s in reported.values())
        if fault or tripped or regressed:
            reason = fault or ("breaker_open" if tripped
                               else "drift_regression")
            self._rollback(key, watch, reason)
            return
        watch["batches_left"] -= 1
        if watch["batches_left"] <= 0:
            self._commit(key, watch)

    def _rollback(self, key: Tuple[str, str], watch: dict,
                  reason: str) -> None:
        name, tenant = key
        t0 = time.monotonic()
        restored = self.server.plans.rollback(
            name, tenant=watch["scope"],
            buckets=getattr(self.server, "plan_buckets", (None, None)),
            lattice=getattr(self.server, "plan_lattice", None))
        with self._lock:
            self._watch.pop(key, None)
        self._note("rollback", counter="lifecycle_rollbacks",
                   model=name, tenant=tenant,
                   generation=watch["generation"], reason=reason,
                   restored=restored)
        if _trace.enabled():
            _trace.add_span("lifecycle.rollback", t0, time.monotonic(),
                            attrs={"model": name, "tenant": tenant,
                                   "generation": watch["generation"],
                                   "reason": reason})
        self._finish(key, "rolled_back")

    def _commit(self, key: Tuple[str, str], watch: dict) -> None:
        name, tenant = key
        self.server.plans.commit(name, tenant=watch["scope"])
        with self._lock:
            self._watch.pop(key, None)
        self._note("commit", counter="lifecycle_commits", model=name,
                   tenant=tenant, generation=watch["generation"])
        self._finish(key, "healthy")
        # a committed swap is a durable lifecycle decision: persist it
        # so a restart resumes with the new generation, not the old
        if getattr(self.server, "state_manager", None) is not None:
            self.server.state_manager.write(reason="lifecycle-commit")

    def _finish(self, key: Tuple[str, str], outcome: str) -> None:
        _log.info("lifecycle cycle for %s/%s finished: %s", key[0],
                  key[1], outcome)
        with self._lock:
            self._states[key] = ST_IDLE
            self._cooldown_until[key] = (
                time.monotonic() + self.config.cooldown_seconds)

    # -- bookkeeping -------------------------------------------------------
    def _note(self, phase: str, counter: Optional[str] = None,
              **fields) -> None:
        if counter:
            _telemetry.count(counter)
        _telemetry.event("lifecycle", phase=phase, **fields)
        with self._lock:
            self.history.append({"phase": phase, **fields})

    def snapshot(self) -> dict:
        """The lifecycle slice of ``metrics_snapshot()``."""
        with self._lock:
            return {
                "states": {"/".join(k): v
                           for k, v in sorted(self._states.items())},
                "watch": {"/".join(k): dict(batches_left=w[
                    "batches_left"], generation=w["generation"])
                    for k, w in sorted(self._watch.items())},
                "ring_rows": {"/".join(k): len(r)
                              for k, r in sorted(self._rings.items())},
                "quarantined": list(
                    self.runtime.quarantined_families()),
                "history": list(self.history),
            }

    # -- warm-restart serialization (serving/state.py) ---------------------
    def state_dict(self) -> dict:
        """The restartable slice of lifecycle state: the generation
        high-water mark, per-lane cooldown time REMAINING (monotonic
        clocks do not survive a process), and the transition history.
        In-flight heal cycles are deliberately not serialized — a
        retrain that dies with the process re-arms from the sentinel
        signal, which IS restored."""
        now = time.monotonic()
        with self._lock:
            return {
                "generation": self.last_generation,
                "cooldownRemaining": {
                    "/".join(k): round(max(until - now, 0.0), 3)
                    for k, until in self._cooldown_until.items()
                    if until > now},
                "history": list(self.history),
            }

    def load_state(self, d: dict) -> None:
        gen = int(d.get("generation", 0))
        now = time.monotonic()
        with self._lock:
            if gen > 0:
                self.last_generation = gen
                self._generations = itertools.count(gen + 1)
            for lane, remaining in (d.get("cooldownRemaining")
                                    or {}).items():
                name, _, tenant = lane.partition("/")
                self._cooldown_until[(name, tenant)] = (
                    now + float(remaining))
            for rec in d.get("history") or []:
                if isinstance(rec, dict):
                    self.history.append(rec)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
