"""ScoringPlan: freeze a fitted DAG into batched, shape-bucketed XLA
programs.

The fit side already batches whole hyperparameter grids into single
vmapped XLA programs (parallel/cv.py); this module gives the SERVING
side the same treatment. Instead of walking the DAG stage-by-stage in
host numpy per batch (workflow.py) — or record-by-record in a Python
loop (local/scoring.py) — a plan:

1. **Compiles the DAG once.** ``topo_layers`` is walked and every
   fitted stage is asked for an array-level kernel
   (``Transformer.transform_arrays``, stages/base.py). Stages that
   lower are composed into ONE traced function; XLA then fuses the
   whole feature pipeline + model predict into a single program
   (operator-fusion rationale: arxiv 2301.13062 — hand the compiler
   the program, not one stage at a time). Stages that cannot lower run
   through their numpy ``transform_columns`` fallback, host-side,
   before (``pre``) or after (``post``) the device program; coverage
   is reported, parity is guaranteed either way.
2. **Buckets batch shapes.** Incoming batches are padded up to
   power-of-two row buckets with a validity mask, so arbitrary request
   sizes hit a handful of cached compilations instead of recompiling
   per batch size. Batches beyond the largest bucket are chunked.
   ``utils/jax_setup.enable_compilation_cache`` is enabled at plan
   compile, so a warm-started server skips XLA entirely.
3. **Scores in one round-trip.** One host->device transfer of the
   encoded raw arrays, one fused program, one device->host transfer of
   the requested outputs — with input-buffer donation on accelerator
   backends.

``plan_compiles()`` counts distinct (plan, bucket) programs — the
compile diagnostic bench.py reports (same idiom as
models/trees.tree_kernel_compiles): a repeated same-bucket batch adds
zero.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

import numpy as np

from ..features.columns import Dataset, FeatureColumn, PredictionColumn
from ..features.feature import topo_layers
from ..features.generator import FeatureGeneratorStage
from ..plans.common import (DEFAULT_MAX_BUCKET, DEFAULT_MIN_BUCKET,
                            PlanCompileError, PlanCoverage,
                            PlanStep as _Step, bucket_for,
                            bucket_profile as _shared_bucket_profile,
                            bucket_section as _bucket_section, compiles,
                            empty_raw_dataset as _empty_raw_dataset,
                            fallback_reason as _shared_fallback_reason,
                            default_lattice, normalize_lattice,
                            pad_rows as _pad_rows, plan_seq,
                            record_compile, record_rows)
from ..observability import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.faults import maybe_inject
from ..runtime.retry import RetryPolicy
from ..stages.base import Transformer
from ..types import Prediction
from .guard import (AdmissionPolicy, BreakerOpenError, CircuitBreaker,
                    GuardedScoreResult, GuardReason, OutputGuard,
                    ServingGuard, _invalidate_rows)

_log = logging.getLogger(__name__)

__all__ = ["ScoringPlan", "EncodedScoreBatch", "PlanCoverage",
           "PlanCompileError", "plan_compiles", "bucket_for",
           "DEFAULT_MIN_BUCKET", "DEFAULT_MAX_BUCKET"]


def plan_compiles() -> int:
    """Distinct compiled scoring programs so far in this process (the
    compile-count diagnostic bench.py's score mode reports)."""
    return compiles("score")


@dataclass
class EncodedScoreBatch:
    """A raw Dataset host-encoded, chunked, padded and masked — ready
    for device dispatch. Splitting :meth:`ScoringPlan.score_raw_dataset`
    into :meth:`~ScoringPlan.encode_raw_dataset` +
    :meth:`~ScoringPlan.dispatch_encoded` lets the serving loop
    double-buffer: batch k+1's host-side boxing/encoding overlaps batch
    k's in-flight device program (serving/server.py)."""
    #: raw Dataset AFTER the plan's "pre"-phase host fallbacks ran
    ds: Dataset
    n_rows: int
    #: (bucket, padded input arrays, validity mask, live rows) per chunk
    chunks: List[Tuple[int, tuple, np.ndarray, int]] = \
        field(default_factory=list)


class ScoringPlan:
    """A fitted ``WorkflowModel`` frozen into jitted, shape-bucketed
    scoring programs. Build once per model, reuse per batch:

    >>> plan = ScoringPlan(model).compile()
    >>> scored = plan.score(records)        # Dataset of result columns
    """

    def __init__(self, model, min_bucket: int = DEFAULT_MIN_BUCKET,
                 max_bucket: int = DEFAULT_MAX_BUCKET,
                 donate: Optional[bool] = None,
                 lattice: Optional[Sequence[int]] = None):
        self.model = model
        #: explicit bucket lattice (tuning/lattice.py choose_lattice)
        #: — None keeps the default power-of-two ladder over
        #: [min_bucket, max_bucket] bitwise; a lattice overrides the
        #: range args (its first/last rungs become min/max)
        self.lattice: Optional[Tuple[int, ...]] = \
            normalize_lattice(lattice) if lattice else None
        if self.lattice:
            self.min_bucket = self.lattice[0]
            self.max_bucket = self.lattice[-1]
        else:
            self.min_bucket = int(min_bucket)
            self.max_bucket = int(max_bucket)
        if self.min_bucket < 1 or self.max_bucket < self.min_bucket:
            raise ValueError(
                f"bad bucket range [{min_bucket}, {max_bucket}]")
        #: donate input buffers to the program (skips one device copy);
        #: None = auto (on for accelerators, off for CPU which does not
        #: implement donation and would warn per call)
        self.donate = donate
        self._plan_id = plan_seq()
        self._compiled = False
        self.coverage = PlanCoverage()
        #: serving guardrails (guard.py) — None means DISABLED: the
        #: default score path is the exact pre-guard code, byte-
        #: identical output (asserted in tests/test_serving_guard.py)
        self.guard: Optional[ServingGuard] = None
        #: online drift sentinel (sentinel.py) — None means disabled
        self.sentinel = None
        #: GuardedScoreResult of the most recent guarded batch
        self.last_guard_result: Optional[GuardedScoreResult] = None
        self._deadline_pool = None
        #: live rows dispatched per bucket (bucket_profile denominator)
        self._bucket_rows: Dict[int, int] = {}
        #: bucket -> deserialized AOT executable (artifacts/loader.py)
        #: — when present for a bucket, dispatch calls it INSTEAD of
        #: the jitted fn: same program, zero serve-process compiles
        self._aot_executables: Dict[int, Any] = {}
        #: the artifact manifest the executables came from (None =
        #: live-compiled plan)
        self.aot_manifest: Optional[dict] = None

    # -- compilation -------------------------------------------------------
    def compile(self) -> "ScoringPlan":
        """Walk the fitted DAG, classify every stage (device kernel vs
        numpy fallback), probe zero rows through the numpy path for
        output metadata, and build the jitted device program. Idempotent.
        """
        if self._compiled:
            return self
        from ..utils.jax_setup import enable_compilation_cache
        try:
            # warm-start serving: persisted XLA artifacts skip compiles
            enable_compilation_cache()
        except (OSError, RuntimeError):  # pragma: no cover - cache dir
            pass                         # not writable
        import jax

        self._raw_features = self.model.raw_features()
        self._result_names = [f.name for f in self.model.result_features]
        self._retry = RetryPolicy.from_env()
        stages = []
        for layer in topo_layers(self.model.result_features):
            for s in layer:
                if isinstance(s, FeatureGeneratorStage):
                    continue
                if not isinstance(s, Transformer):
                    raise PlanCompileError(
                        f"unfitted estimator {s!r} in scoring DAG")
                stages.append(s)

        self._proto_cols = self._probe_zero_rows(stages)
        # graceful degradation loop: a stage kernel that fails to trace
        # is DEMOTED to its host transform_columns fallback (with the
        # reason in coverage + a loud warning) and the plan rebuilds —
        # a bad kernel costs that stage's speedup, never the plan
        self._demoted: Dict[str, str] = {}
        for _ in range(len(stages) + 1):
            self.coverage = PlanCoverage()
            self._classify(stages)
            self._build_device_fn(jax)
            culprit = self._verify_device_fn(jax)
            if culprit is None:
                break
            uid, stage_name, reason = culprit
            self._demoted[uid] = reason
            _telemetry.count("plan_fallbacks")
            _telemetry.event("plan_fallback", stage=stage_name,
                             reason=reason)
            _log.warning(
                "scoring plan: stage %s failed to compile (%s); "
                "falling back to its host transform_columns path",
                stage_name, reason)
        self._compiled = True
        return self

    # -- AOT artifacts (artifacts/, docs/aot_artifacts.md) -----------------
    def attach_artifacts(self, execs: Dict[int, Any],
                         manifest: Optional[dict] = None
                         ) -> "ScoringPlan":
        """Route per-bucket dispatch through deserialized AOT
        executables (artifacts/loader.load_or_compile is the sanctioned
        caller). The executables ARE the programs the live path would
        compile — bitwise-identical outputs, asserted in
        tests/test_aot_artifacts.py."""
        self._aot_executables = dict(execs)
        self.aot_manifest = manifest
        return self

    def aot_active(self) -> bool:
        return bool(self._aot_executables)

    def aot_summary(self) -> Optional[dict]:
        """The snapshot/metrics slice: which artifact store this plan
        serves from (serving/state.py records it per model)."""
        if not self._aot_executables:
            return None
        from ..artifacts.store import manifest_summary
        out = manifest_summary(self.aot_manifest) or {}
        out["loadedBuckets"] = sorted(self._aot_executables)
        return out

    def fallbacks(self) -> int:
        """How many stages of this plan run through the host
        ``transform_columns`` fallback instead of the fused device
        program — including kernels demoted because they failed to
        compile (``coverage`` carries the reasons)."""
        return len(self.coverage.fallback)

    def _probe_zero_rows(self, stages: List[Transformer]
                         ) -> Dict[str, FeatureColumn]:
        """Run the whole DAG over ZERO rows through the numpy path —
        milliseconds, no device code — capturing every intermediate
        column's type/width/metadata so device outputs can be wrapped
        back into columns exactly as the numpy path would build them.
        Prediction outputs are skipped (they carry no metadata)."""
        ds = _empty_raw_dataset(self._raw_features)
        for stage in stages:
            out = stage.get_output()
            if issubclass(stage.static_output_type(), Prediction):
                ds = ds.with_column(
                    out.name, PredictionColumn.from_arrays(np.zeros(0)))
                continue
            try:
                ds = stage.transform_dataset(ds)
            except Exception as e:
                raise PlanCompileError(
                    f"stage {type(stage).__name__}({stage.uid}) failed "
                    f"the zero-row probe: {e!r}") from e
        return {name: ds[name] for name in ds.column_names}

    def _classify(self, stages: List[Transformer]) -> None:
        """Assign each stage to the device graph or a host fallback
        phase. A stage lowers when it has an array kernel AND every
        input is array-feedable; a fallback stage downstream of any
        lowered stage must wait for the device outputs (phase "post"),
        and nothing downstream of a "post" stage can lower (the device
        program runs once)."""
        producer: Dict[str, str] = {f.name: "host"
                                    for f in self._raw_features}
        steps: List[_Step] = []
        for stage in stages:
            out_name = stage.get_output().name
            in_names = tuple(f.name for f in stage.input_features)
            reason = ""
            if stage.uid in getattr(self, "_demoted", {}):
                reason = self._demoted[stage.uid]
            elif not stage.supports_arrays():
                reason = "no array kernel (transform_arrays)"
            else:
                for i, name in enumerate(in_names):
                    src = producer.get(name, "host")
                    if src == "post":
                        reason = (f"input {name!r} is produced by a "
                                  f"host fallback downstream of the "
                                  f"device graph")
                        break
                    if src == "device":
                        if stage.encodes_input(i):
                            reason = (f"input {name!r} needs host "
                                      f"encoding but is produced on "
                                      f"device")
                            break
                        continue
                    # host-materialized input: probe the encoder on the
                    # zero-row proto column
                    try:
                        stage.encode_input_column(
                            i, self._proto_cols[name])
                    except Exception as e:
                        reason = self._fallback_reason(
                            f"input {name!r} not encodable", e)
                        break
            if not reason:
                phase = "device"
                producer[out_name] = "device"
            else:
                upstream = {producer.get(n, "host") for n in in_names}
                phase = "pre" if upstream <= {"host"} else "post"
                producer[out_name] = "host" if phase == "pre" else "post"
                self.coverage.fallback.append(
                    (f"{type(stage).__name__}({out_name})", reason))
            if phase == "device":
                self.coverage.lowered.append(
                    f"{type(stage).__name__}({out_name})")
            steps.append(_Step(stage, out_name, in_names, phase, reason))
        self._steps = steps
        self._producer = producer

        # device inputs: (key, feature name, encoder) — encoders with
        # stage-specific lookups get their own key, identity encodings
        # share the feature name
        self._host_inputs: List[Tuple[str, str, Callable]] = []
        seen_keys = set()
        for step in steps:
            if step.phase != "device":
                continue
            for i, name in enumerate(step.input_names):
                if self._producer.get(name) == "device":
                    continue
                if step.stage.encodes_input(i):
                    key = f"enc:{step.stage.uid}:{i}"
                    enc = (lambda col, s=step.stage, slot=i:
                           s.encode_input_column(slot, col))
                else:
                    key = name
                    enc = (lambda col, s=step.stage, slot=i:
                           s.encode_input_column(slot, col))
                if key not in seen_keys:
                    seen_keys.add(key)
                    self._host_inputs.append((key, name, enc))

        # which device outputs must be materialized back into columns:
        # result features + inputs of host "post" fallbacks
        needed = set(self._result_names)
        for step in steps:
            if step.phase == "post":
                needed.update(step.input_names)
        self._device_outputs = [
            s.out_name for s in steps
            if s.phase == "device" and s.out_name in needed]

    @staticmethod
    def _fallback_reason(what: str, e: Exception) -> str:
        """One-line fallback reason for coverage records (the TX-R01
        contract: a swallowed hot-path exception must surface as a
        recorded degradation, never vanish)."""
        return _shared_fallback_reason(what, e)

    def _verify_device_fn(self, jax):
        """Abstractly trace the composed device program (zero device
        code — ``jax.eval_shape``) and return the first stage whose
        kernel fails as ``(uid, stage_name, reason)``, or None when the
        program traces clean. The compile() loop demotes the culprit to
        the host path and rebuilds."""
        # deterministic test hook: an injected per-stage compile fault
        # demotes exactly like a real trace failure
        for stage, out_name, _ in self._device_steps:
            try:
                maybe_inject("plan", type(stage).__name__, "compile")
            except Exception as e:
                return (stage.uid, f"{type(stage).__name__}({out_name})",
                        self._fallback_reason("injected compile fault",
                                              e))
        if not self._device_steps:
            return None
        sds = {}
        for key, name, enc in self._host_inputs:
            arr = np.asarray(enc(self._proto_cols[name]))
            sds[key] = jax.ShapeDtypeStruct(
                (self.min_bucket,) + arr.shape[1:], arr.dtype)
        env = dict(sds)
        for stage, out_name, keys in self._device_steps:
            try:
                env[out_name] = jax.eval_shape(
                    lambda *a, s=stage: s.transform_arrays(list(a)),
                    *[env[k] for k in keys])
            except Exception as e:
                return (stage.uid, f"{type(stage).__name__}({out_name})",
                        self._fallback_reason("kernel failed abstract "
                                              "trace", e))
        return None

    def _build_device_fn(self, jax) -> None:
        """Compose the lowered kernels into ONE traced function; jit it
        once — per-bucket shapes then hit jit's own compile cache."""
        device_steps = [
            (s.stage,
             s.out_name,
             tuple((f"enc:{s.stage.uid}:{i}"
                    if self._producer.get(n) != "device"
                    and s.stage.encodes_input(i) else n)
                   for i, n in enumerate(s.input_names)))
            for s in self._steps if s.phase == "device"]
        self._device_steps = device_steps
        in_keys = tuple(k for k, _, _ in self._host_inputs)
        out_names = tuple(self._device_outputs)

        def run(inputs, mask):
            env = dict(zip(in_keys, inputs))
            outs = []
            for stage, out_name, keys in device_steps:
                env[out_name] = stage.transform_arrays(
                    [env[k] for k in keys])
            for name in out_names:
                o = env[name]
                outs.append(o * (mask[:, None] if o.ndim == 2 else mask))
            return tuple(outs)

        if self.donate is None:
            self.donate = jax.default_backend() != "cpu"
        donate = (0,) if self.donate else ()
        self._device_fn = jax.jit(run, donate_argnums=donate)  # tx-lint: disable=TX-J02,TX-J06 (one jit per PLAN: compile() runs once per model, each bucket shape cached)

    # -- guardrails --------------------------------------------------------
    def with_guardrails(self, admission: Optional[AdmissionPolicy] = None,
                        output_guard: Optional[OutputGuard] = None,
                        breaker: Optional[CircuitBreaker] = None,
                        deadline_seconds: Optional[float] = None,
                        sentinel: Any = True,
                        thresholds=None) -> "ScoringPlan":
        """Enable the serving guardrails (docs/serving_guardrails.md):
        schema admission + output guards + circuit breaker + per-batch
        deadline, and (``sentinel=True``, the default here) the online
        drift sentinel. Guardrails are OFF unless this is called — the
        default ``score()`` path is byte-identical to the unguarded
        plan. ``sentinel`` may also be a prebuilt
        :class:`~.sentinel.DriftSentinel`."""
        self.guard = ServingGuard(self.model, admission=admission,
                                  output_guard=output_guard,
                                  breaker=breaker,
                                  deadline_seconds=deadline_seconds)
        from .sentinel import DriftSentinel
        if isinstance(sentinel, DriftSentinel):
            self.sentinel = sentinel
        elif sentinel:
            self.sentinel = DriftSentinel.for_model(
                self.model, thresholds=thresholds)
            if self.sentinel is None:
                _log.warning(
                    "drift sentinel unavailable: the model carries no "
                    "training fingerprints (re-save it with this build "
                    "or train in-process); serving without drift "
                    "monitoring")
        return self

    def drift_report(self) -> dict:
        """Per-feature JS divergence of scored traffic vs training
        (sentinel.py). ``{"enabled": False}`` when no sentinel is
        attached."""
        if self.sentinel is None:
            return {"enabled": False}
        report = self.sentinel.drift_report()
        report["enabled"] = True
        return report

    # -- execution ---------------------------------------------------------
    def score(self, data: Any) -> Dataset:
        """Score a Dataset / record iterable / DataReader through the
        plan; returns the raw + result feature columns (the
        ``Workflow.score`` contract). Compiles lazily on first use.

        With guardrails enabled (:meth:`with_guardrails`) this routes
        through :meth:`score_guarded`, stashing the quarantine/
        invalidation ledger on ``last_guard_result``."""
        if self.guard is not None or self.sentinel is not None:
            return self.score_guarded(data).scored
        self.compile()
        from ..workflow.workflow import _generate_raw_data
        ds = _generate_raw_data(self._raw_features, data,
                                require_responses=False)
        return self.score_raw_dataset(ds)

    def score_guarded(self, data: Any) -> GuardedScoreResult:
        """Guarded batch scoring: admission -> masked device scoring
        (or host fallback behind the breaker) -> output guards ->
        sentinel observation. The returned Dataset keeps the FULL row
        count; quarantined/invalidated rows carry NaN outputs and one
        machine-readable reason each."""
        self.compile()
        from ..readers.data_readers import DataReader
        from ..workflow.workflow import _generate_raw_data
        if self.guard is not None \
                and not isinstance(data, (Dataset, DataReader)):
            # record admission materializes the raw Dataset itself:
            # malformed fields become boxable placeholders instead of
            # crashing strict extraction, and the row is masked out
            ds, reasons = self.guard.schema.admit_records(list(data))
            return self._score_guarded_raw(ds, pre_reasons=reasons,
                                           columnar_admission=False)
        ds = _generate_raw_data(self._raw_features, data,
                                require_responses=False)
        return self._score_guarded_raw(ds)

    def _score_guarded_raw(self, ds: Dataset,
                           pre_reasons: Optional[List[GuardReason]] = None,
                           columnar_admission: bool = True
                           ) -> GuardedScoreResult:
        """Core guarded path over a materialized raw Dataset."""
        with _trace.span("score.guarded", rows=ds.n_rows):
            return self._score_guarded_raw_inner(
                ds, pre_reasons=pre_reasons,
                columnar_admission=columnar_admission)

    def _score_guarded_raw_inner(self, ds: Dataset,
                                 pre_reasons: Optional[
                                     List[GuardReason]] = None,
                                 columnar_admission: bool = True
                                 ) -> GuardedScoreResult:
        n = ds.n_rows
        quarantined: List[GuardReason] = list(pre_reasons or [])
        if self.guard is not None and columnar_admission:
            ds, more = self.guard.schema.admit_dataset(ds)
            quarantined.extend(more)
        qmask = np.zeros(n, dtype=bool)
        for r in quarantined:
            if 0 <= r.row < n:
                qmask[r.row] = True
        valid = (~qmask).astype(np.float64)

        breaker = self.guard.breaker if self.guard is not None else None
        used_fallback = False
        try:
            if breaker is not None:
                breaker.before_dispatch()
            scored = self.score_raw_dataset(ds, valid_mask=valid)
            if breaker is not None:
                breaker.record_success()
        except BreakerOpenError as e:
            used_fallback = True
            _telemetry.count("serving_breaker_short_circuits")
            _log.warning("scoring breaker open; host fallback: %s", e)
            scored = self._score_host_fallback(ds)
        except Exception as e:
            # device dispatch failed after retries: trip the breaker
            # and serve this batch through the host columnar fallback
            # (classified + recorded — the TX-R01/TX-R02 contract)
            from ..runtime.errors import BUG, classify_error
            if breaker is None or classify_error(e) == BUG:
                raise
            breaker.record_failure()
            used_fallback = True
            _telemetry.count("serving_device_failures")
            _telemetry.event("serving_fallback",
                             error=f"{type(e).__name__}: {e}",
                             breaker=breaker.state)
            _log.warning(
                "device scoring failed (%s: %s); host fallback "
                "(breaker %s)", type(e).__name__, e, breaker.state)
            scored = self._score_host_fallback(ds)

        # deterministic test hook: poison one output row so the output
        # guard's invalidate path is provable under TX_FAULT_PLAN
        if maybe_inject("serving", "output", "guard") == "nan":
            scored = _poison_first_valid_row(scored, self._result_names,
                                             qmask)

        invalidated: List[GuardReason] = []
        if self.guard is not None:
            scored, invalidated = self.guard.output.check(
                scored, self._result_names, skip_rows=qmask)
        if qmask.any():
            # quarantined rows were masked out of the device batch;
            # their zeroed outputs are garbage by construction — NaN
            # them so nothing downstream mistakes them for scores
            scored = _invalidate_rows(scored, self._result_names, qmask)

        if self.sentinel is not None:
            obs = ds.take(np.flatnonzero(~qmask)) if qmask.any() else ds
            self.sentinel.observe_dataset(obs)

        n_bad = int(qmask.sum())
        _telemetry.count("serving_rows_scored", n - n_bad)
        if n_bad:
            _telemetry.count("serving_rows_quarantined", n_bad)
        if invalidated:
            _telemetry.count("serving_rows_invalidated",
                             len({r.row for r in invalidated}))
        result = GuardedScoreResult(
            scored=scored, quarantined=quarantined,
            invalidated=invalidated, used_host_fallback=used_fallback,
            breaker_state=(breaker.state if breaker is not None
                           else CircuitBreaker.CLOSED))
        self.last_guard_result = result
        return result

    def score_host_columnar(self, ds: Dataset) -> Dataset:
        """The existing host columnar path (per-stage numpy kernels,
        layer by layer) as a whole-batch fallback when the device is
        unavailable — same outputs as ``engine="columnar"``. Public:
        the serving loop routes breaker-open / failed-dispatch batches
        here per tenant (serving/server.py)."""
        from ..workflow.workflow import _fit_and_transform_layers
        _telemetry.count("serving_host_fallback_batches")
        layers = topo_layers(self.model.result_features)
        scored, _ = _fit_and_transform_layers(layers, ds, fit=False)
        return self._select_outputs(scored)

    #: pre-PR-8 internal name, kept for call-site compatibility
    _score_host_fallback = score_host_columnar

    def score_raw_dataset(self, ds: Dataset,
                          valid_mask: Optional[np.ndarray] = None
                          ) -> Dataset:
        """Score an already-materialized raw Dataset (all raw feature
        columns present; absent responses NaN-filled by the caller).
        ``valid_mask`` (guarded path) zeroes quarantined rows inside
        the padded device batch — same shapes, zero recompiles."""
        self.compile()
        return self.dispatch_encoded(
            self.encode_raw_dataset(ds, valid_mask=valid_mask))

    def encode_raw_dataset(self, ds: Dataset,
                           valid_mask: Optional[np.ndarray] = None
                           ) -> EncodedScoreBatch:
        """The HOST half of scoring: run the "pre"-phase numpy
        fallbacks, encode every device input column once, and chunk/
        pad/mask the arrays onto the power-of-two bucket lattice. Pure
        host work — the serving loop runs it for batch k+1 while batch
        k's device program is still in flight (double-buffering)."""
        self.compile()
        n = ds.n_rows
        with _trace.span("score.encode", rows=n):
            return self._encode_raw_dataset_inner(ds, valid_mask)

    def _encode_raw_dataset_inner(self, ds: Dataset,
                                  valid_mask: Optional[np.ndarray]
                                  ) -> EncodedScoreBatch:
        n = ds.n_rows
        # phase "pre": numpy fallbacks feeding the device graph
        for step in self._steps:
            if step.phase == "pre":
                ds = step.stage.transform_dataset(ds)

        # encode once per host input, then chunk onto the bucket lattice
        encoded = [(key, enc(ds[name]))
                   for key, name, enc in self._host_inputs]
        chunks: List[Tuple[int, tuple, np.ndarray, int]] = []
        for start in range(0, max(n, 1), self.max_bucket):
            stop = min(start + self.max_bucket, n)
            rows = stop - start
            bucket = bucket_for(rows, self.min_bucket, self.max_bucket,
                                lattice=self.lattice)
            inputs = tuple(_pad_rows(arr[start:stop], bucket)
                           for _, arr in encoded)
            mask = np.zeros(bucket, dtype=np.float64)
            if valid_mask is None:
                mask[:rows] = 1.0
            else:
                mask[:rows] = valid_mask[start:stop]
            chunks.append((bucket, inputs, mask, rows))
            if n == 0:
                break
        return EncodedScoreBatch(ds=ds, n_rows=n, chunks=chunks)

    def dispatch_encoded(self, enc: EncodedScoreBatch) -> Dataset:
        """The DEVICE half of scoring: dispatch every encoded chunk
        through the fused program (per-bucket cost recorded for
        :meth:`bucket_profile`), then materialize columns and run the
        "post"-phase host fallbacks."""
        out_chunks: List[List[np.ndarray]] = [[] for _ in
                                              self._device_outputs]
        with _trace.span("score.dispatch", rows=enc.n_rows,
                         chunks=len(enc.chunks)):
            for bucket, inputs, mask, rows in enc.chunks:
                if bucket in self._aot_executables:
                    # AOT path: the program was deserialized, not
                    # compiled — the compile diagnostic stays flat
                    _telemetry.count("serve_aot_dispatches")
                else:
                    record_compile("score", (self._plan_id, bucket))
                self._bucket_rows[bucket] = \
                    self._bucket_rows.get(bucket, 0) + rows
                # real (pre-padding) rows: the occupancy histogram the
                # lattice chooser trains on (plans/common.record_rows)
                record_rows("score", rows)
                # the bucket section reports into the span as a child
                # carrying the per-bucket compile/execute split
                # (utils/compile_time section observer)
                with _bucket_section("score", self._plan_id, bucket):
                    outs = self._dispatch_device(inputs, mask,
                                                 bucket=bucket)
                for i, o in enumerate(outs):
                    out_chunks[i].append(np.asarray(o)[:rows])
        return self._finish_score(enc.ds, out_chunks)

    def bucket_profile(self) -> Dict[int, dict]:
        """Observed per-bucket dispatch cost of THIS plan:
        ``{bucket: {calls, wall_seconds, compile_seconds,
        execute_seconds, rows}}`` (plans/common.bucket_profile over
        utils/compile_time sections). Lattice-aware by construction:
        keys are the buckets ACTUALLY dispatched (whatever rungs this
        plan's lattice has) and ``rows`` is the real pre-padding row
        count per bucket — nothing assumes a power-of-two ladder. The
        serving coalescer (serving/server.py) reads this to pick its
        dispatch target from recorded data; bench emits it."""
        return _shared_bucket_profile("score", self._plan_id,
                                      self._bucket_rows)

    def _aot_dispatch_fallback(self, bucket, e: Exception):
        """A loaded executable that fails at CALL time (arg layout
        drift, backend refusal) is dropped for its bucket — the live
        jit path takes over seamlessly — and the degradation is
        recorded loudly (the artifacts loud-fallback contract)."""
        self._aot_executables.pop(bucket, None)
        _telemetry.count("serve_aot_dispatch_errors")
        _telemetry.event("serve_aot_dispatch_error", bucket=bucket,
                         error=f"{type(e).__name__}: {e}")
        _log.warning(
            "AOT executable for bucket %s failed at dispatch "
            "(%s: %s); live-compiling this bucket from now on",
            bucket, type(e).__name__, e)
        record_compile("score", (self._plan_id, bucket))

    def _dispatch_device(self, inputs, mask, bucket=None):
        """One fused-program dispatch behind the runtime retry policy:
        a preemption/RESOURCE_EXHAUSTED-shaped backend error retries
        with backoff (runtime/retry.py) instead of failing the serving
        request; persistent errors propagate to the caller. With a
        guardrail deadline configured, the whole dispatch (retries
        included) runs under a per-batch wall-clock budget — a hung
        backend is abandoned (the thread is orphaned, exactly like the
        selector's family deadline) and surfaces as DEADLINE_EXCEEDED
        for the breaker/fallback layer.

        With an AOT executable attached for ``bucket`` the dispatch
        calls it instead of the jitted fn — the identical program,
        deserialized rather than compiled."""
        def attempt():
            maybe_inject("plan", "device", "dispatch")
            aot = (self._aot_executables.get(bucket)
                   if bucket is not None else None)
            if aot is not None:
                try:
                    return aot(inputs, mask)
                except Exception as e:
                    self._aot_dispatch_fallback(bucket, e)
            return self._device_fn(inputs, mask)

        deadline = (self.guard.deadline_seconds
                    if self.guard is not None else None)
        if deadline is None:
            return self._retry.call(attempt, description="plan-dispatch")
        import concurrent.futures as _cf
        if self._deadline_pool is None:
            self._deadline_pool = _cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tx-serve-dispatch")
        future = self._deadline_pool.submit(
            self._retry.call, attempt, description="plan-dispatch")
        try:
            return future.result(timeout=deadline)
        except _cf.TimeoutError:
            future.cancel()
            # the pool thread may be wedged inside the backend; a new
            # pool is created for the next batch rather than queueing
            # behind it
            self._deadline_pool = None
            _telemetry.count("serving_deadline_exceeded")
            raise TimeoutError(
                f"DEADLINE_EXCEEDED: device scoring batch exceeded "
                f"the {deadline}s per-batch deadline") from None

    def _finish_score(self, ds: Dataset, out_chunks) -> Dataset:
        for name, chunks in zip(self._device_outputs, out_chunks):
            arr = (np.concatenate(chunks, axis=0) if chunks
                   else np.zeros(0))
            ds = ds.with_column(name, self._wrap_output(name, arr))

        # phase "post": numpy fallbacks consuming device outputs
        for step in self._steps:
            if step.phase == "post":
                ds = step.stage.transform_dataset(ds)
        return self._select_outputs(ds)

    def _select_outputs(self, ds: Dataset) -> Dataset:
        keep = [f.name for f in self._raw_features if f.name in ds] \
            + [nm for nm in self._result_names]
        seen, names = set(), []
        for nm in keep:
            if nm not in seen:
                seen.add(nm)
                names.append(nm)
        return ds.select(names)

    def _wrap_output(self, name: str, arr: np.ndarray) -> FeatureColumn:
        """Materialize a device output array as the column the numpy
        path would have produced (metadata from the zero-row probe;
        Prediction raws through the model's own prediction_from_raw)."""
        step = next(s for s in self._steps if s.out_name == name)
        stage = step.stage
        if issubclass(stage.static_output_type(), Prediction):
            return stage.prediction_from_raw(arr)
        proto = self._proto_cols[name]
        if proto.kind == "vector":
            arr = arr.reshape(len(arr), -1)
            return FeatureColumn(ftype=proto.ftype, data=arr,
                                 metadata=proto.metadata)
        return FeatureColumn(ftype=proto.ftype, data=arr.reshape(-1))

    # -- introspection -----------------------------------------------------
    def describe(self) -> dict:
        """Plan summary for logs/benchmarks."""
        self.compile()
        return {
            "stages": len(self._steps),
            "device_stages": len(self.coverage.lowered),
            "fallback_stages": len(self.coverage.fallback),
            "coverage": self.coverage.to_json(),
            "host_inputs": [k for k, _, _ in self._host_inputs],
            "device_outputs": list(self._device_outputs),
            "buckets": self.buckets(),
            "lattice": list(self.lattice) if self.lattice else None,
        }

    def buckets(self) -> List[int]:
        """The plan's bucket ladder: the explicit lattice when one was
        chosen, else the default power-of-two ladder (identical values
        to the historical doubling loop)."""
        if self.lattice:
            return list(self.lattice)
        return list(default_lattice(self.min_bucket, self.max_bucket))

    def device_input_avals(self, bucket: int):
        """The abstract inputs of one bucket's device program:
        ``(tuple of ShapeDtypeStruct, mask aval)`` — exactly the shapes
        ``dispatch_encoded`` feeds it (encoders probed on the zero-row
        proto columns, mask is the f64 validity vector)."""
        self.compile()
        import jax
        sds = []
        for key, name, enc in self._host_inputs:
            arr = np.asarray(enc(self._proto_cols[name]))
            sds.append(jax.ShapeDtypeStruct(
                (int(bucket),) + arr.shape[1:], arr.dtype))
        mask = jax.ShapeDtypeStruct((int(bucket),), np.float64)
        return tuple(sds), mask

    def lower_bucket(self, bucket: int):
        """AOT-lower ONE bucket's fused scoring program — no execution,
        no device buffers, works under ``JAX_PLATFORMS=cpu``. This is
        the plan auditor's entry point (analysis/audit.py): the
        returned ``jax.stages.Lowered`` exposes the StableHLO text the
        TX-P rules and the canonical IR fingerprint are computed from."""
        self.compile()
        if not self._device_steps:
            raise PlanCompileError(
                "plan has no device program (every stage fell back to "
                "host numpy); nothing to lower")
        inputs, mask = self.device_input_avals(bucket)
        return self._device_fn.lower(inputs, mask)


def _poison_first_valid_row(scored: Dataset, result_names, qmask
                            ) -> Dataset:
    """TX_FAULT_PLAN ``serving:output:guard:N=nan`` hook: corrupt the
    first non-quarantined row's outputs with NaN, so the output guard's
    invalidate-with-reason path is provable end to end."""
    valid = np.flatnonzero(~qmask)
    if valid.size == 0:
        return scored
    row = int(valid[0])
    for name in result_names:
        if name not in scored:
            continue
        col = scored[name]
        if isinstance(col, PredictionColumn):
            data = col.data.copy()
            data[row] = np.nan
            scored = scored.with_column(name, PredictionColumn(
                ftype=col.ftype, data=data, metadata=col.metadata,
                probability=col.probability,
                raw_prediction=col.raw_prediction))
        elif col.kind == "numeric":
            data = np.asarray(col.data, dtype=np.float64).copy()
            data[row] = np.inf
            scored = scored.with_column(name, FeatureColumn(
                ftype=col.ftype, data=data, metadata=col.metadata))
    return scored


