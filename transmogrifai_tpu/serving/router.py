"""Fleet router: one asyncio front-end over N serving replicas
(docs/fleet.md).

A single serving process (docs/serving_loop.md) is one event loop on
one host; the fleet layer puts a router in front of N of them. The
router speaks the SAME newline-delimited JSON protocol as ``tx serve``
(cli/serve.py) — existing clients, including the reconnecting
:class:`~.client.TcpServingClient`, point at the router port and
notice nothing — and owns three fleet-only concerns:

- **Placement.** Each (model, tenant) lane is pinned to one replica,
  chosen by predicted dispatch cost from the tuning cost model
  (tuning/model.py) plus plan-cache pressure — NOT round-robin: a
  replica already hosting the lane's compiled plan is cheaper than one
  that would have to evict + recompile (docs/autotuning.md,
  docs/aot_artifacts.md). Lanes stick until their replica dies or
  drains, so per-tenant state (sentinels, breakers, fair-queue
  deficits) stays on one incarnation.
- **Failover.** Forwards carry the reconnect/resend semantics of
  :class:`~.client.TcpServingClient`, made async: a transport failure
  mid-request closes the backend link, re-places the lane on a
  survivor and RESENDS — the caller sees one answer, late replies for
  abandoned requests are deduped on the echoed ``request_id``. A
  ``{"ok": false, "draining": true}`` answer from a gracefully
  stopping replica (docs/serving_restart.md) is the rolling-deploy
  re-place signal: the lane moves, the request resends, zero
  client-observed failures. A replica the router marked dead on a
  transient blip is re-probed by the admission poll and restored to
  ``ok`` on a successful round trip (``fleet_replica_recoveries``) —
  router-side death is never permanent while the replica stays
  registered.
- **Fleet-coherent admission.** The router polls every replica's
  ``metrics_snapshot()["admission"]`` block (docs/admission.md) and
  merges them: fleet state is the WORST replica state, the drain rate
  is the fleet-wide sum, and when the merged state is ``shed`` the
  router sheds at ITS door for every lane at once — no replica sits in
  ``ok`` serving full rate while its neighbor browns out. Shed answers
  carry ``retry_after_ms`` derived from the merged drain rate.

Deterministic fault drills (runtime/faults.py, ``TX_FAULT_PLAN``):
``fleet:<replica>:partition`` is probed on every forward to that
replica (a raising fault — e.g. ``preempt`` — is treated as a
transport failure: reconnect, then fail over), and
``fleet:<replica>:hang`` stalls the forward in an executor thread so
the per-request timeout and the failover path are drillable without a
real hung replica. ``fleet:<replica>:kill`` lives in the replica
manager (serving/fleet.py).
"""
from __future__ import annotations

import asyncio
import itertools
import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Set, Tuple

from ..observability import trace as _trace
from ..runtime import telemetry as _telemetry
from ..runtime.errors import classify_error
from ..runtime.faults import InjectedFault, injector_active, maybe_inject
from ..runtime.retry import RetryPolicy

__all__ = ["FleetRouter", "RouterConfig", "ReplicaHandle",
           "BackendUnavailable", "merge_admission",
           "FLEET_METRICS_SCHEMA"]

#: schema identity of the router's merged metrics document
FLEET_METRICS_SCHEMA = "tx-fleet-metrics/1"

#: admission states ordered by severity (serving/admission.py)
_STATE_ORDER = {"ok": 0, "brownout": 1, "shed": 2}

#: bounds on the merged retry hint — same clamp the per-replica
#: controller applies (serving/admission.py retry_after_ms)
_MIN_RETRY_MS = 1
_MAX_RETRY_MS = 5000

#: ring of request ids whose replies were abandoned mid-failover —
#: a late reply for one of these is a duplicate, not an answer
_STALE_RING = 64


class BackendUnavailable(ConnectionError):
    """Every live replica (or every allowed failover attempt) failed
    to answer the forwarded request."""


@dataclass
class RouterConfig:
    """Router knobs. ``plan_budget`` mirrors the replicas'
    ``--plan-cache`` so the placement cost can model eviction
    pressure; the cost priors only matter until the profile store has
    real measurements."""
    max_failovers: int = 3          # distinct replicas tried per request
    forward_timeout: float = 30.0   # per-forward round-trip deadline
    admission_poll_s: float = 0.25  # merged-admission refresh period
    plan_budget: int = 4            # replica plan-cache budget (LRU slots)
    default_wall_ms: float = 1.0    # dispatch-cost prior (cold store)
    default_compile_ms: float = 250.0  # compile-cost prior (cold store)
    placement_bucket: int = 8       # bucket the dispatch prediction reads


@dataclass
class ReplicaHandle:
    """One registered backend replica as the router sees it."""
    name: str
    host: str
    port: int
    generation: int = 1
    #: "ok" | "draining" | "dead"
    state: str = "ok"
    #: last polled admission block (metrics_snapshot()["admission"])
    admission: Optional[dict] = None
    #: last polled process/plan slice, for the fleet metrics document
    last_metrics: Dict[str, Any] = field(default_factory=dict)

    def usable(self) -> bool:
        return self.state == "ok"


def merge_admission(snaps: Dict[str, Optional[dict]]) -> dict:
    """Fold per-replica admission snapshots into ONE fleet-wide block
    (the DrJAX map-reduce framing: replicas map, the router reduces).

    - ``state`` — the WORST replica state: one replica in ``shed``
      puts the whole fleet in ``shed``, which is what makes the
      brownout coherent (the router sheds every lane, so no replica
      keeps absorbing full rate while another drowns).
    - ``drain_rows_per_s`` — the SUM across replicas: the fleet drains
      its merged backlog with all its capacity.
    - ``retry_after_ms`` — merged backlog over merged drain rate,
      clamped exactly like the per-replica hint.
    """
    live = {n: s for n, s in snaps.items()
            if isinstance(s, dict) and s.get("enabled")}
    replicas = {n: {"state": s.get("state", "ok"),
                    "pressure": float(s.get("pressure", 0.0))}
                for n, s in snaps.items() if isinstance(s, dict)}
    if not live:
        return {"enabled": False, "state": "ok", "pressure": 0.0,
                "drain_rows_per_s": 0.0, "queue_rows": 0,
                "retry_after_ms": _MIN_RETRY_MS, "replicas": replicas}
    drain = sum(float(s.get("drain_rows_per_s", 0.0))
                for s in live.values())
    depth = sum(sum(int(v) for v in (s.get("queue_depth") or {})
                    .values()) for s in live.values())
    state = max((s.get("state", "ok") for s in live.values()),
                key=lambda st: _STATE_ORDER.get(st, 0))
    pressure = max(float(s.get("pressure", 0.0)) for s in live.values())
    retry = int(min(max(depth / max(drain, 1e-6) * 1000.0,
                        _MIN_RETRY_MS), _MAX_RETRY_MS))
    return {"enabled": True, "state": state,
            "pressure": round(pressure, 4),
            "drain_rows_per_s": round(drain, 1), "queue_rows": depth,
            "retry_after_ms": retry, "replicas": replicas}


class _BackendLink:
    """Async reconnecting JSON-lines client for ONE replica — the
    asyncio twin of :class:`~.client.TcpServingClient`: transport
    failures close, back off (``await asyncio.sleep``) and RESEND;
    answered verdicts return as-is. Requests are serialized per link
    (one lane talks to one replica at a time), and replies whose
    echoed ``request_id`` belongs to an abandoned earlier request are
    discarded, not surfaced."""

    def __init__(self, handle: ReplicaHandle, retry: RetryPolicy,
                 timeout: float):
        self.handle = handle
        self.retry = retry
        self.timeout = timeout
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._stale_rids: deque = deque(maxlen=_STALE_RING)

    async def _connect(self) -> None:
        if self._writer is not None:
            return
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.handle.host,
                                    self.handle.port),
            self.timeout)

    async def close(self) -> None:
        writer, self._reader, self._writer = self._writer, None, None
        if writer is not None:
            try:
                writer.close()
                await writer.wait_closed()
            except (OSError, ConnectionError):
                pass

    async def _roundtrip(self, line: bytes, expect_rid: Optional[str]
                         ) -> dict:
        if injector_active():
            # fleet:<replica>:hang — the stall runs in an executor
            # thread so only THIS forward waits; the surrounding
            # wait_for turns a long hang into a transport timeout and
            # the caller fails over (docs/fleet.md fault matrix)
            await asyncio.get_running_loop().run_in_executor(
                None, maybe_inject, "fleet", self.handle.name, "hang")
        await self._connect()
        self._writer.write(line)
        await self._writer.drain()
        while True:
            raw = await self._reader.readline()
            if not raw:
                raise ConnectionError(
                    f"replica {self.handle.name} closed the "
                    f"connection mid-request")
            doc = json.loads(raw)
            rid = (doc.get("request_id")
                   if isinstance(doc, dict) else None)
            wanted = (expect_rid is not None and rid is not None
                      and str(rid) == str(expect_rid))
            if rid is not None and not wanted \
                    and rid in self._stale_rids:
                # late reply for a request we already abandoned and
                # resent elsewhere — surfacing it would answer the
                # CURRENT request with a stale payload. A reply whose
                # rid matches expect_rid is NEVER stale: an in-link
                # reconnect resends the SAME rid, and its answer is
                # exactly the one we are waiting for.
                _telemetry.count("fleet_backend_duplicate_replies")
                continue
            if expect_rid is not None and rid is not None \
                    and str(rid) != str(expect_rid):
                _telemetry.count("fleet_backend_duplicate_replies")
                continue
            return doc

    async def request(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """One round trip with reconnect + resend under the bounded
        retry policy. Raises :class:`BackendUnavailable` when every
        attempt fails — the caller's failover signal."""
        line = (json.dumps(payload, default=float) + "\n").encode()
        expect_rid = payload.get("id")
        last: Optional[Exception] = None
        async with self._lock:
            for attempt in range(1, self.retry.max_attempts + 1):
                try:
                    # fleet:<replica>:partition — a raising fault
                    # (preempt/oom) IS the simulated partition: the
                    # send never reaches the replica
                    maybe_inject("fleet", self.handle.name,
                                 "partition")
                    return await asyncio.wait_for(
                        self._roundtrip(line, expect_rid),
                        self.timeout)
                except (OSError, ConnectionError, asyncio.TimeoutError,
                        json.JSONDecodeError, InjectedFault) as e:
                    last = e
                    await self.close()
                    _telemetry.count("fleet_backend_reconnects")
                    if attempt < self.retry.max_attempts:
                        await asyncio.sleep(self.retry.delay_for(
                            attempt,
                            f"fleet:{self.handle.name}:"
                            f"{self.handle.port}"))
            if expect_rid is not None:
                # only NOW is the request abandoned on this link (the
                # caller fails the lane over and resends elsewhere) —
                # a reply that straggles in later must not answer a
                # future request. Recording the rid per-attempt would
                # make the in-link reconnect discard its own resend's
                # genuine reply as a duplicate.
                self._stale_rids.append(expect_rid)
        raise BackendUnavailable(
            f"replica {self.handle.name} "
            f"({self.handle.host}:{self.handle.port}) unreachable "
            f"after {self.retry.max_attempts} attempts "
            f"[{classify_error(last)}]: {last}") from last

    async def probe(self) -> dict:
        """One SINGLE-attempt metrics round trip with a short
        deadline and no backoff — the router's dead-replica recovery
        probe (:meth:`FleetRouter.poll_admission_once`). Kept separate
        from :meth:`request` so a still-dead replica costs the poll
        loop one fast failure, not a full retry ladder."""
        line = b'{"metrics": true}\n'
        async with self._lock:
            try:
                maybe_inject("fleet", self.handle.name, "partition")
                return await asyncio.wait_for(
                    self._roundtrip(line, None),
                    min(self.timeout, 2.0))
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    json.JSONDecodeError, InjectedFault) as e:
                await self.close()
                raise BackendUnavailable(
                    f"replica {self.handle.name} "
                    f"({self.handle.host}:{self.handle.port}) probe "
                    f"failed [{classify_error(e)}]: {e}") from e


class FleetRouter:
    """The fleet front door: lane placement, forwarding with failover,
    merged admission, and the fleet metrics document. Runs entirely on
    ONE asyncio loop — replica managers on other threads talk to it
    only through the ``*_threadsafe`` entry points, which marshal onto
    the loop via ``call_soon_threadsafe`` (the TX-X03 contract)."""

    def __init__(self, config: Optional[RouterConfig] = None,
                 cost_model=None, retry: Optional[RetryPolicy] = None):
        self.config = config or RouterConfig()
        self.retry = retry or RetryPolicy.from_env()
        if cost_model is None:
            # load NOW, from sync construction context — the store
            # read is file I/O, which must never run on the event
            # loop inside the async forward path (lint TX-X01)
            from ..tuning.model import CostModel
            cost_model = CostModel.from_store()
        self._cost = cost_model
        self.replicas: Dict[str, ReplicaHandle] = {}
        self._links: Dict[str, _BackendLink] = {}
        #: (model, tenant) -> replica name; the sticky lane table
        self._lanes: Dict[Tuple[str, str], str] = {}
        #: live client connections (popped on disconnect — TX-R07)
        self._client_writers: Dict[int, asyncio.StreamWriter] = {}
        self._fleet_admission: dict = {
            "enabled": False, "state": "ok", "pressure": 0.0,
            "drain_rows_per_s": 0.0, "queue_rows": 0,
            "retry_after_ms": _MIN_RETRY_MS, "replicas": {}}
        self.default_model: Optional[str] = None
        self.on_replica_down: Optional[Callable[[str, str], None]] = None
        self.stats = {"requests": 0, "answered": 0, "failovers": 0,
                      "sheds": 0, "placements": 0,
                      "lane_replacements": 0, "unavailable": 0,
                      "recoveries": 0}
        self._rid_counter = itertools.count(1)
        self._conn_counter = itertools.count(1)
        self._started_at = time.time()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._poll_task: Optional[asyncio.Task] = None
        self._stop_event: Optional[asyncio.Event] = None

    # -- replica registry --------------------------------------------------
    def register_replica(self, name: str, host: str, port: int,
                         generation: int = 1) -> ReplicaHandle:
        """Add (or refresh, after a takeover respawn) one replica.
        Loop context only — threads use the ``_threadsafe`` variant."""
        old = self._links.pop(name, None)
        if old is not None and self._loop is not None:
            self._loop.create_task(old.close())
        handle = ReplicaHandle(name=name, host=host, port=port,
                               generation=generation)
        self.replicas[name] = handle
        self._links[name] = _BackendLink(handle, self.retry,
                                         self.config.forward_timeout)
        _telemetry.event("fleet_replica_registered", replica=name,
                         port=port, generation=generation)
        return handle

    def unregister_replica(self, name: str,
                           reason: str = "unregistered") -> None:
        handle = self.replicas.get(name)
        if handle is not None:
            handle.state = "dead"
        self._replace_lanes(name, reason)
        link = self._links.pop(name, None)
        if link is not None and self._loop is not None:
            self._loop.create_task(link.close())

    def mark_draining(self, name: str) -> None:
        """Stop placing lanes on ``name`` and move its existing lanes
        to survivors — the rolling-deploy pre-drain signal."""
        handle = self.replicas.get(name)
        if handle is not None and handle.state == "ok":
            handle.state = "draining"
        self._replace_lanes(name, "draining")

    # thread-safe marshals for the replica manager's watch thread ---------
    def _call_threadsafe(self, fn, *args) -> None:
        loop = self._loop
        if loop is None or loop.is_closed():
            fn(*args)
        else:
            loop.call_soon_threadsafe(fn, *args)

    def register_replica_threadsafe(self, name: str, host: str,
                                    port: int,
                                    generation: int = 1) -> None:
        self._call_threadsafe(self.register_replica, name, host, port,
                              generation)

    def unregister_replica_threadsafe(self, name: str,
                                      reason: str = "down") -> None:
        self._call_threadsafe(self.unregister_replica, name, reason)

    def mark_draining_threadsafe(self, name: str) -> None:
        self._call_threadsafe(self.mark_draining, name)

    def stop_threadsafe(self) -> None:
        """Ask a running :meth:`serve` loop to shut down from another
        thread — the in-process drills and bench phases own the router
        without owning a signal to send it."""
        loop, ev = self._loop, self._stop_event
        if loop is not None and ev is not None and not loop.is_closed():
            loop.call_soon_threadsafe(ev.set)

    # -- placement ---------------------------------------------------------
    def _placement_cost(self, name: str, model: str) -> float:
        """Predicted cost (ms) of routing one more lane of ``model``
        to ``name``: the model's predicted per-dispatch wall cost
        scaled by the replica's current lane load, plus a plan-cache
        pressure term — landing a model the replica does not already
        host costs its predicted compile, scaled up as the cache fills
        toward (and past) its LRU budget, where placement would force
        an eviction (docs/fleet.md)."""
        cfg = self.config
        est = self._cost.predict("score", bucket=cfg.placement_bucket)
        wall_ms = (est.wall * 1000.0 if est.wall
                   else cfg.default_wall_ms)
        compile_ms = (est.compile * 1000.0 if est.compile
                      else cfg.default_compile_ms)
        lanes_here = sum(1 for r in self._lanes.values() if r == name)
        models_here = {m for (m, _t), r in self._lanes.items()
                       if r == name}
        cost = wall_ms * (1.0 + lanes_here)
        if model not in models_here:
            pressure = len(models_here) / max(cfg.plan_budget, 1)
            cost += compile_ms * (1.0 + pressure)
        return cost

    def place(self, model: str, tenant: str,
              exclude: Optional[Set[str]] = None) -> str:
        """The replica for lane (model, tenant): sticky while its
        replica stays usable, otherwise re-placed on the cheapest
        survivor by :meth:`_placement_cost` (deterministic tie-break
        on replica name). Raises :class:`BackendUnavailable` when no
        usable replica remains."""
        exclude = exclude or set()
        lane = (model, tenant)
        current = self._lanes.get(lane)
        if current is not None and current not in exclude:
            handle = self.replicas.get(current)
            if handle is not None and handle.usable():
                return current
        best: Optional[Tuple[float, str]] = None
        for name in sorted(self.replicas):
            if name in exclude or not self.replicas[name].usable():
                continue
            score = self._placement_cost(name, model)
            if best is None or score < best[0]:
                best = (score, name)
        if best is None:
            raise BackendUnavailable(
                f"no usable replica for lane {model}/{tenant} "
                f"(replicas: "
                f"{ {n: h.state for n, h in self.replicas.items()} })")
        self._lanes[lane] = best[1]
        self.stats["placements"] += 1
        _telemetry.count("fleet_lane_placements")
        _telemetry.event("fleet_lane_placed", model=model,
                         tenant=tenant, replica=best[1],
                         cost_ms=round(best[0], 3))
        return best[1]

    def _replace_lanes(self, name: str, reason: str) -> None:
        moved = [lane for lane, r in self._lanes.items() if r == name]
        for lane in moved:
            del self._lanes[lane]
        if moved:
            self.stats["lane_replacements"] += len(moved)
            _telemetry.count("fleet_lane_replacements", len(moved))
            _telemetry.event("fleet_lanes_replaced", replica=name,
                             lanes=len(moved), reason=reason)

    def _mark_down(self, name: str, reason: str) -> None:
        handle = self.replicas.get(name)
        if handle is None or handle.state == "dead":
            return
        handle.state = "dead"
        _telemetry.count("fleet_replicas_down")
        _telemetry.event("fleet_replica_down", replica=name,
                         reason=reason[:200])
        self._replace_lanes(name, "replica down")
        if self.on_replica_down is not None:
            self.on_replica_down(name, reason)

    # -- merged admission --------------------------------------------------
    async def poll_admission_once(self) -> dict:
        """One poll + merge pass over every usable replica — the
        background poller's body, callable directly from tests."""
        for name in list(self.replicas):
            handle = self.replicas.get(name)
            link = self._links.get(name)
            if handle is None or link is None \
                    or handle.state == "draining":
                continue
            if handle.state == "dead":
                # recovery probe: a replica the ROUTER marked dead on
                # a transient blip (failed forward or metrics poll)
                # is still registered — one successful round trip
                # restores it. Without this, a brief network error
                # would shrink the fleet permanently: the manager
                # only re-announces a replica after a respawn, and a
                # healthy child never respawns.
                try:
                    answer = await link.probe()
                except BackendUnavailable:
                    _telemetry.count("fleet_recovery_probe_failures")
                    continue
                handle.state = "ok"
                self.stats["recoveries"] += 1
                _telemetry.count("fleet_replica_recoveries")
                _telemetry.event("fleet_replica_recovered",
                                 replica=name)
            else:
                try:
                    answer = await link.request({"metrics": True})
                except BackendUnavailable as e:
                    _telemetry.count("fleet_admission_poll_failures")
                    self._mark_down(name, f"metrics poll failed: {e}")
                    continue
            snap = answer.get("metrics", answer) \
                if isinstance(answer, dict) else {}
            handle.admission = snap.get("admission")
            handle.last_metrics = {
                "plan_compiles": snap.get("plan_compiles"),
                "answered": snap.get("answered"),
                "process": snap.get("process"),
                "plan_cache": snap.get("plan_cache"),
            }
        merged = merge_admission(
            {n: h.admission for n, h in self.replicas.items()
             if h.state != "dead"})
        if merged["state"] != self._fleet_admission.get("state"):
            _telemetry.event("fleet_admission_transition",
                             frm=self._fleet_admission.get("state"),
                             to=merged["state"],
                             pressure=merged["pressure"])
        self._fleet_admission = merged
        return merged

    async def _poll_admission_forever(self) -> None:
        while True:
            await asyncio.sleep(self.config.admission_poll_s)
            await self.poll_admission_once()

    @property
    def fleet_admission(self) -> dict:
        return self._fleet_admission

    # -- forwarding --------------------------------------------------------
    async def score(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Forward one score request: fleet-admission check at the
        door, then place -> forward -> (on failure or a draining
        answer) re-place and resend, up to ``max_failovers`` distinct
        replicas. The caller observes exactly one answer."""
        self.stats["requests"] += 1
        model = msg.get("model") or self.default_model
        tenant = str(msg.get("tenant", "default"))
        rid = str(msg.get("id") or f"flt-{next(self._rid_counter)}")
        merged = self._fleet_admission
        if merged.get("state") == "shed":
            # the COHERENT brownout: one merged decision sheds every
            # lane at the fleet door, hint from the merged drain rate
            self.stats["sheds"] += 1
            _telemetry.count("fleet_router_sheds")
            return {"ok": False, "request_id": rid, "shed": True,
                    "fleet": True,
                    "retry_after_ms": merged["retry_after_ms"],
                    "error": "ServeShed: fleet admission state is "
                             "shed (merged across replicas)",
                    "kind": "transient"}
        payload = dict(msg)
        payload["id"] = rid   # pin the id so resends dedupe downstream
        tried: Set[str] = set()
        t0 = time.time()
        for _hop in range(self.config.max_failovers + 1):
            try:
                name = self.place(model or "", tenant, exclude=tried)
            except BackendUnavailable:
                break
            link = self._links.get(name)
            if link is None:
                tried.add(name)
                continue
            try:
                answer = await link.request(payload)
            except BackendUnavailable as e:
                tried.add(name)
                self.stats["failovers"] += 1
                _telemetry.count("fleet_router_failovers")
                self._mark_down(name, str(e))
                continue
            if isinstance(answer, dict) and answer.get("draining"):
                # graceful drain answer = the rolling-deploy re-place
                # signal: move the lane, resend, caller never sees it
                tried.add(name)
                _telemetry.count("fleet_drain_replacements")
                self.mark_draining(name)
                continue
            if isinstance(answer, dict) and answer.get("shed") \
                    and merged.get("enabled"):
                # per-replica shed under a merged view: rewrite the
                # hint so every caller backs off by FLEET drain time
                answer["retry_after_ms"] = merged["retry_after_ms"]
            self.stats["answered"] += 1
            if _trace.enabled():
                _trace.add_span("fleet.forward", t0, time.time(),
                                attrs={"replica": name, "rid": rid,
                                       "model": model or "",
                                       "tenant": tenant,
                                       "hops": len(tried) + 1})
            return answer
        self.stats["unavailable"] += 1
        _telemetry.count("fleet_router_unavailable")
        return {"ok": False, "request_id": rid,
                "error": "BackendUnavailable: no usable replica "
                         "answered within the failover budget",
                "kind": "transient", "unavailable": True}

    # -- metrics -----------------------------------------------------------
    def ready(self) -> bool:
        return any(h.usable() for h in self.replicas.values())

    def metrics_snapshot(self) -> dict:
        """The fleet-level metrics document: router counters, the lane
        table, per-replica last-polled slices, and the merged
        admission block (docs/fleet.md)."""
        return {
            "schema": FLEET_METRICS_SCHEMA,
            "uptime_seconds": round(time.time() - self._started_at, 3),
            "router": dict(self.stats),
            "replicas": {
                name: {"state": h.state, "host": h.host,
                       "port": h.port, "generation": h.generation,
                       **h.last_metrics}
                for name, h in sorted(self.replicas.items())},
            "lanes": {f"{m}/{t}": r
                      for (m, t), r in sorted(self._lanes.items())},
            "admission": self._fleet_admission,
            "client_connections": len(self._client_writers),
        }

    # -- the JSON-lines front end ------------------------------------------
    async def handle(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        """One client connection: same protocol as cli/serve.py's
        handler — score requests, ``{"metrics": true}`` and
        ``{"ready": true}`` control lines — answered from the fleet."""
        key = next(self._conn_counter)
        self._client_writers[key] = writer
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError as e:
                    out = {"ok": False, "request_id": None,
                           "error": f"{type(e).__name__}: {e}",
                           "kind": classify_error(e)}
                    writer.write((json.dumps(out) + "\n").encode())
                    await writer.drain()
                    continue
                if isinstance(msg, dict) and msg.get("metrics"):
                    out = {"ok": True,
                           "metrics": self.metrics_snapshot()}
                elif isinstance(msg, dict) and msg.get("ready"):
                    out = {"ok": True, "ready": self.ready(),
                           "draining": False, "generation": 0,
                           "fleet": {n: h.state for n, h in
                                     sorted(self.replicas.items())}}
                elif isinstance(msg, dict):
                    out = await self.score(msg)
                else:
                    out = {"ok": False, "request_id": None,
                           "error": "TypeError: request must be a "
                                    "JSON object", "kind": "permanent"}
                writer.write((json.dumps(out, default=float) + "\n")
                             .encode())
                await writer.drain()
        except (OSError, ConnectionError):
            # client went away mid-answer: nothing to answer TO — the
            # finally below releases the writer entry either way
            _telemetry.count("fleet_client_disconnects")
        finally:
            # the disconnect-cleanup path (lint TX-R07): the writer
            # entry MUST leave the table when the connection does
            self._client_writers.pop(key, None)
            writer.close()

    async def serve(self, host: str, port: int,
                    ready_cb=None, max_requests: Optional[int] = None,
                    banner_extra: Optional[dict] = None) -> int:
        """Bind the router front end and run until SIGTERM/SIGINT (or
        ``max_requests`` answers). Prints the same one-line JSON
        banner shape as ``tx serve`` with ``"fleet": true``."""
        self._loop = asyncio.get_running_loop()
        self._stop_event = stop = asyncio.Event()
        server = await asyncio.start_server(self.handle, host, port)
        bound = server.sockets[0].getsockname()[1]
        banner = {"serving": True, "fleet": True, "host": host,
                  "port": bound,
                  "replicas": sorted(self.replicas)}
        if banner_extra:
            banner.update(banner_extra)
        print(json.dumps(banner), flush=True)
        if ready_cb is not None:
            ready_cb(bound)
        sig_installed = []
        try:
            import signal as _signal
            for sig in (_signal.SIGTERM, _signal.SIGINT):
                self._loop.add_signal_handler(sig, stop.set)
                sig_installed.append(sig)
        except (ValueError, OSError, RuntimeError,
                NotImplementedError):
            pass
        self._poll_task = asyncio.create_task(
            self._poll_admission_forever())

        async def _watch_budget():
            while max_requests and \
                    self.stats["answered"] < max_requests:
                await asyncio.sleep(0.05)
            stop.set()

        budget_task = (asyncio.create_task(_watch_budget())
                       if max_requests else None)
        try:
            await stop.wait()
        finally:
            for sig in sig_installed:
                try:
                    self._loop.remove_signal_handler(sig)
                except (ValueError, RuntimeError):  # pragma: no cover
                    _telemetry.count("fleet_signal_cleanup_races")
            if budget_task is not None:
                budget_task.cancel()
            self._poll_task.cancel()
            self._poll_task = None
            self._stop_event = None
            server.close()
            await server.wait_closed()
            for link in list(self._links.values()):
                await link.close()
        print(json.dumps({"fleet": True, **self.metrics_snapshot()},
                         default=float), flush=True)
        return 0
