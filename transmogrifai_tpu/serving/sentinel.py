"""Online drift sentinel: train/serve distribution-shift detection.

The paper's ``RawFeatureFilter`` prunes features whose train/score
distributions diverge — but only at *train* time. Once a model is
saved, nothing watches the traffic it scores. This module closes that
gap:

- at ``save_model`` time the training-data per-feature distributions
  (``FeatureDistribution`` + the numeric ``StreamingHistogram``
  sketches, checkers/raw_feature_filter.py) are serialized into the
  model directory as ``drift-fingerprints.json``;
- at serve time a :class:`DriftSentinel` maintains streaming
  per-feature sketches over the scored traffic (same binning, same
  hashing) and reports Jensen-Shannon divergence against the training
  fingerprints via ``plan.drift_report()`` — reusing the exact
  ``FeatureDistribution.js_divergence`` machinery the train-time
  filter uses, so "shift" means the same thing in both places.

Thresholds: per-feature JS >= ``warn_threshold`` marks the feature
(and the report) ``warn``; >= ``degrade_threshold`` marks it
``degrade`` (the CLI exits 2 on degrade). Both are knobs; reports on
fewer than ``min_rows`` observed rows stay ``ok`` — tiny samples make
noisy histograms, not drift evidence.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..checkers.raw_feature_filter import (FeatureDistribution,
                                           numeric_histogram_js)
from ..features.columns import ColumnKind, Dataset
from ..ops.vector_utils import stable_hash as _stable_hash
from ..runtime import telemetry as _telemetry
from ..types import OPNumeric
from ..utils.histogram import StreamingHistogram

__all__ = ["DriftSentinel", "FeatureFingerprint", "DriftThresholds",
           "FingerprintSchemaError", "compute_fingerprints",
           "save_fingerprints", "load_fingerprints",
           "load_fingerprint_doc", "DRIFT_FINGERPRINTS_FILE",
           "FINGERPRINT_SCHEMA",
           "STATUS_OK", "STATUS_WARN", "STATUS_DEGRADE"]

DRIFT_FINGERPRINTS_FILE = "drift-fingerprints.json"
FINGERPRINT_FORMAT_VERSION = 1
#: schema identity of the fingerprint document. A hot-swapped model
#: MUST NOT be compared against fingerprints written under a different
#: schema — the comparison would be silently meaningless — so load
#: rejects a mismatch loudly (FingerprintSchemaError) instead of
#: falling back to stale data.
FINGERPRINT_SCHEMA = "tx-drift-fingerprints/1"


class FingerprintSchemaError(ValueError):
    """drift-fingerprints.json was written under an incompatible
    schema; deliberately NOT swallowed by ``DriftSentinel.for_model``'s
    best-effort fallbacks."""

STATUS_OK = "ok"
STATUS_WARN = "warn"
STATUS_DEGRADE = "degrade"
_STATUS_ORDER = {STATUS_OK: 0, STATUS_WARN: 1, STATUS_DEGRADE: 2}


@dataclass(frozen=True)
class DriftThresholds:
    """JS-divergence thresholds (the train-time filter's default
    exclusion threshold is 0.90; serving warns far earlier because a
    serving drift report is advisory, not destructive)."""
    warn: float = 0.25
    degrade: float = 0.50
    #: a report over fewer observed rows than this stays "ok"
    min_rows: int = 50

    def status_for(self, js: float, rows: int) -> str:
        if rows < self.min_rows:
            return STATUS_OK
        if js >= self.degrade:
            return STATUS_DEGRADE
        if js >= self.warn:
            return STATUS_WARN
        return STATUS_OK


@dataclass
class FeatureFingerprint:
    """One raw feature's training-time distribution, serialized into
    the model dir. Numeric features carry the full streaming-histogram
    sketch (centroids + counts); categorical/text features the hashed
    ``bins``-bucket counts (FeatureDistribution.scala:58 semantics)."""
    name: str
    is_numeric: bool
    count: int = 0
    nulls: int = 0
    bins: int = 100
    #: hashed bucket counts (categorical) — empty for numeric
    counts: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.float64))
    #: streaming histogram (numeric) — None for categorical
    histogram: Optional[StreamingHistogram] = None

    def to_json(self) -> dict:
        return {
            "name": self.name, "isNumeric": self.is_numeric,
            "count": self.count, "nulls": self.nulls, "bins": self.bins,
            "counts": self.counts.tolist(),
            "histogram": (self.histogram.to_json()
                          if self.histogram is not None else None),
        }

    @classmethod
    def from_json(cls, d: dict) -> "FeatureFingerprint":
        return cls(
            name=d["name"], is_numeric=d["isNumeric"],
            count=d.get("count", 0), nulls=d.get("nulls", 0),
            bins=d.get("bins", 100),
            counts=np.asarray(d.get("counts", []), dtype=np.float64),
            histogram=(StreamingHistogram.from_json(d["histogram"])
                       if d.get("histogram") else None))


class _Sketch:
    """Streaming serve-side counterpart of one fingerprint."""

    def __init__(self, fp: FeatureFingerprint):
        self.fp = fp
        self.rows = 0
        self.nulls = 0
        if fp.is_numeric:
            self.histogram = StreamingHistogram(
                fp.histogram.max_bins if fp.histogram is not None
                else fp.bins)
            self.counts = np.zeros(0, dtype=np.float64)
        else:
            self.histogram = None
            self.counts = np.zeros(fp.bins, dtype=np.float64)

    def observe_column(self, col) -> None:
        self.rows += col.n_rows
        if self.fp.is_numeric:
            vals = np.asarray(col.data, dtype=np.float64)
            finite = vals[np.isfinite(vals)]
            self.nulls += int(col.n_rows - finite.size)
            if finite.size:
                self.histogram.update(finite)
        else:
            missing = col.is_missing()
            self.nulls += int(missing.sum())
            bins = self.fp.bins
            for v, miss in zip(col.data, missing):
                if miss:
                    continue
                if isinstance(v, (set, frozenset, list, tuple)):
                    for e in v:
                        self.counts[_stable_hash(str(e), bins)] += 1
                elif isinstance(v, dict):
                    for k in v:
                        self.counts[_stable_hash(str(k), bins)] += 1
                else:
                    self.counts[_stable_hash(str(v), bins)] += 1

    def js_vs_train(self) -> float:
        if self.fp.is_numeric:
            return numeric_histogram_js(self.fp.histogram, self.histogram,
                                        self.fp.bins)
        if self.counts.size != self.fp.counts.size:
            return 0.0
        a = FeatureDistribution(name=self.fp.name,
                                distribution=self.fp.counts)
        b = FeatureDistribution(name=self.fp.name,
                                distribution=self.counts)
        return a.js_divergence(b)

    @property
    def fill_rate(self) -> float:
        return 1.0 - self.nulls / self.rows if self.rows else 0.0

    # -- warm-restart serialization (serving/state.py) ---------------------
    def to_state(self) -> dict:
        return {
            "rows": self.rows, "nulls": self.nulls,
            "counts": self.counts.tolist(),
            "histogram": (self.histogram.to_json()
                          if self.histogram is not None else None),
        }

    def load_state(self, d: dict) -> None:
        self.rows = int(d.get("rows", 0))
        self.nulls = int(d.get("nulls", 0))
        counts = np.asarray(d.get("counts", []), dtype=np.float64)
        if counts.size == self.counts.size:
            self.counts = counts
        if d.get("histogram") and self.fp.is_numeric:
            self.histogram = StreamingHistogram.from_json(d["histogram"])


# ---------------------------------------------------------------------------
# fingerprint computation + persistence
# ---------------------------------------------------------------------------

def compute_fingerprints(raw_features: Sequence, ds: Dataset,
                         bins: int = 100) -> List[FeatureFingerprint]:
    """Training-time fingerprints for every raw predictor present in
    ``ds`` (the same distributions RawFeatureFilter computes, kept in
    their streaming form so serve-time comparison shares breakpoints)."""
    out: List[FeatureFingerprint] = []
    for f in raw_features:
        if f.is_response or f.name not in ds:
            continue
        col = ds[f.name]
        if col.kind == ColumnKind.VECTOR:
            continue
        numeric = issubclass(f.ftype, OPNumeric)
        fp = FeatureFingerprint(name=f.name, is_numeric=numeric,
                                count=col.n_rows, bins=bins)
        if numeric:
            vals = np.asarray(col.data, dtype=np.float64)
            finite = vals[np.isfinite(vals)]
            fp.nulls = int(col.n_rows - finite.size)
            fp.histogram = StreamingHistogram(bins).update(finite)
        else:
            missing = col.is_missing()
            fp.nulls = int(missing.sum())
            counts = np.zeros(bins, dtype=np.float64)
            for v, miss in zip(col.data, missing):
                if miss:
                    continue
                if isinstance(v, (set, frozenset, list, tuple)):
                    for e in v:
                        counts[_stable_hash(str(e), bins)] += 1
                elif isinstance(v, dict):
                    for k in v:
                        counts[_stable_hash(str(k), bins)] += 1
                else:
                    counts[_stable_hash(str(v), bins)] += 1
            fp.counts = counts
        out.append(fp)
    return out


def save_fingerprints(fingerprints: Sequence[FeatureFingerprint],
                      model_dir: str, trained_at: int = 0) -> str:
    """``trained_at`` is the model GENERATION the fingerprints belong
    to (0 = the original offline train; each lifecycle hot-swap bumps
    it) — a loaded sentinel carries it so operators can tell which
    model generation the drift numbers compare against."""
    from ..observability.store import atomic_write_json
    path = os.path.join(model_dir, DRIFT_FINGERPRINTS_FILE)
    atomic_write_json(
        path,
        {"formatVersion": FINGERPRINT_FORMAT_VERSION,
         "schema": FINGERPRINT_SCHEMA,
         "trainedAt": int(trained_at),
         "features": [fp.to_json() for fp in fingerprints]},
        indent=0, fsync=True)
    return path


def load_fingerprint_doc(model_dir: str
                         ) -> Optional[Tuple[List[FeatureFingerprint],
                                             dict]]:
    """(fingerprints, metadata) from a model dir, or None when the
    file does not exist. Metadata carries ``schema`` and ``trainedAt``.
    Raises :class:`FingerprintSchemaError` on a schema mismatch — a
    document with no ``schema`` field predates versioning and is read
    as the v1 schema."""
    path = os.path.join(model_dir, DRIFT_FINGERPRINTS_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        doc = json.load(fh)
    schema = doc.get("schema", FINGERPRINT_SCHEMA)
    if schema != FINGERPRINT_SCHEMA:
        raise FingerprintSchemaError(
            f"{path} was written under fingerprint schema {schema!r}; "
            f"this build reads {FINGERPRINT_SCHEMA!r} — refusing to "
            f"compare live traffic against incompatible fingerprints "
            f"(re-save the model to regenerate them)")
    if doc.get("formatVersion", 1) > FINGERPRINT_FORMAT_VERSION:
        raise FingerprintSchemaError(
            f"{path} uses fingerprint format "
            f"{doc['formatVersion']}; this build reads up to "
            f"{FINGERPRINT_FORMAT_VERSION}")
    fps = [FeatureFingerprint.from_json(d)
           for d in doc.get("features", [])]
    return fps, {"schema": schema,
                 "trainedAt": int(doc.get("trainedAt", 0))}


def load_fingerprints(model_dir: str
                      ) -> Optional[List[FeatureFingerprint]]:
    loaded = load_fingerprint_doc(model_dir)
    return None if loaded is None else loaded[0]


# ---------------------------------------------------------------------------
# the sentinel
# ---------------------------------------------------------------------------

class DriftSentinel:
    """Streaming train/serve drift monitor for one model.

    >>> sentinel = DriftSentinel.for_model(model)
    >>> sentinel.observe_dataset(raw_batch)      # per scored batch
    >>> sentinel.drift_report()["status"]        # "ok"|"warn"|"degrade"
    """

    def __init__(self, fingerprints: Sequence[FeatureFingerprint],
                 thresholds: Optional[DriftThresholds] = None):
        self.thresholds = thresholds or DriftThresholds()
        self.fingerprints = list(fingerprints)
        self._sketches = {fp.name: _Sketch(fp)
                          for fp in self.fingerprints}
        self.rows_seen = 0
        #: features already warned about (one telemetry event per
        #: feature per status escalation, not per batch)
        self._reported: Dict[str, str] = {}
        #: model generation the fingerprints were computed against
        #: (0 = offline train; lifecycle hot-swaps bump it)
        self.generation = 0

    # -- construction ------------------------------------------------------
    @classmethod
    def for_model(cls, model,
                  thresholds: Optional[DriftThresholds] = None,
                  bins: int = 100) -> Optional["DriftSentinel"]:
        """Sentinel from the best available training fingerprint
        source: the model dir's ``drift-fingerprints.json`` (saved
        models), the in-memory ``train_dataset`` (freshly trained), or
        the RawFeatureFilter's train distributions. None when no source
        exists (the caller serves unguarded, loudly)."""
        model_dir = getattr(model, "model_dir", None)
        if model_dir:
            loaded = None
            try:
                loaded = load_fingerprint_doc(model_dir)
            except FingerprintSchemaError:
                # an incompatible schema is a configuration error, not
                # a missing file — falling back to in-memory data would
                # hide it, so it propagates to the caller
                raise
            except (OSError, ValueError, KeyError):
                loaded = None
            if loaded and loaded[0]:
                sentinel = cls(loaded[0], thresholds)
                sentinel.generation = loaded[1].get("trainedAt", 0)
                return sentinel
        train_ds = getattr(model, "train_dataset", None)
        if train_ds is not None:
            return cls(compute_fingerprints(model.raw_features(),
                                            train_ds, bins=bins),
                       thresholds)
        rff = getattr(model, "raw_feature_filter_results", None)
        if rff is not None and rff.train_distributions:
            fps = []
            for d in rff.train_distributions:
                fps.append(FeatureFingerprint(
                    name=d.name, is_numeric=d.is_numeric,
                    count=d.count, nulls=d.nulls,
                    bins=max(d.distribution.size, 2),
                    counts=(np.zeros(0) if d.is_numeric
                            else d.distribution),
                    histogram=getattr(d, "_histogram", None)))
            return cls(fps, thresholds)
        return None

    # -- observation -------------------------------------------------------
    def observe_dataset(self, ds: Dataset) -> None:
        """Fold one scored batch's RAW feature columns into the
        serve-side sketches (admission-sanitized values, i.e. what the
        model actually scored)."""
        self.rows_seen += ds.n_rows
        for name, sketch in self._sketches.items():
            if name in ds:
                sketch.observe_column(ds[name])
        self._emit_escalations()

    def observe_records(self, records: Sequence[Dict[str, Any]]) -> None:
        """Record-dict convenience path (streaming_score)."""
        from ..features.columns import FeatureColumn
        from ..types import FeatureTypeError
        cols = {}
        for fp in self.fingerprints:
            vals = [r.get(fp.name) if isinstance(r, dict) else None
                    for r in records]
            try:
                cols[fp.name] = FeatureColumn.from_values(
                    _ftype_for(fp), vals)
            except (FeatureTypeError, TypeError, ValueError):
                # unconvertible raw values: this feature sits out the
                # batch — recorded, not silent
                _telemetry.count("sentinel_skipped_feature_batches")
                continue
        if cols:
            self.observe_dataset(Dataset(cols))

    def _emit_escalations(self) -> None:
        for name, sketch in self._sketches.items():
            js = sketch.js_vs_train()
            status = self.thresholds.status_for(js, sketch.rows)
            prev = self._reported.get(name, STATUS_OK)
            if _STATUS_ORDER[status] > _STATUS_ORDER[prev]:
                self._reported[name] = status
                _telemetry.count(f"drift_{status}")
                _telemetry.event("drift", feature=name,
                                 status=status, js=round(js, 4),
                                 rows=sketch.rows)

    # -- warm-restart serialization (serving/state.py) ---------------------
    def state_dict(self) -> dict:
        """Everything a restarted serving process needs to continue
        drift detection where this one left off: the serve-side
        sketches, the rows-seen counter, the per-feature escalation
        high-water marks, and the fingerprint generation. The training
        fingerprints themselves are NOT serialized — they reload from
        the model dir, so a snapshot never overrides them."""
        return {
            "rowsSeen": self.rows_seen,
            "generation": self.generation,
            "reported": dict(self._reported),
            "sketches": {name: sk.to_state()
                         for name, sk in self._sketches.items()},
        }

    def load_state(self, d: dict) -> None:
        """Restore serve-side sketches from :meth:`state_dict`.
        Features present in the snapshot but absent from the current
        fingerprints (the model changed between incarnations) are
        dropped silently — the fingerprints on disk are authoritative."""
        self.rows_seen = int(d.get("rowsSeen", 0))
        self.generation = int(d.get("generation", self.generation))
        self._reported = {str(k): str(v)
                          for k, v in (d.get("reported") or {}).items()}
        for name, state in (d.get("sketches") or {}).items():
            sketch = self._sketches.get(name)
            if sketch is not None:
                sketch.load_state(state)

    # -- reporting ---------------------------------------------------------
    def drift_report(self) -> dict:
        """Per-feature JS divergence vs training + overall status."""
        features = []
        worst = STATUS_OK
        for fp in self.fingerprints:
            sketch = self._sketches[fp.name]
            js = sketch.js_vs_train()
            status = self.thresholds.status_for(js, sketch.rows)
            if _STATUS_ORDER[status] > _STATUS_ORDER[worst]:
                worst = status
            features.append({
                "feature": fp.name,
                "isNumeric": fp.is_numeric,
                "jsDivergence": round(js, 6),
                "status": status,
                "rowsObserved": sketch.rows,
                "serveFillRate": round(sketch.fill_rate, 4),
                "trainFillRate": round(
                    1.0 - fp.nulls / fp.count if fp.count else 0.0, 4),
            })
        features.sort(key=lambda d: -d["jsDivergence"])
        return {
            "status": worst,
            "rowsSeen": self.rows_seen,
            "warnThreshold": self.thresholds.warn,
            "degradeThreshold": self.thresholds.degrade,
            "minRows": self.thresholds.min_rows,
            "features": features,
        }


def _ftype_for(fp: FeatureFingerprint):
    from ..types import Real, Text
    return Real if fp.is_numeric else Text
