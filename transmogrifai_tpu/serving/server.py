"""Async micro-batching serving loop: live requests -> bucketed
compiled dispatches under latency SLOs.

Every prior serving entry point scores a MATERIALIZED batch: the caller
already holds all the rows. "Millions of users" (ROADMAP north star)
means concurrent single-record requests arriving on their own clock —
and per-request dispatch wastes the compiled bucket programs the
:class:`~.plan.ScoringPlan` exists to amortize (a batch-of-1 pays the
same fixed dispatch cost as a batch-of-64), while unbounded coalescing
blows the tail latency. This module is the middle path, the
batching-vs-latency tradeoff the Gemma-on-TPU serving comparison in
PAPERS.md frames:

- **Deadline-or-full coalescing.** Requests queue per (model, tenant)
  lane; a lane dispatches when its queue reaches the coalescer's
  target bucket OR the oldest request has waited ``max_wait_ms`` —
  whichever comes first. The target bucket is picked from the plan's
  RECORDED per-bucket dispatch costs (:meth:`~.plan.ScoringPlan
  .bucket_profile`, the "A Learned Performance Model for TPUs"
  direction in PAPERS.md) rather than a static default.
- **Double buffering.** Host-side boxing/encoding of batch k+1
  (:meth:`~.plan.ScoringPlan.encode_raw_dataset`, the encode pool)
  overlaps batch k's in-flight device program
  (:meth:`~.plan.ScoringPlan.dispatch_encoded`, the device lane); a
  semaphore bounds the pipeline at one in-flight dispatch so the
  collector never runs unboundedly ahead.
- **Per-tenant guardrails.** Each tenant carries its own PR-5 stack:
  schema admission with machine-readable quarantine reasons, an output
  guard, a circuit breaker + per-batch deadline around device dispatch
  with the host columnar fallback, and a drift sentinel fed from the
  live stream. One tenant's breaker trip routes ITS batches to the
  fallback pool — another tenant's queue keeps dispatching to the
  device lane (isolation asserted in tests/test_serving_loop.py). A
  hung backend is ORPHANED at the deadline: the device executor is
  abandoned and replaced, so the event loop never wedges behind it.
- **Multi-model plan cache.** N fitted models stay resident under an
  LRU budget keyed by (model dir, bucket range); evictions are counted
  (``serve_plan_cache_evictions``) and an evicted model transparently
  recompiles on next use — one process serves a model zoo.

The whole hot path runs through the already-fused ScoringPlan bucket
programs, so steady state pays ZERO compiles (asserted); per-request
results are bitwise identical to offline ``score_guarded()`` on the
same rows (asserted). Entry points: ``python -m transmogrifai_tpu.cli
serve`` (JSON-lines over TCP, cli/serve.py) and the in-process
:class:`ServingClient` for tests/bench (``TX_BENCH_MODE=serve_loop``).
Blocking calls are banned from the async handlers by lint rule TX-J10
(docs/lint.md); everything blocking runs in a named executor.
"""
from __future__ import annotations

import asyncio
import collections
import concurrent.futures as _cf
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

import itertools

import numpy as np

from ..observability import trace as _trace
from ..observability.metrics import ServeMetrics
from ..runtime import telemetry as _telemetry
from .admission import AdmissionConfig, AdmissionController, ServeShed
from .guard import (AdmissionPolicy, BreakerOpenError, CircuitBreaker,
                    GuardReason, OutputGuard, SchemaGuard,
                    _invalidate_rows)
from .plan import EncodedScoreBatch, ScoringPlan

_log = logging.getLogger(__name__)

__all__ = ["ServeConfig", "ServingServer", "ServingClient", "PlanCache",
           "ServeRejected", "ServeDraining", "ServeShed",
           "AdmissionConfig", "AdmissionController", "serve_in_process"]

from ..tuning.registry import STATIC_DEFAULTS as _TUNABLES

#: coalescer target when no bucket profile has been recorded yet (the
#: number lives in tuning/registry.py — lint rule TX-T01)
_DEFAULT_TARGET = int(_TUNABLES["serving.target_batch"])

#: raw admitted records retained per model for the warm-restart
#: snapshot's prewarm manifest (serving/state.py) — enough to cycle
#: into any recorded bucket, small enough to serialize
_SAMPLE_RING = 8


class ServeRejected(RuntimeError):
    """A request was refused before scoring (queue over its
    backpressure limit, unknown model, or server shutdown)."""


class ServeDraining(ServeRejected):
    """The loop is draining toward a graceful shutdown: queued and
    in-flight requests will still be answered, but NEW requests are
    refused with a machine-readable ``"draining"`` answer so a
    reconnecting client (serving/client.py) retries against the next
    incarnation instead of counting a failure."""


@dataclass
class ServeConfig:
    """Knobs of the serving loop (docs/serving_loop.md)."""
    #: SLO half of deadline-or-full: a request waits at most this long
    #: in the coalescing queue before its lane dispatches
    max_wait_ms: float = 5.0
    #: coalescer target batch; None derives it per lane from the
    #: plan's recorded ``bucket_profile()`` (largest bucket whose warm
    #: per-dispatch cost fits inside max_wait_ms)
    target_batch: Optional[int] = None
    #: hard cap on rows per dispatch (<= the plan's max bucket)
    max_batch: int = 256
    #: per-lane backpressure: requests beyond this are rejected with
    #: ServeRejected instead of growing the queue without bound
    queue_limit: int = 4096
    #: LRU budget of the multi-model plan cache (resident plans)
    plan_budget: int = 4
    #: per-tenant PR-5 guardrails (admission/output/breaker/sentinel);
    #: False = raw dispatch (no quarantine, no breaker, no sentinel)
    guardrails: bool = True
    admission: Optional[AdmissionPolicy] = None
    #: drift sentinel per tenant (requires guardrails)
    sentinel: bool = True
    drift_thresholds: Any = None
    #: per-batch device dispatch deadline; a dispatch still running at
    #: the deadline is ORPHANED (executor abandoned + replaced) and the
    #: batch falls back to the host columnar path
    deadline_seconds: Optional[float] = None
    #: per-tenant breaker parameters (breaker_factory overrides, e.g.
    #: to inject a test clock)
    breaker_failures: int = 3
    breaker_cooldown_seconds: float = 30.0
    breaker_factory: Optional[Callable[[], CircuitBreaker]] = None
    #: self-healing lifecycle (serving/lifecycle.LifecycleConfig);
    #: None (the default) disables drift-triggered retraining entirely
    #: — the loop behaves byte-identically to a build without it
    lifecycle: Any = None
    #: overload admission control (serving/admission.AdmissionConfig);
    #: None (the default, and `tx serve --admission=off`) constructs
    #: no controller — the enqueue edge, dispatch semaphore and every
    #: answer are byte-identical to a build without docs/admission.md
    admission_control: Optional[AdmissionConfig] = None
    #: coalescer split policy (docs/ragged_batching.md):
    #: "deadline_or_full" (the classic rule) or "predicted_cost"
    #: (split a popped batch at a lattice rung when the cost model
    #: predicts the smaller dispatch is cheaper per row); None defers
    #: to the tuning policy, which only upgrades off the default when
    #: a tuned lattice AND recorded score costs exist
    coalesce_policy: Optional[str] = None


@dataclass
class _Request:
    record: dict
    future: asyncio.Future
    arrived: float
    #: request id, generated at admission (or supplied by the TCP
    #: client) and propagated enqueue -> coalesce -> encode -> dispatch
    #: -> reply; the trace id of this request's span tree
    rid: str = ""


@dataclass
class _CacheEntry:
    model: Any
    plan: ScoringPlan
    result_names: List[str]
    guards: Dict[str, "_TenantGuards"] = field(default_factory=dict)


class _TenantGuards:
    """One tenant's PR-5 stack over a shared compiled plan. The plan
    itself stays UNGUARDED (``plan.guard is None``) — guard state that
    used to live on the plan (breaker, sentinel sketches) lives here,
    per tenant, so tenants fail and recover independently."""

    def __init__(self, model, config: ServeConfig):
        self.schema: Optional[SchemaGuard] = None
        self.output: Optional[OutputGuard] = None
        self.breaker: Optional[CircuitBreaker] = None
        self.sentinel = None
        if not config.guardrails:
            return
        self.schema = SchemaGuard(model, policy=config.admission)
        self.output = OutputGuard()
        self.breaker = (config.breaker_factory()
                        if config.breaker_factory is not None else
                        CircuitBreaker(
                            failure_threshold=config.breaker_failures,
                            cooldown_seconds=(
                                config.breaker_cooldown_seconds)))
        if config.sentinel:
            from .sentinel import DriftSentinel
            self.sentinel = DriftSentinel.for_model(
                model, thresholds=config.drift_thresholds)


#: marker pinned when a tenant swap had no previous override (rollback
#: must REMOVE the override, not restore a None entry)
_NO_OVERRIDE = object()


class PlanCache:
    """LRU of compiled ScoringPlans keyed by (model dir, bucket range)
    — the compile-cache budget that turns one process into a model-zoo
    server. Eviction drops the plan (and its jitted programs) but
    keeps the loader, so an evicted model transparently reloads +
    recompiles on next use; hits/misses/evictions are counted.

    Hot-swaps go through :meth:`swap_entry`/:meth:`rollback` ONLY (lint
    rule TX-R03 bans in-place mutation of a live entry): the replace is
    one dict assignment, atomic between batches — a prepare that
    already captured the old entry finishes on it, the next prepare
    resolves the new one, and the previous entry stays PINNED for one
    generation so a post-swap fault rolls back instantly."""

    def __init__(self, budget: int = 4):
        if budget < 1:
            raise ValueError("plan cache budget must be >= 1")
        self.budget = int(budget)
        #: name -> loader (model dir string, or an in-memory model)
        self._loaders: Dict[str, Any] = {}
        self._entries: "collections.OrderedDict[Tuple, _CacheEntry]" = \
            collections.OrderedDict()
        #: (name, tenant) -> swapped-in entry (tenant-scoped hot-swaps;
        #: resolution order: override, then the shared LRU entry)
        self._overrides: Dict[Tuple[str, str], _CacheEntry] = {}
        #: previous entry pinned per swap scope until commit/rollback
        self._pinned: Dict[Tuple[str, Optional[str]], Any] = {}
        self.evictions = 0
        self.hits = 0
        self.misses = 0

    def register(self, name: str, model_or_dir: Any) -> None:
        self._loaders[name] = model_or_dir

    def names(self) -> List[str]:
        return sorted(self._loaders)

    @staticmethod
    def _key(name: str, buckets: Tuple[int, int],
             lattice: Optional[Tuple[int, ...]]) -> Tuple:
        """Cache key. With ``lattice=None`` the key is EXACTLY the
        pre-lattice ``(name, buckets)`` shape, so cold starts, warm
        restarts (serving/state.py) and every existing snapshot keep
        resolving the same entries bitwise."""
        if lattice is None:
            return (name, buckets)
        return (name, buckets, tuple(int(b) for b in lattice))

    def get(self, name: str,
            buckets: Tuple[int, int] = (None, None),
            lattice: Optional[Tuple[int, ...]] = None) -> _CacheEntry:
        """Resident entry for ``name`` (LRU-bumped), loading the model
        and compiling its plan on a miss. Blocking — call from an
        executor, never from the event loop."""
        if name not in self._loaders:
            raise ServeRejected(f"unknown model {name!r}; registered: "
                                f"{self.names()}")
        key = self._key(name, buckets, lattice)
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
            self.hits += 1
            _telemetry.count("serve_plan_cache_hits")
            return entry
        self.misses += 1
        _telemetry.count("serve_plan_cache_misses")
        loader = self._loaders[name]
        if isinstance(loader, str):
            from ..workflow.workflow import WorkflowModel
            model = WorkflowModel.load(loader)
        else:
            model = loader
        kwargs = {}
        if buckets[0] is not None:
            kwargs["min_bucket"] = buckets[0]
        if buckets[1] is not None:
            kwargs["max_bucket"] = buckets[1]
        if lattice is not None:
            kwargs["lattice"] = lattice
        # artifact-first compile (artifacts/loader.py, TX-R06): a
        # saved model's AOT executables deserialize instead of
        # compiling — a cache MISS (boot or eviction reload) costs a
        # file read, not an XLA compile; loud counted fallback
        # otherwise
        from ..artifacts.loader import load_or_compile
        plan = load_or_compile(
            model, model_dir=loader if isinstance(loader, str) else None,
            **kwargs)
        entry = _CacheEntry(
            model=model, plan=plan,
            result_names=[f.name for f in model.result_features])
        self._entries[key] = entry
        while len(self._entries) > self.budget:
            old_key, _old = self._entries.popitem(last=False)
            self.evictions += 1
            _telemetry.count("serve_plan_cache_evictions")
            _telemetry.event("serve_plan_evicted", model=old_key[0])
        return entry

    # -- hot-swap (the ONLY sanctioned live replacement, TX-R03) -----------
    def entry_for(self, name: str, tenant: str,
                  buckets: Tuple[int, int] = (None, None),
                  lattice: Optional[Tuple[int, ...]] = None
                  ) -> _CacheEntry:
        """Tenant-aware resolution: a tenant-scoped swapped-in entry
        wins; every other tenant resolves the shared LRU entry —
        untouched by a 'tenant'-policy swap, hence bitwise
        unaffected."""
        override = self._overrides.get((name, tenant))
        if override is not None:
            self.hits += 1
            _telemetry.count("serve_plan_cache_hits")
            return override
        return self.get(name, buckets, lattice)

    def swap_entry(self, name: str, new_entry: _CacheEntry,
                   tenant: Optional[str] = None,
                   buckets: Tuple[int, int] = (None, None),
                   lattice: Optional[Tuple[int, ...]] = None) -> None:
        """Atomically replace the live entry for ``name`` (one dict
        assignment — batches already holding the old entry finish on
        it; the next ``entry_for`` resolves ``new_entry``). The
        previous entry is pinned until :meth:`commit` or
        :meth:`rollback`. ``tenant=None`` swaps the shared entry for
        every tenant; a tenant name swaps only that tenant's
        resolution."""
        if name not in self._loaders:
            raise ServeRejected(f"unknown model {name!r}; registered: "
                                f"{self.names()}")
        if tenant is not None:
            self._pinned[(name, tenant)] = self._overrides.get(
                (name, tenant), _NO_OVERRIDE)
            self._overrides[(name, tenant)] = new_entry
        else:
            key = self._key(name, buckets, lattice)
            self._pinned[(name, None)] = self._entries.get(key)
            self._entries[key] = new_entry
        _telemetry.count("serve_plan_swaps")
        _telemetry.event("serve_plan_swapped", model=name,
                         tenant=tenant or "*")

    def rollback(self, name: str, tenant: Optional[str] = None,
                 buckets: Tuple[int, int] = (None, None),
                 lattice: Optional[Tuple[int, ...]] = None) -> bool:
        """Instantly restore the entry pinned by the last
        :meth:`swap_entry` for this scope. Returns False when nothing
        is pinned (already committed or never swapped)."""
        pin = (name, tenant)
        if pin not in self._pinned:
            return False
        prev = self._pinned.pop(pin)
        key = self._key(name, buckets, lattice)
        if tenant is not None:
            if prev is _NO_OVERRIDE:
                self._overrides.pop((name, tenant), None)
            else:
                self._overrides[(name, tenant)] = prev
        elif prev is not None:
            self._entries[key] = prev
        else:
            self._entries.pop(key, None)
        return True

    def commit(self, name: str, tenant: Optional[str] = None) -> None:
        """Unpin the previous entry after a healthy post-swap watch
        window — the swap becomes permanent and the old plan (and its
        compiled programs) may be released."""
        self._pinned.pop((name, tenant), None)

    def swapped_entries(self) -> Dict[Tuple[str, str], _CacheEntry]:
        """Live tenant-scoped overrides (metrics/introspection)."""
        return dict(self._overrides)

    def resident_entries(self) -> List[Tuple[Tuple, _CacheEntry]]:
        """Resident (key, entry) pairs, LRU first (introspection +
        the warm-restart snapshot, serving/state.py)."""
        return list(self._entries.items())

    def touch(self, name: str,
              buckets: Tuple[int, int] = (None, None),
              lattice: Optional[Tuple[int, ...]] = None) -> bool:
        """LRU-bump a resident entry without resolving it (no
        hit/miss accounting) — how a warm restart replays the
        snapshot's recorded LRU order (serving/state.py)."""
        key = self._key(name, buckets, lattice)
        if key not in self._entries:
            return False
        self._entries.move_to_end(key)
        return True

    def lru_order(self) -> List[Tuple[str, Tuple[int, int]]]:
        """Resident entry keys, least-recently-used first."""
        return list(self._entries.keys())


class _Lane:
    """One (model, tenant) coalescing queue + its collector task."""

    def __init__(self, model_name: str, tenant: str,
                 queue_limit: int = 4096):
        self.model_name = model_name
        self.tenant = tenant
        #: bounded at the backpressure limit (TX-R05): the enqueue edge
        #: rejects BEFORE append, so the maxlen never silently drops —
        #: it is the structural backstop, not the admission policy
        self.queue: "collections.deque[_Request]" = collections.deque(
            maxlen=max(int(queue_limit), 1))
        self.wakeup: Optional[asyncio.Event] = None   # built on the loop
        self.full: Optional[asyncio.Event] = None
        #: the collector's current deadline-or-full threshold; the
        #: enqueue edge signals ``full`` when the queue reaches it so
        #: the collector wakes ONCE per batch, not once per request
        self.target: int = _DEFAULT_TARGET
        self.task: Optional[asyncio.Task] = None


@dataclass
class _PreparedBatch:
    """Everything the dispatch stage needs, produced host-side in the
    encode pool (the double-buffered half)."""
    entry: _CacheEntry
    guards: _TenantGuards
    requests: List[_Request]
    enc: EncodedScoreBatch
    ds: Any
    quarantined: List[GuardReason]
    qmask: np.ndarray
    #: (model, tenant) lane + batch sequence number — span attributes
    model: str = ""
    tenant: str = ""
    seq: int = 0
    #: monotonic marks of the batch's pipeline stages
    #: (encode_t0/encode_t1/guard_t0/guard_t1, fallback flag); the
    #: request spans are reconstructed from these at resolve time
    marks: Dict[str, float] = field(default_factory=dict)
    #: set when the per-batch deadline orphaned this batch's dispatch:
    #: the batch was already answered through the host fallback, so a
    #: hung device thread that eventually wakes must NOT run the
    #: finish stage (it would double-count telemetry and re-observe
    #: rows on the sentinel, long after the batch resolved)
    abandoned: bool = False


class ServingServer:
    """The asyncio micro-batching scorer. Typical in-process use::

        server = ServingServer(ServeConfig(max_wait_ms=2.0))
        server.add_model("titanic", model)       # or a saved model dir
        client = server.start_background()
        row = client.score({"age": 31.0, ...}, model="titanic")
        server.stop()

    ``python -m transmogrifai_tpu.cli serve`` wraps the same object in
    a JSON-lines TCP front end (cli/serve.py)."""

    def __init__(self, config: Optional[ServeConfig] = None):
        self.config = config or ServeConfig()
        self.plans = PlanCache(budget=self.config.plan_budget)
        self._lanes: Dict[Tuple[str, str], _Lane] = {}
        self._default_model: Optional[str] = None
        self._running = False
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._encode_pool = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tx-serve-encode")
        self._device_pool = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tx-serve-device")
        self._fallback_pool = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tx-serve-fallback")
        self._dispatch_sem: Optional[asyncio.Semaphore] = None
        #: live metrics (per-tenant latency histograms, answered/failed
        #: counts) — served by the {"metrics": true} control request
        #: and `tx serve --metrics-port` (docs/observability.md)
        self.metrics = ServeMetrics()
        self._batch_seq = itertools.count(1)
        #: float accumulators (occupancy/saturation; bench reads these)
        self.stats: Dict[str, float] = {
            "requests": 0, "batches": 0, "rows": 0,
            "full_dispatches": 0, "deadline_dispatches": 0,
            "dispatch_seconds": 0.0, "orphaned_dispatches": 0,
        }
        self._first_dispatch_at: Optional[float] = None
        self._last_dispatch_at: Optional[float] = None
        #: graceful-drain + warm-restart process state
        #: (docs/serving_restart.md)
        self._draining = False
        self._inflight = 0
        self._drain_event: Optional[asyncio.Event] = None
        #: readiness gate: False while a --resume-state boot is still
        #: restoring/prewarming; the TCP front end answers the
        #: {"ready": true} control request from this flag
        self.ready = True
        #: which restart of this serving identity we are (the
        #: --supervise parent bumps TX_SERVE_GENERATION per incarnation)
        self.restart_generation = int(
            os.environ.get("TX_SERVE_GENERATION", "0") or 0)
        #: wall-clock time of the last successful state snapshot, and
        #: the manager that writes them (attached by cli/serve.py when
        #: --state-dir/--resume-state is on; None = feature off)
        self.last_snapshot_at: Optional[float] = None
        self.state_manager = None
        #: per-model ring of recently admitted raw records — the
        #: snapshot's prewarm rows (serving/state.py)
        self._sample_records: Dict[str, "collections.deque"] = {}
        #: self-healing lifecycle manager — None unless
        #: ``config.lifecycle`` is an enabled LifecycleConfig
        self.lifecycle = None
        lc = self.config.lifecycle
        if lc is not None and getattr(lc, "enabled", False):
            from .lifecycle import ModelLifecycle
            self.lifecycle = ModelLifecycle(self, lc)
        #: telemetry-driven autotuning (docs/autotuning.md): one store
        #: snapshot's decisions for this server's lifetime. With an
        #: empty store or TX_TUNE=off every decision IS the static
        #: default, so behavior below is bitwise the untuned loop.
        from ..tuning.policy import TuningPolicy
        self.tuning = TuningPolicy()
        self._target_decision = self.tuning.target_batch(
            self.config.max_wait_ms, self.config.max_batch)
        lo_d, hi_d = self.tuning.bucket_range(self.config.max_batch)
        #: ScoringPlan bucket range for every plan this server
        #: compiles; (None, None) = plan defaults (and the SAME cache
        #: key as before, keeping cold-start bitwise)
        self.plan_buckets: Tuple[Optional[int], Optional[int]] = (
            (lo_d.chosen, hi_d.chosen)
            if (lo_d.tuned() or hi_d.tuned()) else (None, None))
        self._bucket_decisions = (lo_d, hi_d)
        #: padding-aware ragged batching (docs/ragged_batching.md):
        #: the tuning policy's per-plan bucket LATTICE, chosen from the
        #: recorded occupancy histogram × predicted per-bucket cost.
        #: Untuned (cold store / TX_TUNE=off / no improvement found)
        #: => None, and every plan + cache key stays bitwise the
        #: power-of-two build.
        self._lattice_decision = self.tuning.bucket_lattice(
            min_bucket=self.plan_buckets[0],
            max_bucket=self.plan_buckets[1])
        self.plan_lattice: Optional[Tuple[int, ...]] = (
            tuple(int(b) for b in self._lattice_decision.chosen)
            if self._lattice_decision.tuned() else None)
        #: coalescer split policy: caller (ServeConfig) wins, then an
        #: override pin, then the model (which only proposes
        #: "predicted_cost" when the lattice itself tuned)
        self._coalesce_decision = self.tuning.coalesce_policy(
            caller=self.config.coalesce_policy,
            lattice_tuned=self._lattice_decision.tuned())
        self.coalesce_policy = str(self._coalesce_decision.chosen)
        #: split dispatches taken by the predicted-cost coalescer
        self.stats.setdefault("split_dispatches", 0)
        #: overload admission (docs/admission.md) — None when
        #: ``config.admission_control`` is None: every path below
        #: byte-identical to a build without the controller
        self._admission: Optional[AdmissionController] = None
        if self.config.admission_control is not None:
            self._admission = AdmissionController(
                self.config.admission_control, tuning=self.tuning,
                max_batch=self.config.max_batch,
                max_wait_ms=self.config.max_wait_ms)

    # -- registry ----------------------------------------------------------
    def add_model(self, name: str, model_or_dir: Any,
                  default: bool = False) -> "ServingServer":
        """Register a fitted model (in-memory ``WorkflowModel`` or a
        saved model directory). The first registered model is the
        default for requests that name none."""
        self.plans.register(name, model_or_dir)
        if default or self._default_model is None:
            self._default_model = name
        return self

    def register_refit(self, name: str, workflow_factory=None,
                       base_records: Optional[List[dict]] = None,
                       checkpoint_dir: Optional[str] = None,
                       save_dir: Optional[str] = None) -> "ServingServer":
        """In-process half of ``tx serve --auto-retrain``: how to
        retrain ``name`` when its sentinel degrades.
        ``workflow_factory`` returns a fresh unfitted workflow (exact
        estimators/hyperparameters); without one the workflow is
        reconstructed generically from the fitted model
        (runtime/refit.py). Requires ``ServeConfig.lifecycle``."""
        if self.lifecycle is None:
            raise ValueError(
                "register_refit requires an enabled "
                "ServeConfig.lifecycle (serving/lifecycle."
                "LifecycleConfig)")
        from ..runtime.refit import RefitSpec
        self.lifecycle.register(name, RefitSpec(
            workflow_factory=workflow_factory,
            base_records=base_records, checkpoint_dir=checkpoint_dir,
            save_dir=save_dir))
        return self

    def prewarm(self, names: Optional[List[str]] = None,
                samples: Optional[Dict[str, List[dict]]] = None
                ) -> Dict[str, List[int]]:
        """Pre-compile the tuning policy's pre-warm bucket set for
        each registered model BEFORE traffic (the serving/state.py
        warm-restart idiom: score a cycled placeholder batch per
        bucket), so an unprofiled plan's first requests never pay the
        per-bucket compile bill in-band. With a cold store or
        TX_TUNE=off the decision is the empty set and this is a no-op.
        ``samples`` supplies representative raw records per model;
        without it the admitted-traffic ring (populated by a state
        restore) is used, then an empty placeholder record — models
        whose raw extractors index keys strictly need real samples.
        Blocking — call before the port binds (cli/serve.py does)."""
        decision = self.tuning.prewarm_buckets(self.config.max_batch)
        buckets = sorted(int(b) for b in (decision.chosen or ()))
        warmed: Dict[str, List[int]] = {}
        if not buckets:
            return warmed
        for name in (names if names is not None
                     else self.plans.names()):
            try:
                entry = self.plans.get(name, self.plan_buckets,
                                       self.plan_lattice)
            except Exception as e:  # pragma: no cover - bad loader
                from ..runtime.errors import classify_error
                _telemetry.event("serve_prewarm_failed", model=name,
                                 kind=classify_error(e),
                                 error=f"{type(e).__name__}: {e}")
                continue
            given = (samples or {}).get(name)
            ring = self._sample_records.get(name)
            samples_for = given or (list(ring) if ring else [{}])
            done: List[int] = []
            for bucket in buckets:
                if bucket < entry.plan.min_bucket \
                        or bucket > entry.plan.max_bucket:
                    continue
                try:
                    entry.plan.score(list(itertools.islice(
                        itertools.cycle(samples_for), bucket)))
                    done.append(bucket)
                except Exception as e:
                    from ..runtime.errors import classify_error
                    _telemetry.event("serve_prewarm_failed",
                                     model=name, bucket=bucket,
                                     kind=classify_error(e),
                                     error=f"{type(e).__name__}: {e}")
            warmed[name] = done
            _telemetry.event("serve_prewarmed", model=name,
                             buckets=done)
        return warmed

    # -- async request edge ------------------------------------------------
    async def score_async(self, record: dict, model: Optional[str] = None,
                          tenant: str = "default") -> dict:
        """Enqueue one record; resolves with the scored row dict (the
        ``ScoreFunction`` row contract — result features by name, plus
        a ``"_guard"`` reason list for quarantined/invalidated rows)."""
        _rid, row = await self.score_with_id(record, model=model,
                                             tenant=tenant)
        return row

    async def score_with_id(self, record: dict,
                            model: Optional[str] = None,
                            tenant: str = "default",
                            rid: Optional[str] = None
                            ) -> Tuple[str, dict]:
        """:meth:`score_async` plus the request id: generated here at
        ADMISSION (or supplied by the caller, e.g. the TCP protocol's
        ``"id"`` field) and carried through coalesce -> encode ->
        dispatch -> reply, so one request's wait/batch/device time is
        attributable end to end. The TCP front end echoes it in every
        response line (cli/serve.py)."""
        if self._draining:
            _telemetry.count("serve_draining_rejections")
            raise ServeDraining(
                "serving loop is draining for shutdown; retry against "
                "the next incarnation")
        if not self._running:
            raise ServeRejected("serving loop is not running")
        name = model or self._default_model
        if name is None:
            raise ServeRejected("no model registered")
        lane = self._lane(name, tenant)
        if len(lane.queue) >= self.config.queue_limit:
            _telemetry.count("serve_queue_rejections")
            raise ServeRejected(
                f"lane {name}/{tenant} queue is at its backpressure "
                f"limit ({self.config.queue_limit})")
        if self._admission is not None:
            # the overload gatekeeper (docs/admission.md): raises
            # ServeShed with a retry_after_ms hint, or admits
            backlog: Dict[str, int] = {}
            for (_m, t), ln in self._lanes.items():
                backlog[t] = backlog.get(t, 0) + len(ln.queue)
            self._admission.admit(name, tenant, len(lane.queue),
                                  backlog)
        loop = asyncio.get_running_loop()
        req = _Request(record=record, future=loop.create_future(),
                       arrived=time.monotonic(),
                       rid=rid or _trace.new_request_id())
        lane.queue.append(req)
        self.stats["requests"] += 1
        _telemetry.count("serve_requests")
        if len(lane.queue) == 1:
            lane.wakeup.set()               # lane was idle: start timer
        if len(lane.queue) >= lane.target:
            lane.full.set()                 # bucket filled: fire early
        self._inflight += 1
        try:
            return req.rid, await req.future
        finally:
            self._inflight -= 1
            if self._inflight == 0 and self._drain_event is not None:
                self._drain_event.set()

    def _lane(self, model_name: str, tenant: str) -> _Lane:
        key = (model_name, tenant)
        lane = self._lanes.get(key)
        if lane is None:
            lane = self._lanes[key] = _Lane(
                model_name, tenant,
                queue_limit=self.config.queue_limit)
            lane.wakeup = asyncio.Event()
            lane.full = asyncio.Event()
            lane.task = asyncio.get_running_loop().create_task(
                self._lane_loop(lane),
                name=f"tx-serve-lane-{model_name}-{tenant}")
        return lane

    # -- the coalescing collector ------------------------------------------
    def _target_batch(self, plan: ScoringPlan) -> int:
        """Deadline-or-full's "full": the coalescer's target batch.
        Explicit config wins; otherwise the largest bucket whose
        RECORDED warm per-dispatch cost still fits inside the wait
        budget (``bucket_profile()``), so the threshold comes from
        this process's measured dispatch costs, not a constant."""
        cfg = self.config
        if cfg.target_batch:
            return max(1, min(cfg.target_batch, cfg.max_batch))
        budget_s = cfg.max_wait_ms / 1000.0
        best = 0
        for bucket, rec in plan.bucket_profile().items():
            if rec["calls"] < 1 or bucket > cfg.max_batch:
                continue
            per_dispatch = rec["execute_seconds"] / rec["calls"]
            if per_dispatch <= budget_s and bucket > best:
                best = bucket
        if best:
            return best
        # no local profile yet: the tuning policy's cross-run
        # prediction (tuning/policy.py) replaces the static constant;
        # cold store / TX_TUNE=off resolves to exactly _DEFAULT_TARGET
        return max(1, min(int(self._target_decision.chosen),
                          cfg.max_batch))

    async def _collect(self, lane: _Lane, target: int
                       ) -> List[_Request]:
        """Deadline-or-full: wait for the first request, then ONE
        timer until the lane holds ``target`` requests (the enqueue
        edge fires ``lane.full``) or the OLDEST request has waited
        ``max_wait_ms`` — whichever comes first."""
        lane.target = max(1, target)
        while not lane.queue:
            lane.wakeup.clear()
            await lane.wakeup.wait()
            if not self._running:
                return []
        wait_ms = self.config.max_wait_ms
        if self._admission is not None:
            # browned out, the coalescer dispatches smaller batches
            # sooner — occupancy traded for latency headroom
            wait_ms = self._admission.effective_max_wait_ms(wait_ms)
        deadline = lane.queue[0].arrived + wait_ms / 1000.0
        while len(lane.queue) < lane.target:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            lane.full.clear()
            try:
                await asyncio.wait_for(lane.full.wait(), remaining)
            except asyncio.TimeoutError:
                break
        n = min(len(lane.queue), self.config.max_batch)
        if self.coalesce_policy == "predicted_cost":
            k = self._coalesce_pop_count(n)
            if k < n:
                # split: the leftover stays queued (its deadline is
                # its own arrival time, so no request waits longer
                # than max_wait_ms) and this dispatch pads less
                self.stats["split_dispatches"] += 1
                _telemetry.count("serve_split_dispatches")
                n = k
        batch = [lane.queue.popleft() for _ in range(n)]
        key = ("full_dispatches" if n >= lane.target
               else "deadline_dispatches")
        self.stats[key] += 1
        _telemetry.count(f"serve_{key}")
        return batch

    def _coalesce_pop_count(self, n: int) -> int:
        """Predicted-cost split rule (docs/ragged_batching.md): pop
        ``k <= n`` where ``k`` is the largest lattice rung <= n IF the
        cost model predicts the rung's per-row execute cost beats
        dispatching all ``n`` rows at their (larger, padded) rung.
        Unknown costs or no lattice => ``n`` (the classic rule)."""
        if n < 2 or not self.plan_lattice:
            return n
        rungs = [b for b in self.plan_lattice
                 if b <= min(n, self.config.max_batch)]
        if not rungs:
            return n
        k = rungs[-1]
        if k >= n:
            return n
        model = getattr(self.tuning, "model", None)
        if model is None:
            return n
        up = next((b for b in self.plan_lattice if b >= n), None)
        if up is None:
            return n
        full = model.predict("score", bucket=int(up))
        part = model.predict("score", bucket=int(k))
        if full.execute is None or part.execute is None:
            return n
        # per-real-row cost of dispatching n rows padded to `up` vs
        # k rows exactly at rung `k` (leftover pays its own dispatch
        # later — charge it the same rate as the k-row dispatch)
        if part.execute / k < full.execute / n:
            return k
        return n

    async def _lane_loop(self, lane: _Lane) -> None:
        """One lane's collector: coalesce -> host-encode (encode pool)
        -> bounded-spawn the dispatch stage. The semaphore is acquired
        HERE and released when the dispatch completes, so exactly one
        batch is on the device while this loop coalesces + encodes the
        next one — the double buffer."""
        from ..runtime.errors import classify_error
        loop = asyncio.get_running_loop()
        # first-collect target before any plan profile exists: the
        # tuning decision (== _DEFAULT_TARGET on a cold store)
        target = max(1, int(self._target_decision.chosen))
        while self._running:
            batch: List[_Request] = []
            try:
                batch = await self._collect(lane, target)
                if not batch:
                    continue
                prep = await loop.run_in_executor(
                    self._encode_pool, self._prepare_batch, lane, batch)
                target = self._target_batch(prep.entry.plan)
                if self._admission is not None:
                    # the DRR fair-queuing twin of the semaphore:
                    # contended grants are served by weighted deficit
                    # round-robin across tenants (docs/admission.md)
                    await self._admission.acquire_grant(
                        lane.tenant, len(prep.requests))
                else:
                    await self._dispatch_sem.acquire()
                loop.create_task(self._dispatch_resolve(prep))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # a failed prepare fails THIS batch's requests with the
                # recorded, classified reason and the lane keeps
                # serving (the TX-R01/TX-R02 contract: never silent)
                _telemetry.count("serve_batch_failures")
                _telemetry.event("serve_batch_failed", lane=lane.tenant,
                                 model=lane.model_name,
                                 kind=classify_error(e),
                                 error=f"{type(e).__name__}: {e}")
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)

    # -- host-side batch prep (encode pool thread) -------------------------
    def _prepare_batch(self, lane: _Lane, batch: List[_Request]
                       ) -> _PreparedBatch:
        """Blocking host work: plan-cache lookup (may reload/recompile
        an evicted model), schema admission with per-row quarantine
        reasons, raw-Dataset boxing, and bucket encode/padding."""
        marks = {"encode_t0": time.monotonic()}
        entry = self.plans.entry_for(lane.model_name, lane.tenant,
                                     buckets=self.plan_buckets,
                                     lattice=self.plan_lattice)
        guards = entry.guards.get(lane.tenant)
        if guards is None:
            guards = entry.guards[lane.tenant] = _TenantGuards(
                entry.model, self.config)
        records = [r.record for r in batch]
        n = len(records)
        if guards.schema is not None:
            ds, quarantined = guards.schema.admit_records(records)
        else:
            from ..workflow.workflow import _generate_raw_data
            ds = _generate_raw_data(entry.model.raw_features(), records,
                                    require_responses=False)
            quarantined = []
        qmask = np.zeros(n, dtype=bool)
        for r in quarantined:
            if 0 <= r.row < n:
                qmask[r.row] = True
        enc = entry.plan.encode_raw_dataset(
            ds, valid_mask=(~qmask).astype(np.float64))
        ring = self._sample_records.get(lane.model_name)
        if ring is None:
            ring = self._sample_records[lane.model_name] = \
                collections.deque(maxlen=_SAMPLE_RING)
        ring.extend(r for i, r in enumerate(records) if not qmask[i])
        marks["encode_t1"] = time.monotonic()
        return _PreparedBatch(entry=entry, guards=guards, requests=batch,
                              enc=enc, ds=ds, quarantined=quarantined,
                              qmask=qmask, model=lane.model_name,
                              tenant=lane.tenant,
                              seq=next(self._batch_seq), marks=marks)

    # -- device dispatch + guarded resolution ------------------------------
    async def _dispatch_resolve(self, prep: _PreparedBatch) -> None:
        try:
            rows = await self._dispatch_guarded(prep)
            now = time.monotonic()
            for req, row in zip(prep.requests, rows):
                if not req.future.done():
                    req.future.set_result(row)
            self.metrics.observe_batch(
                prep.tenant,
                [now - req.arrived for req in prep.requests])
            if _trace.enabled():
                self._emit_request_spans(prep, now)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # classified-bug dispatches (and finish-stage crashes)
            # fail the batch's requests with the recorded reason
            from ..runtime.errors import classify_error
            _telemetry.count("serve_batch_failures")
            self.metrics.note_failure()
            _telemetry.event("serve_batch_failed",
                             kind=classify_error(e),
                             error=f"{type(e).__name__}: {e}")
            if _trace.enabled():
                self._emit_request_spans(prep, time.monotonic(),
                                         error=f"{type(e).__name__}: "
                                               f"{e}")
            for req in prep.requests:
                if not req.future.done():
                    req.future.set_exception(e)
        finally:
            if self._admission is not None:
                self._admission.release_grant()
            else:
                self._dispatch_sem.release()

    def _emit_request_spans(self, prep: _PreparedBatch, resolved: float,
                            error: Optional[str] = None) -> None:
        """Reconstruct each request's span tree from the batch's
        monotonic marks at resolve time: root ``serve.request`` (trace
        id = request id) with CONTIGUOUS children wait / encode /
        dispatch / guard, so >= 95% of the request wall-clock is
        covered by child spans (the acceptance gate tests assert).
        Retrospective emission keeps the hot path free of context
        managers across async hops — the cost is a handful of dict
        appends per request, paid only when tracing is on."""
        m = prep.marks
        enc0 = m.get("encode_t0")
        enc1 = m.get("encode_t1", enc0)
        guard0 = m.get("guard_t0", resolved)
        attrs = {"model": prep.model, "tenant": prep.tenant,
                 "batch": prep.seq, "batch_rows": len(prep.requests)}
        if m.get("fallback"):
            attrs["host_fallback"] = True
        if error is not None:
            attrs["status"], attrs["error"] = "error", error
        for req in prep.requests:
            root = _trace.add_span("serve.request", req.arrived,
                                   resolved, trace_id=req.rid,
                                   attrs=attrs)
            parent = (req.rid, root)
            if enc0 is None:
                continue
            _trace.add_span("serve.wait", req.arrived, enc0,
                            parent=parent)
            _trace.add_span("serve.encode", enc0, enc1, parent=parent)
            _trace.add_span("serve.dispatch", enc1, guard0,
                            parent=parent,
                            attrs={"fallback": bool(m.get("fallback"))})
            # guard runs from finish-stage start to RESOLUTION: the
            # guard/boxing work plus the executor->loop handoff that
            # delivers the reply — the four children partition the
            # request's latency completely
            _trace.add_span("serve.guard", guard0, resolved,
                            parent=parent,
                            attrs={"boxing_seconds": round(
                                max(m.get("guard_t1", guard0) - guard0,
                                    0.0), 6)})

    async def _dispatch_guarded(self, prep: _PreparedBatch
                                ) -> List[dict]:
        """Breaker-gated device dispatch with the per-batch deadline
        and host columnar fallback — the per-tenant serving half of
        ``ScoringPlan.score_guarded`` over a shared unguarded plan.
        Dispatch + post-dispatch bookkeeping run in ONE executor hop
        (``_device_batch``): every loop round-trip costs real tail
        latency on a contended host."""
        loop = asyncio.get_running_loop()
        breaker = prep.guards.breaker
        t0 = time.monotonic()
        try:
            if breaker is not None:
                breaker.before_dispatch()
            fut = self._device_pool.submit(self._device_batch, prep)
            aw = asyncio.wrap_future(fut)
            deadline = self.config.deadline_seconds
            if deadline is not None:
                try:
                    rows = await asyncio.wait_for(aw, deadline)
                except asyncio.TimeoutError:
                    # the device thread may be wedged inside the
                    # backend: ORPHAN the executor (new lane for the
                    # next batch) rather than queueing behind it
                    prep.abandoned = True
                    self._orphan_device_pool()
                    _telemetry.count("serving_deadline_exceeded")
                    raise TimeoutError(
                        f"DEADLINE_EXCEEDED: serve batch exceeded the "
                        f"{deadline}s device dispatch deadline"
                    ) from None
            else:
                rows = await aw
            if breaker is not None:
                breaker.record_success()
            self._note_dispatch(prep, t0)
            return rows
        except BreakerOpenError as e:
            _telemetry.count("serving_breaker_short_circuits")
            _log.warning("serve lane breaker open; host fallback: %s", e)
        except Exception as e:
            from ..runtime.errors import BUG, classify_error
            if breaker is None or classify_error(e) == BUG:
                raise
            breaker.record_failure()
            _telemetry.count("serving_device_failures")
            _telemetry.event("serving_fallback",
                             error=f"{type(e).__name__}: {e}",
                             breaker=breaker.state)
            _log.warning(
                "serve device dispatch failed (%s: %s); host fallback "
                "(breaker %s)", type(e).__name__, e, breaker.state)
        # breaker open / classified device failure: the tenant's batch
        # scores through the host columnar path in the FALLBACK pool —
        # the device lane stays free for healthy tenants
        rows = await loop.run_in_executor(
            self._fallback_pool, self._fallback_batch, prep)
        self._note_dispatch(prep, t0)
        return rows

    def _device_batch(self, prep: _PreparedBatch) -> List[dict]:
        """Device-pool thread: fused-program dispatch + guarded finish
        in one hop. An abandoned batch (deadline fired; answered via
        fallback) skips both — this thread may be waking from a hang
        long after anyone cared."""
        if prep.abandoned:
            return []
        scored = prep.entry.plan.dispatch_encoded(prep.enc)
        if prep.abandoned:
            return []
        return self._finish_batch(prep, scored, used_fallback=False)

    def _fallback_batch(self, prep: _PreparedBatch) -> List[dict]:
        """Fallback-pool thread: host columnar scoring + guarded
        finish for a tenant whose device path is unavailable."""
        scored = prep.entry.plan.score_host_columnar(prep.ds)
        return self._finish_batch(prep, scored, used_fallback=True)

    def _note_dispatch(self, prep: _PreparedBatch, t0: float) -> None:
        now = time.monotonic()
        self.stats["batches"] += 1
        self.stats["rows"] += len(prep.requests)
        self.stats["dispatch_seconds"] += now - t0
        if self._admission is not None:
            # measured drain rate + brownout recovery as backlogs clear
            self._admission.note_dispatch(
                len(prep.requests), now - t0,
                max(len(ln.queue) for ln in self._lanes.values())
                if self._lanes else 0)
        if self._first_dispatch_at is None:
            self._first_dispatch_at = t0
        self._last_dispatch_at = now
        _telemetry.count("serve_batches")
        _telemetry.count("serve_rows", len(prep.requests))

    def _finish_batch(self, prep: _PreparedBatch, scored,
                      used_fallback: bool) -> List[dict]:
        """Blocking post-dispatch host work: output guard, quarantined-
        row invalidation, sentinel observation, per-request row boxing
        (identical bookkeeping to ``ScoringPlan._score_guarded_raw``)."""
        from ..local.scoring import _unbox
        prep.marks["guard_t0"] = time.monotonic()
        prep.marks["fallback"] = used_fallback
        guards, names = prep.guards, prep.entry.result_names
        n, qmask = len(prep.requests), prep.qmask
        invalidated: List[GuardReason] = []
        if guards.output is not None:
            scored, invalidated = guards.output.check(
                scored, names, skip_rows=qmask)
        if qmask.any():
            scored = _invalidate_rows(scored, names, qmask)
        if guards.sentinel is not None:
            obs = (prep.ds.take(np.flatnonzero(~qmask)) if qmask.any()
                   else prep.ds)
            guards.sentinel.observe_dataset(obs)
        if self.lifecycle is not None:
            # ring feed + drift poll + post-swap watch
            # (serving/lifecycle.py); a dict lookup when idle
            self.lifecycle.note_batch(prep)
        n_bad = int(qmask.sum())
        _telemetry.count("serving_rows_scored", n - n_bad)
        if n_bad:
            _telemetry.count("serving_rows_quarantined", n_bad)
        if invalidated:
            _telemetry.count("serving_rows_invalidated",
                             len({r.row for r in invalidated}))
        by_row: Dict[int, List[dict]] = {}
        for r in prep.quarantined:
            by_row.setdefault(r.row, []).append(
                {"kind": "quarantined", **r.to_json()})
        for r in invalidated:
            by_row.setdefault(r.row, []).append(
                {"kind": "invalidated", **r.to_json()})
        cols = [scored[nm] for nm in names]
        rows: List[dict] = []
        for i in range(n):
            if i in by_row:
                row: dict = {nm: None for nm in names}
                row["_guard"] = by_row[i]
            else:
                row = {nm: _unbox(col.boxed(i))
                       for nm, col in zip(names, cols)}
            if used_fallback:
                row["_host_fallback"] = True
            rows.append(row)
        prep.marks["guard_t1"] = time.monotonic()
        return rows

    def _orphan_device_pool(self) -> None:
        """Abandon a wedged device executor (its thread may be stuck
        inside the backend forever) and stand up a fresh lane so the
        loop keeps dispatching — the serving twin of the selector's
        family-deadline abandonment."""
        self.stats["orphaned_dispatches"] += 1
        old = self._device_pool
        self._device_pool = _cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tx-serve-device")
        old.shutdown(wait=False)

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        """Arm the loop-bound primitives (call from the event loop the
        server will live on)."""
        self.loop = asyncio.get_running_loop()
        self._dispatch_sem = asyncio.Semaphore(1)
        self._running = True

    async def drain(self, timeout: float = 30.0) -> dict:
        """Graceful-shutdown half of preemption tolerance
        (docs/serving_restart.md): flip the loop to DRAINING — new
        requests refuse with :class:`ServeDraining` (the TCP front end
        turns that into the machine-readable ``"draining"`` answer) —
        then wait up to ``timeout`` seconds for every queued and
        in-flight request to resolve. Returns ``{"drained", "inflight",
        "seconds"}``; ``drained`` False means the deadline fired with
        requests still outstanding (they fail at :meth:`shutdown`)."""
        t0 = time.monotonic()
        self._draining = True
        self._drain_event = asyncio.Event()
        _telemetry.count("serve_drains")
        _telemetry.event("serve_draining", inflight=self._inflight)
        if self._inflight == 0:
            self._drain_event.set()
        try:
            await asyncio.wait_for(self._drain_event.wait(), timeout)
            drained = True
        except asyncio.TimeoutError:
            drained = False
            _telemetry.count("serve_drain_timeouts")
        out = {"drained": drained, "inflight": self._inflight,
               "seconds": round(time.monotonic() - t0, 4)}
        _telemetry.event("serve_drained", **out)
        return out

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def inflight(self) -> int:
        return self._inflight

    async def shutdown(self) -> None:
        self._running = False
        if self._admission is not None:
            self._admission.drain_waiters()
        for lane in self._lanes.values():
            if lane.wakeup is not None:
                lane.wakeup.set()
            if lane.task is not None:
                lane.task.cancel()
            for req in lane.queue:
                if not req.future.done():
                    req.future.set_exception(
                        ServeRejected("serving loop stopped"))
            lane.queue.clear()

    def start_background(self) -> "ServingClient":
        """Run the server on a daemon-thread event loop and return a
        sync :class:`ServingClient` — the in-process entry point for
        tests and the bench."""
        if self._thread is not None:
            return ServingClient(self)
        ready = threading.Event()

        def runner():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            loop.run_until_complete(self.start())
            ready.set()
            loop.run_forever()
            loop.run_until_complete(self.shutdown())
            loop.close()

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="tx-serve-loop")
        self._thread.start()
        ready.wait(timeout=30)
        return ServingClient(self)

    def stop(self) -> None:
        if self._thread is None:
            return
        self._running = False
        if self.loop is not None:
            self.loop.call_soon_threadsafe(lambda: None)  # wake
            self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=30)
        self._thread = None
        self._encode_pool.shutdown(wait=False)
        self._device_pool.shutdown(wait=False)
        self._fallback_pool.shutdown(wait=False)
        if self.lifecycle is not None:
            self.lifecycle.shutdown()

    # -- introspection -----------------------------------------------------
    def describe(self) -> dict:
        """Loop stats for bench/ops: occupancy (mean rows per
        dispatch) and device-lane saturation (fraction of wall time a
        dispatch was in flight)."""
        batches = self.stats["batches"] or 1
        wall = None
        if self._first_dispatch_at is not None:
            wall = max((self._last_dispatch_at or 0)
                       - self._first_dispatch_at, 1e-9)
        return {
            "requests": int(self.stats["requests"]),
            "batches": int(self.stats["batches"]),
            "rows": int(self.stats["rows"]),
            "mean_batch_occupancy": self.stats["rows"] / batches,
            "full_dispatches": int(self.stats["full_dispatches"]),
            "deadline_dispatches": int(self.stats["deadline_dispatches"]),
            "orphaned_dispatches": int(self.stats["orphaned_dispatches"]),
            "dispatch_saturation": (
                self.stats["dispatch_seconds"] / wall
                if wall is not None else 0.0),
            "plan_cache": {"budget": self.plans.budget,
                           "resident": len(self.plans._entries),
                           "evictions": self.plans.evictions},
            "models": self.plans.names(),
            "lanes": sorted("/".join(k) for k in self._lanes),
        }

    def process_block(self) -> dict:
        """The ``process`` slice of :meth:`metrics_snapshot`: this
        incarnation's identity and restart-readiness state — what a
        supervisor, load balancer, or the bench restart drill polls.
        Field set is pinned by tests (schema version 3)."""
        return {
            "uptime_seconds": round(self.metrics.uptime_seconds(), 3),
            "restart_generation": self.restart_generation,
            "draining": self._draining,
            "ready": bool(self.ready),
            "inflight": self._inflight,
            "last_snapshot_age_seconds": (
                round(max(time.time() - self.last_snapshot_at, 0.0), 3)
                if self.last_snapshot_at is not None else None),
        }

    def metrics_snapshot(self) -> dict:
        """The LIVE metrics document (schema versioned,
        docs/observability.md): loop counters, per-tenant latency
        quantiles from the streaming histograms, per-lane queue depth,
        plan-cache hits/evictions, per-tenant breaker state, and the
        serving slice of the process telemetry counters. Answered by
        the ``{"metrics": true}`` TCP control request and the
        ``tx serve --metrics-port`` HTTP endpoint while the loop is
        SERVING — no stop() required. Cheap enough for the event loop:
        dict reads + fixed-bin quantile interpolation, no device work,
        no I/O."""
        from ..observability.metrics import METRICS_SCHEMA_VERSION
        from .plan import plan_compiles
        breakers = {}
        sentinels = {}
        live = [(name, entry) for (name, _buckets), entry
                in list(self.plans._entries.items())]
        live += [(name, entry) for (name, _tenant), entry
                 in self.plans.swapped_entries().items()]
        for name, entry in live:
            for tenant, guards in list(entry.guards.items()):
                lane = f"{name}/{tenant}"
                if guards.breaker is not None:
                    breakers[lane] = guards.breaker.state
                if guards.sentinel is not None:
                    # per-tenant drift state: per-feature JS vs the
                    # warn/degrade thresholds + rows observed — the
                    # condition that triggers the self-healing loop,
                    # visible BEFORE it fires (docs/self_healing.md)
                    report = guards.sentinel.drift_report()
                    sentinels[lane] = {
                        "status": report["status"],
                        "rowsSeen": report["rowsSeen"],
                        "warnThreshold": report["warnThreshold"],
                        "degradeThreshold": report["degradeThreshold"],
                        "generation": getattr(guards.sentinel,
                                              "generation", 0),
                        "features": {
                            f["feature"]: {
                                "jsDivergence": f["jsDivergence"],
                                "status": f["status"],
                                "rowsObserved": f["rowsObserved"],
                            } for f in report["features"]},
                    }
        serving_counters = {
            k: v for k, v in _telemetry.counters().items()
            if k.startswith(("serve_", "serving_", "breaker_",
                             "drift_", "lifecycle_"))}
        return {
            "schema": METRICS_SCHEMA_VERSION,
            "uptime_seconds": round(self.metrics.uptime_seconds(), 3),
            "running": self._running,
            "process": self.process_block(),
            "requests": int(self.stats["requests"]),
            "answered": self.metrics.answered,
            "failed_batches": self.metrics.failed,
            "batches": int(self.stats["batches"]),
            "rows": int(self.stats["rows"]),
            "mean_batch_occupancy": round(
                self.stats["rows"] / (self.stats["batches"] or 1), 3),
            "full_dispatches": int(self.stats["full_dispatches"]),
            "deadline_dispatches": int(
                self.stats["deadline_dispatches"]),
            "orphaned_dispatches": int(
                self.stats["orphaned_dispatches"]),
            "queue_depth": {"/".join(k): len(lane.queue)
                            for k, lane in sorted(self._lanes.items())},
            "admission": (self._admission.snapshot(
                {"/".join(k): len(lane.queue)
                 for k, lane in sorted(self._lanes.items())})
                if self._admission is not None
                else {"enabled": False}),
            "latency_ms": self.metrics.latency_json(),
            "plan_cache": {"budget": self.plans.budget,
                           "resident": len(self.plans._entries),
                           "hits": self.plans.hits,
                           "misses": self.plans.misses,
                           "evictions": self.plans.evictions},
            "plan_compiles": plan_compiles(),
            # AOT artifact state per resident model (docs/
            # aot_artifacts.md): which plans serve from deserialized
            # executables vs live compiles — the zero-compile-cold-
            # start acceptance signal next to plan_compiles above
            "aot": {
                name: (entry.plan.aot_summary()
                       if hasattr(entry.plan, "aot_summary") else None)
                for name, entry in live},
            "breakers": breakers,
            "sentinels": sentinels,
            "lifecycle": (self.lifecycle.snapshot()
                          if self.lifecycle is not None else None),
            "counters": serving_counters,
            "trace": {"enabled": _trace.enabled(),
                      "path": _trace.trace_path()},
        }


class ServingClient:
    """Synchronous in-process facade over a background-thread
    :class:`ServingServer` — what tests and ``TX_BENCH_MODE=serve_loop``
    drive. ``submit`` returns a concurrent future for open-loop load
    generation; ``score`` blocks for one row."""

    def __init__(self, server: ServingServer):
        self.server = server

    def submit(self, record: dict, model: Optional[str] = None,
               tenant: str = "default") -> "_cf.Future":
        if self.server.loop is None:
            raise ServeRejected("server not started")
        return asyncio.run_coroutine_threadsafe(
            self.server.score_async(record, model=model, tenant=tenant),
            self.server.loop)

    def score(self, record: dict, model: Optional[str] = None,
              tenant: str = "default", timeout: float = 60.0) -> dict:
        return self.submit(record, model=model, tenant=tenant).result(
            timeout)

    def score_many(self, records: Sequence[dict],
                   model: Optional[str] = None, tenant: str = "default",
                   timeout: float = 120.0) -> List[dict]:
        """Submit every record CONCURRENTLY (they coalesce into shared
        bucket dispatches) and return rows in request order."""
        futs = [self.submit(r, model=model, tenant=tenant)
                for r in records]
        return [f.result(timeout) for f in futs]


def serve_in_process(models: Dict[str, Any],
                     config: Optional[ServeConfig] = None
                     ) -> Tuple[ServingServer, ServingClient]:
    """One-call setup for tests/bench: register ``models`` (name ->
    fitted model or saved dir), start the loop on a background thread,
    return (server, client). Caller owns ``server.stop()``."""
    server = ServingServer(config)
    for name, m in models.items():
        server.add_model(name, m)
    client = server.start_background()
    return server, client
