"""Warm-restart state snapshot: preemption tolerance for the serving
process (docs/serving_restart.md).

The serving loop accumulates expensive, purely-derived state — compiled
bucket programs, per-tenant drift sketches, breaker states, lifecycle
generations — that a SIGTERM or preemption throws away, forcing a cold
restart that recompiles the world before the first reply. This module
makes that state durable without making it authoritative:

- :class:`ServingStateSnapshot` captures the live server into one
  schema-versioned JSON document: the model-zoo manifest (which saved
  model dirs are registered), the per-model WARM-BUCKET manifest (which
  bucket programs this incarnation actually compiled, from
  ``bucket_profile()``, plus a small ring of admitted records to replay
  into them), per-(model, tenant) sentinel sketches + generations,
  breaker states, plan-cache LRU order, the lifecycle slice, and
  telemetry high-water marks.
- :class:`StateManager` writes the snapshot through the shared atomic
  tmp+``os.replace`` writer (``observability/store.atomic_write_json``,
  lint rule TX-R04) — periodically, at lifecycle commits, and at the
  end of a graceful drain — and restores it on a ``tx serve
  --resume-state`` boot BEFORE the TCP port binds: the recorded buckets
  are re-compiled and pre-warmed behind the readiness gate, so steady
  state after a warm restart pays ZERO compiles.

A torn, unreadable, or schema-mismatched snapshot is a loud telemetry
event (``serving_state_*``) followed by a clean COLD start — never a
crash, never a silent partial restore (any mid-restore failure rolls
the decision back to cold). Fault drills: ``TX_FAULT_PLAN``
``state:<model>:snapshot`` / ``state:<model>:restore`` scopes, with the
``torn`` fault truncating the written document mid-serialization
(runtime/faults.py).
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..observability.store import atomic_write_json
from ..runtime import telemetry as _telemetry
from ..runtime.errors import classify_error
from ..runtime.faults import maybe_inject

_log = logging.getLogger(__name__)

__all__ = ["ServingStateSnapshot", "StateManager", "SNAPSHOT_SCHEMA",
           "SNAPSHOT_FILE"]

#: schema identity of the snapshot document; a restore refuses any
#: other schema (cold start + telemetry, never a guess)
SNAPSHOT_SCHEMA = "tx-serving-state/1"
SNAPSHOT_FILE = "serving-state.json"

#: telemetry counter prefixes worth carrying across incarnations —
#: the serving slice metrics_snapshot() reports
_COUNTER_PREFIXES = ("serve_", "serving_", "breaker_", "drift_",
                     "lifecycle_")


def _jsonable(records: List[dict]) -> List[dict]:
    """Only records that round-trip through JSON belong in the
    snapshot (in-process callers may enqueue exotic values; the TCP
    path is JSON-native by construction)."""
    out = []
    for r in records:
        try:
            out.append(json.loads(json.dumps(r)))
        except (TypeError, ValueError):
            _telemetry.count("serving_state_sample_drops")
            continue
    return out


@dataclass
class ServingStateSnapshot:
    """One incarnation's restorable warm state. ``capture`` reads the
    live server; ``restore`` replays the document into a fresh one."""
    written_at: float = 0.0
    restart_generation: int = 0
    #: name -> {dir, warm_buckets, bucket_range, samples, tenants}
    models: Dict[str, dict] = field(default_factory=dict)
    #: "model/tenant" -> DriftSentinel.state_dict()
    sentinels: Dict[str, dict] = field(default_factory=dict)
    #: "model/tenant" -> {state, consecutiveFailures,
    #:                    openRemainingSeconds}
    breakers: Dict[str, dict] = field(default_factory=dict)
    #: resident plan-cache model names, least-recently-used first
    lru: List[str] = field(default_factory=list)
    #: ModelLifecycle.state_dict() (None when lifecycle is off)
    lifecycle: Optional[dict] = None
    #: telemetry counter high-water marks (serving slice)
    counters: Dict[str, int] = field(default_factory=dict)
    answered: int = 0

    # -- capture -----------------------------------------------------------
    @classmethod
    def capture(cls, server) -> "ServingStateSnapshot":
        snap = cls(written_at=time.time(),
                   restart_generation=server.restart_generation)
        for key, entry in server.plans.resident_entries():
            name = key[0]
            plan = entry.plan
            warm = sorted(b for b, rec in plan.bucket_profile().items()
                          if rec.get("calls", 0) > 0)
            samples = _jsonable(
                list(server._sample_records.get(name, ())))
            loader = server.plans._loaders.get(name)
            snap.models[name] = {
                "dir": loader if isinstance(loader, str) else None,
                "warm_buckets": warm,
                "bucket_range": [plan.min_bucket, plan.max_bucket],
                "samples": samples,
                "tenants": sorted(entry.guards),
                # which AOT artifact store this incarnation served
                # from (None = live-compiled) — the restore logs a
                # drift event when the next boot resolves a DIFFERENT
                # store (docs/aot_artifacts.md)
                "artifacts": (plan.aot_summary()
                              if hasattr(plan, "aot_summary")
                              else None),
            }
            if name not in snap.lru:
                snap.lru.append(name)
            for tenant, guards in list(entry.guards.items()):
                lane = f"{name}/{tenant}"
                if guards.sentinel is not None:
                    snap.sentinels[lane] = guards.sentinel.state_dict()
                br = guards.breaker
                if br is not None:
                    remaining = 0.0
                    if br.state == br.OPEN and br.opened_at is not None:
                        remaining = max(
                            br.cooldown_seconds
                            - (br.clock() - br.opened_at), 0.0)
                    snap.breakers[lane] = {
                        "state": br.state,
                        "consecutiveFailures": br.consecutive_failures,
                        "openRemainingSeconds": round(remaining, 3),
                    }
        if server.lifecycle is not None:
            snap.lifecycle = server.lifecycle.state_dict()
        snap.counters = {
            k: int(v) for k, v in _telemetry.counters().items()
            if k.startswith(_COUNTER_PREFIXES)}
        snap.answered = int(server.metrics.answered)
        return snap

    def to_json(self) -> dict:
        return {
            "schema": SNAPSHOT_SCHEMA,
            "writtenAt": self.written_at,
            "restartGeneration": self.restart_generation,
            "models": self.models,
            "sentinels": self.sentinels,
            "breakers": self.breakers,
            "lru": self.lru,
            "lifecycle": self.lifecycle,
            "counters": self.counters,
            "answered": self.answered,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "ServingStateSnapshot":
        return cls(
            written_at=float(doc.get("writtenAt", 0.0)),
            restart_generation=int(doc.get("restartGeneration", 0)),
            models={str(k): dict(v)
                    for k, v in (doc.get("models") or {}).items()},
            sentinels=dict(doc.get("sentinels") or {}),
            breakers=dict(doc.get("breakers") or {}),
            lru=[str(n) for n in doc.get("lru") or []],
            lifecycle=doc.get("lifecycle"),
            counters={str(k): int(v) for k, v in
                      (doc.get("counters") or {}).items()},
            answered=int(doc.get("answered", 0)))

    # -- restore -----------------------------------------------------------
    def restore(self, server) -> dict:
        """Replay this snapshot into ``server`` (blocking — call
        BEFORE the port binds, behind the readiness gate). Raises on
        any inconsistency; :meth:`StateManager.restore` catches and
        degrades to cold. Returns the warm-boot summary."""
        from .server import _TenantGuards
        from .plan import plan_compiles
        compiles0 = plan_compiles()
        warmed: Dict[str, List[int]] = {}
        for name, mdoc in self.models.items():
            if name not in server.plans._loaders:
                mdir = mdoc.get("dir")
                if mdir and os.path.isdir(mdir):
                    server.add_model(name, mdir)
                else:
                    _telemetry.event("serving_state_model_skipped",
                                     model=name,
                                     reason="unregistered in-memory "
                                            "model")
                    continue
            entry = server.plans.get(
                name, getattr(server, "plan_buckets", (None, None)),
                getattr(server, "plan_lattice", None))
            # artifact-manifest continuity: a warm restart that lands
            # on a different (or no) artifact store than the previous
            # incarnation is loud — the model dir changed under us
            prev_art = mdoc.get("artifacts")
            cur_art = (entry.plan.aot_summary()
                       if hasattr(entry.plan, "aot_summary") else None)
            drifted = prev_art is not None and (
                cur_art is None
                or cur_art.get("fingerprint")
                != prev_art.get("fingerprint"))
            if drifted:
                _telemetry.count("serving_state_artifact_drift")
                _telemetry.event(
                    "serving_state_artifact_drift", model=name,
                    previous=str((prev_art or {}).get("fingerprint")),
                    current=str((cur_art or {}).get("fingerprint")))
            if drifted:
                # the model dir was re-saved between snapshot and
                # resume: the snapshot's warm buckets describe
                # PROGRAMS THAT NO LONGER EXIST. Replaying them would
                # pay full compiles for plans the new fingerprint may
                # bucket differently — boot cold for this model and
                # let live traffic warm the real lattice.
                warmed[name] = []
            else:
                samples = list(mdoc.get("samples") or []) or [{}]
                buckets = [int(b)
                           for b in mdoc.get("warm_buckets") or []]
                for bucket in sorted(buckets):
                    batch = list(itertools.islice(
                        itertools.cycle(samples), bucket))
                    entry.plan.score(batch)
                warmed[name] = sorted(buckets)
            for tenant in mdoc.get("tenants") or []:
                if tenant not in entry.guards:
                    entry.guards[tenant] = _TenantGuards(
                        entry.model, server.config)
        lanes = 0
        for lane, state in self.sentinels.items():
            guards = self._lane_guards(server, lane)
            if guards is not None and guards.sentinel is not None:
                guards.sentinel.load_state(state)
                lanes += 1
        for lane, bstate in self.breakers.items():
            guards = self._lane_guards(server, lane)
            if guards is None or guards.breaker is None:
                continue
            br = guards.breaker
            st = bstate.get("state", br.CLOSED)
            if st in (br.CLOSED, br.OPEN, br.HALF_OPEN):
                br.state = st
            br.consecutive_failures = int(
                bstate.get("consecutiveFailures", 0))
            if br.state == br.OPEN:
                remaining = float(
                    bstate.get("openRemainingSeconds", 0.0))
                br.opened_at = (br.clock()
                                - max(br.cooldown_seconds - remaining,
                                      0.0))
        for name in self.lru:
            server.plans.touch(
                name, getattr(server, "plan_buckets", (None, None)),
                getattr(server, "plan_lattice", None))
        if self.lifecycle is not None and server.lifecycle is not None:
            server.lifecycle.load_state(self.lifecycle)
        for k, v in self.counters.items():
            if v > 0:
                _telemetry.count(k, v)
        server.metrics.answered += self.answered
        server.last_snapshot_at = self.written_at
        return {"mode": "warm", "restored": True,
                "models": sorted(warmed),
                "warm_buckets": warmed,
                "sentinel_lanes": lanes,
                "breaker_lanes": len(self.breakers),
                "compiles": plan_compiles() - compiles0,
                "written_at": self.written_at}

    @staticmethod
    def _lane_guards(server, lane: str):
        name, _, tenant = lane.partition("/")
        key = (name, (None, None))
        entry = server.plans._entries.get(key)
        if entry is None:
            return None
        return entry.guards.get(tenant)


class StateManager:
    """Owns the snapshot file of one serving process: where it lives,
    when it is written, and how a boot restores it."""

    def __init__(self, server, state_dir: str):
        self.server = server
        self.state_dir = state_dir
        self.path = os.path.join(state_dir, SNAPSHOT_FILE)
        os.makedirs(state_dir, exist_ok=True)
        server.state_manager = self

    def _probe_name(self) -> str:
        return getattr(self.server, "_default_model", None) or "server"

    # -- write path --------------------------------------------------------
    def write(self, reason: str = "periodic") -> bool:
        """Capture + atomically persist. The ``state:<model>:snapshot``
        probe sits between capture and write: a ``torn`` fault
        truncates the serialized document onto the live path (the
        crash-mid-write drill); raising faults propagate (a ``kill``
        here dies exactly where a preemption would)."""
        snap = ServingStateSnapshot.capture(self.server)
        doc = snap.to_json()
        fault = maybe_inject("state", self._probe_name(), "snapshot")
        if fault == "torn":
            text = json.dumps(doc)
            self._write_torn(text[:max(len(text) // 2, 1)])
            _telemetry.count("serving_state_torn_writes")
            _telemetry.event("serving_state_torn_write",
                             path=self.path, reason=reason)
            return False
        ok = atomic_write_json(self.path, doc)
        if ok:
            self.server.last_snapshot_at = snap.written_at
            _telemetry.count("serve_state_snapshots")
            _telemetry.event("serve_state_snapshot", reason=reason,
                             models=len(snap.models))
        return ok

    def _write_torn(self, text: str) -> None:
        # the torn DRILL still goes tmp -> os.replace (TX-R04): what
        # is being simulated is a crash mid-serialization, i.e. a
        # truncated document at the live path — not a torn rename
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
        os.replace(tmp, self.path)

    # -- restore path ------------------------------------------------------
    def restore(self) -> dict:
        """Best-effort warm boot. Every failure mode — missing file,
        torn JSON, schema mismatch, injected restore fault, any
        exception while replaying — lands on the same answer: a loud
        telemetry event and ``{"mode": "cold"}``. Never raises."""
        if not os.path.exists(self.path):
            return {"mode": "cold", "restored": False,
                    "reason": "no snapshot"}
        try:
            with open(self.path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            _telemetry.count("serving_state_torn")
            _telemetry.event("serving_state_torn", path=self.path,
                             error=f"{type(e).__name__}: {e}")
            _log.warning("serving state snapshot at %s is torn/"
                         "unreadable (%s); cold start", self.path, e)
            return {"mode": "cold", "restored": False,
                    "reason": "torn snapshot"}
        schema = doc.get("schema") if isinstance(doc, dict) else None
        if schema != SNAPSHOT_SCHEMA:
            _telemetry.count("serving_state_schema_mismatch")
            _telemetry.event("serving_state_schema_mismatch",
                             path=self.path, found=str(schema),
                             expected=SNAPSHOT_SCHEMA)
            _log.warning("serving state snapshot schema %r != %r; "
                         "cold start", schema, SNAPSHOT_SCHEMA)
            return {"mode": "cold", "restored": False,
                    "reason": "schema mismatch"}
        try:
            fault = maybe_inject("state", self._probe_name(),
                                 "restore")
            if fault is not None:
                raise RuntimeError(
                    f"injected state-restore fault: {fault}")
            snap = ServingStateSnapshot.from_json(doc)
            out = snap.restore(self.server)
        except Exception as e:
            kind = classify_error(e)
            _telemetry.count("serving_state_restore_failures")
            _telemetry.event("serving_state_restore_failed",
                             path=self.path, kind=kind,
                             error=f"{type(e).__name__}: {e}")
            _log.warning("serving state restore failed (%s %s: %s); "
                         "cold start", kind, type(e).__name__, e)
            return {"mode": "cold", "restored": False,
                    "reason": f"restore failed: {type(e).__name__}"}
        _telemetry.count("serve_state_restores")
        _telemetry.event("serve_state_restored", **{
            k: v for k, v in out.items() if k != "warm_buckets"})
        return out
