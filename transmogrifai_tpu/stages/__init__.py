from .base import (BinaryEstimator, BinaryModel, BinarySequenceEstimator,
                   BinarySequenceTransformer, BinaryTransformer, Estimator,
                   LambdaTransformer, Model, PipelineStage,
                   QuaternaryTransformer, SequenceEstimator, SequenceModel,
                   SequenceTransformer, TernaryTransformer, Transformer,
                   UnaryEstimator, UnaryModel, UnaryTransformer,
                   register_stage_class, stage_class_by_name)

__all__ = [
    "PipelineStage", "Transformer", "Estimator", "Model",
    "UnaryTransformer", "UnaryEstimator", "UnaryModel",
    "BinaryTransformer", "BinaryEstimator", "BinaryModel",
    "TernaryTransformer", "QuaternaryTransformer",
    "SequenceTransformer", "SequenceEstimator", "SequenceModel",
    "BinarySequenceTransformer", "BinarySequenceEstimator",
    "LambdaTransformer", "register_stage_class", "stage_class_by_name",
]
