"""Stage kernel: pipeline stages, transformers, estimators.

TPU-native re-design of the reference stage kernel
(features/src/main/scala/com/salesforce/op/stages/{OpPipelineStages.scala:56,
base/*}). Key differences from the Spark design:

- The row-level ``OpTransformer.transformKeyValue`` interface
  (OpPipelineStages.scala:592) is replaced by a **columnar** batch interface
  ``transform_columns`` operating on numpy-backed ``FeatureColumn``s, which
  feed XLA device arrays directly. A derived row-level path
  (``transform_value``) remains for local serving and contract tests.
- The reference's reflective ctor-args capture for persistence
  (OpPipelineStageWriter.scala:78-120) becomes automatic-but-explicit ctor
  binding: every stage's ``__init__`` kwargs are recorded at construction
  and round-tripped through ``get_params`` / class registry lookup.

Arity conventions mirror the reference: Unary/Binary/Ternary/Quaternary
plus Sequence (N same-typed inputs) and BinarySequence (1 + N).
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, ClassVar, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..types import FeatureType, OPVector
from ..utils.uid import uid as make_uid

if False:  # TYPE_CHECKING without importing typing's guard at runtime:
    from ..features.columns import Dataset, FeatureColumn  # noqa: F401
    from ..features.feature import Feature  # noqa: F401

__all__ = [
    "PipelineStage", "Transformer", "Estimator", "Model",
    "UnaryTransformer", "UnaryEstimator", "UnaryModel",
    "BinaryTransformer", "BinaryEstimator", "BinaryModel",
    "TernaryTransformer", "QuaternaryTransformer",
    "SequenceTransformer", "SequenceEstimator", "SequenceModel",
    "BinarySequenceTransformer", "BinarySequenceEstimator",
    "LambdaTransformer", "stage_class_by_name", "register_stage_class",
    "AllowLabelAsInput",
]

_STAGE_REGISTRY: Dict[str, type] = {}


def register_stage_class(cls):
    _STAGE_REGISTRY[cls.__name__] = cls
    return cls


def stage_class_by_name(name: str):
    """Resolve a stage class for deserialization. Falls back to scanning
    registered subclasses (reference OpPipelineStageReader class-for-name,
    OpPipelineStageReader.scala:89-135)."""
    if name in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[name]
    # lazily import the ops/models packages so their classes register
    from .. import ops as _ops  # noqa: F401
    from .. import models as _models  # noqa: F401
    from .. import checkers as _checkers  # noqa: F401
    from .. import selector as _selector  # noqa: F401
    if name in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[name]
    raise KeyError(f"Unknown stage class {name!r}")


class PipelineStage:
    """Base of all stages (reference OpPipelineStageBase,
    OpPipelineStages.scala:56)."""

    #: expected input feature types; None entries accept any FeatureType.
    #: For sequence stages this is the per-element type.
    input_types: ClassVar[Optional[Tuple[Optional[type], ...]]] = None
    #: produced output feature type
    output_type: ClassVar[Type[FeatureType]] = OPVector
    #: minimum number of inputs for sequence stages
    min_inputs: ClassVar[int] = 1

    def __init__(self, operation_name: Optional[str] = None,
                 uid: Optional[str] = None):
        self.operation_name = operation_name or type(self).__name__
        self.uid = uid or make_uid(type(self))
        self.input_features: Tuple[Feature, ...] = ()
        self._output_feature: Optional["Feature"] = None

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        register_stage_class(cls)
        orig = cls.__init__
        if getattr(orig, "_captures_ctor", False):
            return
        try:
            sig = inspect.signature(orig)
        except (TypeError, ValueError):  # pragma: no cover
            return

        @functools.wraps(orig)
        def wrapper(self, *args, **kwargs):
            if not hasattr(self, "_ctor_args"):
                try:
                    bound = sig.bind(self, *args, **kwargs)
                    bound.apply_defaults()
                    captured = {}
                    for name, val in bound.arguments.items():
                        if name == "self":
                            continue
                        p = sig.parameters[name]
                        if p.kind == inspect.Parameter.VAR_KEYWORD:
                            captured.update(val)
                        elif p.kind == inspect.Parameter.VAR_POSITIONAL:
                            captured[name] = list(val)
                        else:
                            captured[name] = val
                    self._ctor_args = captured
                except TypeError:
                    self._ctor_args = {}
            orig(self, *args, **kwargs)

        wrapper._captures_ctor = True
        cls.__init__ = wrapper

    # -- wiring ------------------------------------------------------------
    def set_input(self, *features: "Feature") -> "PipelineStage":
        """Typed input wiring (reference OpPipelineStages.setInput:80)."""
        self._check_input_types(features)
        self.check_input_constraints(features)
        self.input_features = tuple(features)
        self._output_feature = None  # re-wiring invalidates the output
        return self

    def _check_input_types(self, features: Sequence[Feature]) -> None:
        expected = self.expected_input_types(len(features))
        if len(features) != len(expected):
            raise ValueError(
                f"{type(self).__name__} expects {len(expected)} inputs, "
                f"got {len(features)}")
        for i, (f, t) in enumerate(zip(features, expected)):
            if t is not None and not issubclass(f.ftype, t):
                raise TypeError(
                    f"{type(self).__name__} input {i} ({f.name!r}) must be "
                    f"{t.__name__}, got {f.ftype.__name__}")

    def expected_input_types(self, n: int) -> List[Optional[type]]:
        if self.input_types is None:
            return [None] * n
        if getattr(self, "is_sequence", False):
            if n < self.min_inputs:
                raise ValueError(
                    f"{type(self).__name__} needs >= {self.min_inputs} inputs")
            fixed = list(self.input_types[:-1])
            elem = self.input_types[-1]
            return fixed + [elem] * (n - len(fixed))
        return list(self.input_types)

    def check_input_constraints(self, features: Sequence[Feature]) -> None:
        """Hook for semantic checks, e.g. response/predictor constraints
        (reference CheckIsResponseValues)."""

    # -- static type metadata (consumed by the lint pre-flight) -----------
    def static_input_types(self) -> Optional[List[Optional[type]]]:
        """The declared input type contract resolved for the CURRENT
        wiring, without touching data or tracing: one entry per wired
        input (None = any FeatureType). Returns None when the stage
        declares no contract, or when the wiring violates the arity so
        badly the contract can't be resolved (lint reports that case
        from the raw declaration instead)."""
        if self.input_types is None:
            return None
        n = len(self.input_features) or len(self.input_types)
        try:
            return self.expected_input_types(n)
        except ValueError:
            return None

    def static_output_type(self) -> Type[FeatureType]:
        """The declared output feature type (instance attribute aware —
        e.g. LambdaTransformer's per-instance output_type)."""
        return self.output_type

    # -- output ------------------------------------------------------------
    def output_is_response(self) -> bool:
        """A feature derived from any response is itself a response, so it
        can never silently re-enter the predictor matrix (label-leakage
        guard; reference OpPipelineStages.scala:56 `exists(_.isResponse)`).
        Stages that legitimately consume the label to produce predictors
        (e.g. SanityChecker) mix in ``AllowLabelAsInput``."""
        return (len(self.input_features) > 0
                and any(f.is_response for f in self.input_features))

    def output_feature_name(self) -> str:
        names = [f.name for f in self.input_features]
        base = "-".join(names[:3]) + (f"-{len(names) - 3}more"
                                      if len(names) > 3 else "")
        suffix = self.uid.rsplit("_", 1)[-1]
        return f"{base}_{self.operation_name}_{suffix}" if base \
            else f"{self.operation_name}_{suffix}"

    def get_output(self) -> "Feature":
        """The (lazy) output feature (reference getOutput). Idempotent:
        repeated calls return the same Feature (same uid) until the stage
        is re-wired with ``set_input``."""
        from ..features.feature import Feature
        if self.input_features == () and not isinstance(self, _ZeroInput):
            raise ValueError(
                f"{type(self).__name__}.get_output() before set_input()")
        if self._output_feature is not None:
            return self._output_feature
        self._output_feature = Feature(
            name=self.output_feature_name(),
            ftype=self.output_type,
            is_response=self.output_is_response(),
            origin_stage=self,
            parents=self.input_features,
        )
        return self._output_feature

    # -- persistence -------------------------------------------------------
    def stage_name(self) -> str:
        return f"{type(self).__name__}_{self.operation_name}"

    def get_params(self) -> Dict[str, Any]:
        """Constructor kwargs captured at instantiation — JSON/npz
        round-trippable (replaces reflective ctor capture,
        OpPipelineStageWriter.scala:78-120)."""
        return dict(getattr(self, "_ctor_args", {}))

    def __repr__(self) -> str:
        ins = ", ".join(f.name for f in self.input_features)
        return f"{type(self).__name__}(uid={self.uid}, inputs=[{ins}])"


class _ZeroInput:
    """Marker for stages with no inputs (feature generators)."""


class AllowLabelAsInput:
    """Mixin for stages allowed to consume the label while producing
    predictor outputs (reference AllowLabelAsInput; used by SanityChecker,
    DecisionTreeNumericBucketizer, ModelSelector etc.). Output is a
    response only if *every* input is."""

    def output_is_response(self) -> bool:
        return (len(self.input_features) > 0
                and all(f.is_response for f in self.input_features))


class Transformer(PipelineStage):
    """A fitted/stateless row-batch transformation
    (reference OpTransformer, OpPipelineStages.scala:592)."""

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        raise NotImplementedError

    # -- compiled-serving lowering (serving/plan.py) -----------------------
    def transform_arrays(self, arrays: List[Any]) -> Any:
        """Array-level kernel for the compiled scoring plan: one jnp
        array per wired input slot (as produced by
        ``encode_input_column`` or an upstream stage's kernel), ONE jnp
        array out. Must be traceable under ``jax.jit`` — no host numpy,
        no Python branching on values. Stages without a lowering keep
        this default; the plan then runs them through the per-stage
        numpy ``transform_columns`` fallback (parity guaranteed)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no array lowering")

    def supports_arrays(self) -> bool:
        """Whether this stage lowers to an array kernel (plan coverage
        probe). Default: ``transform_arrays`` overridden somewhere below
        ``Transformer``."""
        return type(self).transform_arrays is not Transformer.transform_arrays

    def encodes_input(self, i: int) -> bool:
        """True when input slot ``i`` needs a stage-specific host
        encoder (``encode_input_column`` override) rather than the
        identity numeric/vector encoding — e.g. a trained
        category->index lookup. The plan only lowers such a stage when
        that input is host-materialized (raw or numpy-fallback output),
        never when it is produced inside the device graph."""
        return False

    def encode_input_column(self, i: int, col: "FeatureColumn") -> np.ndarray:
        """Host-side boundary encoder: FeatureColumn -> the dense
        numeric array input slot ``i`` of ``transform_arrays`` expects.
        The default is the identity encoding for numeric/vector columns
        (so in-graph arrays and host-encoded arrays are
        interchangeable); object columns must be encoded by a
        stage-specific override (``encodes_input`` -> True)."""
        kind = col.kind
        if kind == "numeric":
            return np.asarray(col.data, dtype=np.float64)
        if kind == "vector":
            return np.asarray(col.data, dtype=np.float64)
        raise TypeError(
            f"{type(self).__name__} input {i} ({col.ftype.__name__}, "
            f"kind={kind!r}) has no default array encoding")

    def transform_dataset(self, ds: Dataset) -> Dataset:
        out = self.get_output()
        cols = [ds[f.name] for f in self.input_features]
        return ds.with_column(out.name, self.transform_columns(cols))

    def transform_value(self, *values: Any) -> FeatureType:
        """Row-level scoring path (local serving; reference
        transformKeyValue). Default implementation routes a single-row
        column batch through ``transform_columns``."""
        from ..features.columns import FeatureColumn
        cols = []
        for f, v in zip(self.input_features, values):
            fv = v if isinstance(v, FeatureType) else f.ftype(v)
            cols.append(FeatureColumn.from_values(f.ftype, [fv]))
        return self.transform_columns(cols).boxed(0)


class Estimator(PipelineStage):
    """A stage that must be fitted to produce a Model
    (reference base/unary/UnaryEstimator.scala:56 et al.)."""

    def fit_columns(self, cols: List[FeatureColumn]) -> "Model":
        raise NotImplementedError

    def fit(self, ds: Dataset) -> "Model":
        cols = [ds[f.name] for f in self.input_features]
        model = self.fit_columns(cols)
        #: back-pointer so downstream stages executing mid-training can
        #: resolve the fitted model before the DAG swap (e.g.
        #: PredictionDeIndexer reading StringIndexer labels). Only valid
        #: during the train() that set it — after training, the swapped
        #: DAG points at the fitted model directly, so consumers must
        #: prefer the origin stage itself over this pointer.
        self.fitted_model = model
        return self._wire_model(model)

    # -- compiled-prepare lowering (plans/prepare.py) ----------------------
    def fit_device(self, arrays: List[Any],
                   protos: List["FeatureColumn"]) -> "Model":
        """Array-level fit kernel for the compiled prepare plan: one
        array per wired input slot (device-resident jax arrays for
        columns produced inside the fused feature program, dense numpy
        for host-materialized numeric/vector inputs), plus the
        zero-row proto columns carrying each input's type/metadata.
        Must return a Model IDENTICAL to ``fit_columns`` on the same
        values — the statistics math may (should) run on device, the
        fitted state must not depend on where. Stages without a device
        fit keep this default; the plan then records a host fallback
        (the inputs are pulled back to columns) with the reason."""
        raise NotImplementedError(
            f"{type(self).__name__} has no device fit kernel")

    def supports_device_fit(self) -> bool:
        """Whether this estimator exposes a ``fit_device`` kernel (the
        prepare plan's placement probe, plans/placement.py). A subclass
        that overrides ``fit_columns`` BELOW the class defining
        ``fit_device`` opts back out: routing its fit through the
        inherited device kernel would silently bypass the override."""
        cls = type(self)
        if cls.fit_device is Estimator.fit_device:
            return False
        mro = cls.__mro__
        dev_i = next(i for i, c in enumerate(mro)
                     if "fit_device" in c.__dict__)
        col_i = next((i for i, c in enumerate(mro)
                      if "fit_columns" in c.__dict__), None)
        return col_i is None or col_i >= dev_i

    def fit_from_arrays(self, arrays: List[Any],
                        protos: List["FeatureColumn"]) -> "Model":
        """``fit_device`` behind the same wiring/bookkeeping ``fit``
        performs (uid inheritance, ``fitted_model`` back-pointer) so
        DAG stage-swapping works identically for both fit paths."""
        model = self.fit_device(arrays, protos)
        self.fitted_model = model
        return self._wire_model(model)

    def _wire_model(self, model: "Model") -> "Model":
        """Fitted model inherits the estimator's uid, wiring and operation
        name so DAG stage-swapping by uid works
        (reference: models share the estimator uid)."""
        model.uid = self.uid
        model.operation_name = self.operation_name
        model.input_features = self.input_features
        model.parent_estimator_class = type(self).__name__
        return model


class Model(Transformer):
    """A fitted transformer produced by an Estimator."""
    parent_estimator_class: Optional[str] = None


# ---------------------------------------------------------------------------
# Arity-specific bases (reference base/{unary,binary,ternary,quaternary,
# sequence}/)
# ---------------------------------------------------------------------------

class UnaryTransformer(Transformer):
    """1 input -> 1 output (reference base/unary/UnaryTransformer.scala:75)."""


class UnaryModel(Model, UnaryTransformer):
    pass


class UnaryEstimator(Estimator):
    pass


class BinaryTransformer(Transformer):
    pass


class BinaryModel(Model, BinaryTransformer):
    pass


class BinaryEstimator(Estimator):
    pass


class TernaryTransformer(Transformer):
    pass


class QuaternaryTransformer(Transformer):
    pass


class _SequenceMixin:
    is_sequence: ClassVar[bool] = True


class SequenceTransformer(_SequenceMixin, Transformer):
    """N same-typed inputs -> 1 output."""


class SequenceModel(_SequenceMixin, Model):
    pass


class SequenceEstimator(_SequenceMixin, Estimator):
    """The vectorizer workhorse (reference
    base/sequence/SequenceEstimator.scala:57)."""


class BinarySequenceTransformer(_SequenceMixin, Transformer):
    """1 distinguished input + N same-typed inputs."""


class BinarySequenceEstimator(_SequenceMixin, Estimator):
    pass


class LambdaTransformer(UnaryTransformer):
    """Generic ``.map``-style transformer over boxed values (reference
    RichFeature.map / lambda transformers). The function operates on boxed
    feature values row-wise — intended for user extract-style logic, not
    hot paths. Not serializable unless the function is importable."""

    def __init__(self, fn: Callable[[FeatureType], FeatureType],
                 output_type: Type[FeatureType],
                 operation_name: str = "lambda",
                 uid: Optional[str] = None):
        super().__init__(operation_name=operation_name, uid=uid)
        self.fn = fn
        self.output_type = output_type  # instance attr shadows classvar

    def transform_columns(self, cols: List["FeatureColumn"]) -> "FeatureColumn":
        from ..features.columns import FeatureColumn
        col = cols[0]
        out = [self.fn(col.boxed(i)) for i in range(col.n_rows)]
        return FeatureColumn.from_values(self.output_type, out)
