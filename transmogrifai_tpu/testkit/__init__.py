"""Testkit: contract specs + seeded random typed-data generators
(SURVEY §2.15; testkit/src/main/scala/com/salesforce/op/testkit/)."""
from .random_data import (RandomBinary, RandomData, RandomIntegral,
                          RandomList, RandomMap, RandomReal, RandomSet,
                          RandomText, RandomVector)
from .spec import StageSpecBase

__all__ = ["StageSpecBase", "RandomReal", "RandomIntegral", "RandomBinary",
           "RandomText", "RandomList", "RandomSet", "RandomMap",
           "RandomVector", "RandomData"]
