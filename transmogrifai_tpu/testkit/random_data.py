"""Seeded random typed-data generators.

TPU-native port of the reference testkit
(testkit/src/main/scala/com/salesforce/op/testkit/{RandomData.scala:51,
RandomReal.scala:45, RandomText.scala:49, RandomIntegral.scala,
RandomBinary.scala, RandomList.scala, RandomMap.scala, RandomSet.scala,
RandomVector.scala, ProbabilityOfEmpty.scala}): every FeatureType gets a
deterministic generator stream with optional probability-of-empty. Used
by stage/selector tests in place of real datasets.
"""
from __future__ import annotations

import string
from typing import Dict, List, Optional, Sequence, Type

import numpy as np

from ..features.columns import FeatureColumn
from ..types import (Binary, City, ComboBox, Country, Currency, Date,
                     DateTime, Email, FeatureType, Geolocation, ID, Integral,
                     MultiPickList, OPVector, Percent, PickList, PostalCode,
                     Real, RealNN, State, Street, Text, TextArea, TextList,
                     URL)

__all__ = ["RandomReal", "RandomIntegral", "RandomBinary", "RandomText",
           "RandomList", "RandomSet", "RandomMap", "RandomVector",
           "RandomData"]


class _RandomBase:
    """Seeded infinite stream of boxed feature values."""

    ftype: Type[FeatureType] = Real

    def __init__(self, seed: int = 42, probability_of_empty: float = 0.0):
        self.seed = seed
        self.probability_of_empty = probability_of_empty
        self.reset(seed)

    def reset(self, seed: Optional[int] = None) -> "_RandomBase":
        """(reference RandomData.reset)"""
        self._rng = np.random.default_rng(
            self.seed if seed is None else seed)
        return self

    def with_probability_of_empty(self, p: float) -> "_RandomBase":
        """(reference ProbabilityOfEmpty.withProbabilityOfEmpty)"""
        self.probability_of_empty = p
        return self

    def _value(self):
        raise NotImplementedError

    def take(self, n: int) -> List[FeatureType]:
        out = []
        for _ in range(n):
            if (self.probability_of_empty > 0
                    and self._rng.uniform() < self.probability_of_empty):
                out.append(self.ftype.empty())
            else:
                out.append(self.ftype(self._value()))
        return out

    def column(self, n: int) -> FeatureColumn:
        return FeatureColumn.from_values(self.ftype, self.take(n))


class RandomReal(_RandomBase):
    """(reference RandomReal.scala:45,75 — uniform/normal/poisson/
    exponential/gamma/logNormal/weibull distributions)"""

    def __init__(self, distribution: str = "uniform", a: float = 0.0,
                 b: float = 1.0, ftype: Type[FeatureType] = Real,
                 seed: int = 42, probability_of_empty: float = 0.0):
        self.distribution = distribution
        self.a, self.b = a, b
        self.ftype = ftype
        super().__init__(seed, probability_of_empty)

    @classmethod
    def uniform(cls, low: float = 0.0, high: float = 1.0,
                ftype: Type[FeatureType] = Real, seed: int = 42):
        return cls("uniform", low, high, ftype, seed)

    @classmethod
    def normal(cls, mean: float = 0.0, sigma: float = 1.0,
               ftype: Type[FeatureType] = Real, seed: int = 42):
        return cls("normal", mean, sigma, ftype, seed)

    @classmethod
    def poisson(cls, mean: float = 1.0, ftype: Type[FeatureType] = Real,
                seed: int = 42):
        return cls("poisson", mean, 0.0, ftype, seed)

    @classmethod
    def exponential(cls, scale: float = 1.0,
                    ftype: Type[FeatureType] = Real, seed: int = 42):
        return cls("exponential", scale, 0.0, ftype, seed)

    @classmethod
    def gamma(cls, shape: float = 2.0, scale: float = 1.0,
              ftype: Type[FeatureType] = Real, seed: int = 42):
        return cls("gamma", shape, scale, ftype, seed)

    @classmethod
    def lognormal(cls, mean: float = 0.0, sigma: float = 1.0,
                  ftype: Type[FeatureType] = Real, seed: int = 42):
        return cls("lognormal", mean, sigma, ftype, seed)

    @classmethod
    def weibull(cls, shape: float = 1.5, scale: float = 1.0,
                ftype: Type[FeatureType] = Real, seed: int = 42):
        return cls("weibull", shape, scale, ftype, seed)

    def _value(self) -> float:
        r, a, b = self._rng, self.a, self.b
        if self.distribution == "uniform":
            return float(r.uniform(a, b))
        if self.distribution == "normal":
            return float(r.normal(a, b))
        if self.distribution == "poisson":
            return float(r.poisson(a))
        if self.distribution == "exponential":
            return float(r.exponential(a))
        if self.distribution == "gamma":
            return float(r.gamma(a, b))
        if self.distribution == "lognormal":
            return float(r.lognormal(a, b))
        if self.distribution == "weibull":
            return float(b * r.weibull(a))
        raise ValueError(f"Unknown distribution {self.distribution!r}")


class RandomIntegral(_RandomBase):
    """(reference RandomIntegral.scala)"""

    ftype = Integral

    def __init__(self, low: int = 0, high: int = 100,
                 ftype: Type[FeatureType] = Integral, seed: int = 42,
                 probability_of_empty: float = 0.0):
        self.low, self.high = low, high
        self.ftype = ftype
        super().__init__(seed, probability_of_empty)

    @classmethod
    def integers(cls, low: int = 0, high: int = 100, seed: int = 42):
        return cls(low, high, Integral, seed)

    @classmethod
    def dates(cls, start_ms: int = 1_500_000_000_000,
              step_ms: int = 86_400_000, seed: int = 42):
        return cls(start_ms, start_ms + 1000 * step_ms, Date, seed)

    @classmethod
    def datetimes(cls, start_ms: int = 1_500_000_000_000,
                  step_ms: int = 3_600_000, seed: int = 42):
        return cls(start_ms, start_ms + 1000 * step_ms, DateTime, seed)

    def _value(self) -> int:
        return int(self._rng.integers(self.low, self.high))


class RandomBinary(_RandomBase):
    """(reference RandomBinary.scala)"""

    ftype = Binary

    def __init__(self, probability_of_true: float = 0.5, seed: int = 42,
                 probability_of_empty: float = 0.0):
        self.probability_of_true = probability_of_true
        super().__init__(seed, probability_of_empty)

    def _value(self) -> bool:
        return bool(self._rng.uniform() < self.probability_of_true)


_COUNTRIES = ["USA", "Canada", "Mexico", "France", "Germany", "Japan",
              "Brazil", "India", "Kenya", "Australia"]
_STATES = ["CA", "NY", "TX", "WA", "OR", "IL", "GA", "MA", "CO", "FL"]
_CITIES = ["San Francisco", "New York", "Austin", "Seattle", "Portland",
           "Chicago", "Atlanta", "Boston", "Denver", "Miami"]
_DOMAINS = ["example.com", "mail.org", "corp.net", "web.io"]


class RandomText(_RandomBase):
    """(reference RandomText.scala:49 — strings/emails/urls/phones/
    countries/states/cities/postal codes/ids/picklists)"""

    ftype = Text

    def __init__(self, kind: str = "strings",
                 domain: Optional[Sequence[str]] = None, min_len: int = 3,
                 max_len: int = 10, ftype: Type[FeatureType] = Text,
                 seed: int = 42, probability_of_empty: float = 0.0):
        self.kind = kind
        self.domain = list(domain) if domain is not None else None
        self.min_len, self.max_len = min_len, max_len
        self.ftype = ftype
        super().__init__(seed, probability_of_empty)

    @classmethod
    def strings(cls, min_len: int = 3, max_len: int = 10, seed: int = 42):
        return cls("strings", None, min_len, max_len, Text, seed)

    @classmethod
    def textareas(cls, min_len: int = 20, max_len: int = 60, seed: int = 42):
        return cls("strings", None, min_len, max_len, TextArea, seed)

    @classmethod
    def emails(cls, domain: Optional[str] = None, seed: int = 42):
        return cls("emails", [domain] if domain else _DOMAINS, 3, 10,
                   Email, seed)

    @classmethod
    def urls(cls, seed: int = 42):
        return cls("urls", _DOMAINS, 3, 10, URL, seed)

    @classmethod
    def phones(cls, seed: int = 42):
        return cls("phones", None, 10, 10, Text, seed)

    @classmethod
    def ids(cls, seed: int = 42):
        return cls("ids", None, 8, 12, ID, seed)

    @classmethod
    def countries(cls, seed: int = 42):
        return cls("pick", _COUNTRIES, 0, 0, Country, seed)

    @classmethod
    def states(cls, seed: int = 42):
        return cls("pick", _STATES, 0, 0, State, seed)

    @classmethod
    def cities(cls, seed: int = 42):
        return cls("pick", _CITIES, 0, 0, City, seed)

    @classmethod
    def streets(cls, seed: int = 42):
        return cls("streets", None, 0, 0, Street, seed)

    @classmethod
    def postal_codes(cls, seed: int = 42):
        return cls("postal", None, 5, 5, PostalCode, seed)

    @classmethod
    def picklists(cls, domain: Sequence[str], seed: int = 42):
        return cls("pick", domain, 0, 0, PickList, seed)

    @classmethod
    def comboboxes(cls, domain: Sequence[str], seed: int = 42):
        return cls("pick", domain, 0, 0, ComboBox, seed)

    def _rand_word(self) -> str:
        n = int(self._rng.integers(self.min_len, self.max_len + 1))
        letters = self._rng.choice(list(string.ascii_lowercase), n)
        return "".join(letters)

    def _value(self) -> str:
        r = self._rng
        if self.kind == "strings":
            return self._rand_word()
        if self.kind == "pick":
            return str(r.choice(self.domain))
        if self.kind == "emails":
            return f"{self._rand_word()}@{r.choice(self.domain)}"
        if self.kind == "urls":
            return f"https://{self._rand_word()}.{r.choice(self.domain)}"
        if self.kind == "phones":
            return "".join(str(d) for d in r.integers(0, 10, 10))
        if self.kind == "ids":
            return "".join(
                str(c) for c in r.choice(list(string.hexdigits[:16]), 10))
        if self.kind == "postal":
            return "".join(str(d) for d in r.integers(0, 10, 5))
        if self.kind == "streets":
            return f"{int(r.integers(1, 9999))} {self._rand_word()} St"
        raise ValueError(f"Unknown text kind {self.kind!r}")


class RandomList(_RandomBase):
    """(reference RandomList.scala)"""

    ftype = TextList

    def __init__(self, element_gen: _RandomBase, min_size: int = 0,
                 max_size: int = 5, ftype: Type[FeatureType] = TextList,
                 seed: int = 42, probability_of_empty: float = 0.0):
        self.element_gen = element_gen
        self.min_size, self.max_size = min_size, max_size
        self.ftype = ftype
        super().__init__(seed, probability_of_empty)

    def _value(self):
        n = int(self._rng.integers(self.min_size, self.max_size + 1))
        return [v.value for v in self.element_gen.take(n)]


class RandomSet(_RandomBase):
    """(reference RandomSet.scala — MultiPickList)"""

    ftype = MultiPickList

    def __init__(self, domain: Sequence[str], min_size: int = 0,
                 max_size: int = 3, seed: int = 42,
                 probability_of_empty: float = 0.0):
        self.domain = list(domain)
        self.min_size, self.max_size = min_size, max_size
        super().__init__(seed, probability_of_empty)

    def _value(self):
        n = int(self._rng.integers(self.min_size,
                                   min(self.max_size, len(self.domain)) + 1))
        return set(self._rng.choice(self.domain, n, replace=False).tolist())


class RandomMap(_RandomBase):
    """(reference RandomMap.scala) — values from an element generator under
    keys ``key_prefix{0..}``."""

    def __init__(self, element_gen: _RandomBase, ftype: Type[FeatureType],
                 key_prefix: str = "k", min_size: int = 1, max_size: int = 4,
                 seed: int = 42, probability_of_empty: float = 0.0):
        self.element_gen = element_gen
        self.ftype = ftype
        self.key_prefix = key_prefix
        self.min_size, self.max_size = min_size, max_size
        super().__init__(seed, probability_of_empty)

    def _value(self):
        n = int(self._rng.integers(self.min_size, self.max_size + 1))
        vals = self.element_gen.take(n)
        return {f"{self.key_prefix}{i}": v.value
                for i, v in enumerate(vals) if v.value is not None}


class RandomVector(_RandomBase):
    """(reference RandomVector.scala)"""

    ftype = OPVector

    def __init__(self, size: int, distribution: str = "normal",
                 seed: int = 42, probability_of_empty: float = 0.0):
        self.size = size
        self.distribution = distribution
        super().__init__(seed, probability_of_empty)

    def _value(self):
        if self.distribution == "normal":
            return self._rng.normal(size=self.size)
        return self._rng.uniform(size=self.size)


class RandomData:
    """Convenience: build a dict of named columns from generators
    (reference RandomData.scala:51)."""

    def __init__(self, seed: int = 42):
        self.seed = seed
        self._gens: Dict[str, _RandomBase] = {}

    def with_column(self, name: str, gen: _RandomBase) -> "RandomData":
        self._gens[name] = gen
        return self

    def columns(self, n: int) -> Dict[str, FeatureColumn]:
        return {name: gen.column(n) for name, gen in self._gens.items()}

    def records(self, n: int) -> List[Dict]:
        cols = {name: gen.take(n) for name, gen in self._gens.items()}
        return [{name: vals[i].value for name, vals in cols.items()}
                for i in range(n)]
