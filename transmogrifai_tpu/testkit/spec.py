"""Contract spec bases for stage tests.

TPU-native port of the reference contract specs
(features/src/main/scala/com/salesforce/op/test/{OpTransformerSpec.scala:51,
OpEstimatorSpec.scala:55, OpPipelineStageSpec.scala}): every stage test
inherits a battery asserting the three core invariants

1. **batch == row**: the columnar path (``transform_columns``) and the
   row-level serving path (``transform_value``) agree on every row,
2. **save/load round-trip**: serializing the (fitted) stage through the
   persistence layer and back yields identical outputs,
3. **params round-trip**: ``get_params`` reconstructs an equivalent stage.

Usage: subclass in a pytest file and implement ``build()``::

    class TestMyVectorizer(StageSpecBase):
        def build(self):
            f = FeatureBuilder.real("x").as_predictor()
            ds = Dataset({"x": FeatureColumn.from_values(Real, [...])})
            return MyVectorizer().set_input(f), ds
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

from ..features.columns import Dataset, FeatureColumn
from ..stages.base import Estimator, Model, PipelineStage, Transformer

__all__ = ["StageSpecBase"]


def _values_equal(a, b) -> bool:
    """Boxed FeatureType equality with float tolerance."""
    va = getattr(a, "value", a)
    vb = getattr(b, "value", b)
    if va is None or vb is None:
        return va is vb
    if isinstance(va, dict) and isinstance(vb, dict):
        return (set(va) == set(vb)
                and all(_values_equal(va[k], vb[k]) for k in va))
    try:
        aa = np.asarray(va, dtype=np.float64)
        bb = np.asarray(vb, dtype=np.float64)
        if aa.shape != bb.shape:
            return False
        return bool(np.allclose(aa, bb, equal_nan=True))
    except (TypeError, ValueError):
        return va == vb


class StageSpecBase:
    """Inherit + implement ``build`` to get the contract battery."""

    #: rows checked in the batch==row comparison (all if fewer)
    n_check_rows = 10

    def build(self) -> Tuple[PipelineStage, Dataset]:
        """Return (stage wired via set_input to features matching the
        dataset columns, dataset)."""
        raise NotImplementedError

    # -- helpers -----------------------------------------------------------
    def _fitted(self) -> Tuple[Transformer, Dataset]:
        stage, ds = self.build()
        if isinstance(stage, Estimator):
            model = stage.fit(ds)
            assert isinstance(model, Model), \
                f"{type(stage).__name__}.fit must return a Model"
            assert model.uid == stage.uid, \
                "fitted model must inherit the estimator uid"
        else:
            model = stage
        return model, ds

    def _input_cols(self, model, ds):
        return [ds[f.name] for f in model.input_features]

    # -- the battery -------------------------------------------------------
    def test_transforms(self):
        model, ds = self._fitted()
        out = model.transform_columns(self._input_cols(model, ds))
        assert isinstance(out, FeatureColumn)
        assert out.n_rows == ds.n_rows
        assert out.ftype is model.output_type or \
            issubclass(out.ftype, model.output_type)

    def test_batch_equals_row(self):
        """(reference OpTransformerSpec: DataFrame path == transformKeyValue
        path)"""
        model, ds = self._fitted()
        cols = self._input_cols(model, ds)
        batch = model.transform_columns(cols)
        n = min(self.n_check_rows, ds.n_rows)
        for i in range(n):
            row_vals = [c.boxed(i) for c in cols]
            row_out = model.transform_value(*row_vals)
            assert _values_equal(batch.boxed(i), row_out), (
                f"row {i}: batch={batch.boxed(i)!r} row={row_out!r}")

    def test_save_load_round_trip(self):
        """(reference OpTransformerSpec save/load assertion)"""
        from ..workflow.persistence import stage_from_json, stage_to_json
        model, ds = self._fitted()
        arrays: dict = {}
        doc = stage_to_json(model, arrays)
        model2 = stage_from_json(doc, arrays)
        assert type(model2) is type(model)
        assert model2.uid == model.uid
        model2.input_features = model.input_features
        model2._output_feature = getattr(model, "_output_feature", None)
        cols = self._input_cols(model, ds)
        out1 = model.transform_columns(cols)
        out2 = model2.transform_columns(cols)
        n = min(self.n_check_rows, ds.n_rows)
        for i in range(n):
            assert _values_equal(out1.boxed(i), out2.boxed(i)), (
                f"row {i} differs after save/load")

    def test_params_round_trip(self):
        stage, _ = self.build()
        params = stage.get_params()
        clone = type(stage)(**params)
        assert clone.get_params() == params
