"""Telemetry-driven autotuning (docs/autotuning.md).

A persisted :class:`~.model.CostModel` over the ProfileStore's
cross-run wall/compile/execute records, and a
:class:`~.policy.TuningPolicy` that turns its predictions into
:class:`~.policy.TuningDecision` records for serving (coalescer
target, bucket range, pre-warm set), search (racing eta/min_fidelity)
and prepare (fit placement seed/margin). ``tx tune`` inspects and
pins every decision; ``TX_TUNE=off`` or an empty store yields the
static defaults bitwise (tuning/registry.py owns those numbers).
"""
from .lattice import (LatticeChoice, bucket_for_lattice, choose_lattice,
                      default_lattice, normalize_lattice)
from .model import CostModel, CostEstimate
from .model_v2 import LEARNED, CostModelV2
from .policy import TuningDecision, TuningPolicy, tuning_enabled
from .registry import KNOBS, STATIC_DEFAULTS, static_default

__all__ = ["CostModel", "CostModelV2", "CostEstimate", "LEARNED",
           "LatticeChoice", "bucket_for_lattice", "choose_lattice",
           "default_lattice", "normalize_lattice", "TuningDecision",
           "TuningPolicy", "tuning_enabled", "KNOBS",
           "STATIC_DEFAULTS", "static_default"]
