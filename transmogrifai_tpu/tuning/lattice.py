"""Bucket lattices: the padded-batch shape sets plans dispatch on.

The default lattice is the power-of-two ladder (8, 16, ... 8192): a 65-
row batch pays 128 padded rows. This module makes the lattice a
DECISION instead of a constant — :func:`choose_lattice` takes the
recorded occupancy histogram (real rows per dispatch,
``plans/common.py row_histogram`` persisted by the ProfileStore) times
the cost model's predicted per-bucket dispatch/compile cost and emits a
non-power-of-two lattice (monotone, deduplicated, bounded at
``tuning.lattice_max_rungs`` rungs, deterministic) where traffic
warrants, via an exact interval-partition dynamic program.

Contract invariants:

- the TOP rung is always ``max_bucket`` — batches beyond it chunk by
  the top rung exactly as before, and the AOT artifact subset-coverage
  check keeps working unchanged (ladder = the chosen lattice),
- a tuned lattice is only returned when its predicted cost is STRICTLY
  below the default power-of-two ladder's on the same histogram —
  empty occupancy (cold start) always yields the default ladder,
- everything is pure arithmetic over the inputs: same store, same
  lattice, bitwise.

This module is a LEAF like tuning/registry.py: stdlib only, importable
from ``plans/common.py`` at module scope. It is (with plans/common.py)
one of the two files where hand-rolled power-of-two bucket math is
allowed — lint rule TX-T02 flags ``1 <<`` / ``2 **`` / ``*= 2`` row
math anywhere else in the dispatch layers.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .registry import STATIC_DEFAULTS as _TUNABLES

__all__ = ["DEFAULT_LATTICE_MAX_RUNGS", "LatticeChoice",
           "default_lattice", "normalize_lattice", "bucket_for_lattice",
           "grow_pow2", "floor_pow2", "lattice_cost", "choose_lattice"]

#: rung bound for tuned lattices (the default 8..8192 ladder has 11)
DEFAULT_LATTICE_MAX_RUNGS = int(_TUNABLES["tuning.lattice_max_rungs"])


def default_lattice(min_bucket: Optional[int] = None,
                    max_bucket: Optional[int] = None) -> Tuple[int, ...]:
    """The power-of-two ladder: doubles from ``min_bucket``, capped by
    a final ``max_bucket`` rung (non-power-of-two caps clamp, exactly
    the historical ``bucket_for`` behavior)."""
    lo = int(_TUNABLES["serving.min_bucket"]
             if min_bucket is None else min_bucket)
    hi = int(_TUNABLES["serving.max_bucket"]
             if max_bucket is None else max_bucket)
    lo = max(lo, 1)
    hi = max(hi, lo)
    out: List[int] = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


def normalize_lattice(lattice: Sequence[int]) -> Tuple[int, ...]:
    """Sorted, deduplicated, positive rungs — the canonical lattice
    form every consumer (plans, artifacts, audit) stores."""
    rungs = sorted({int(b) for b in lattice if int(b) >= 1})
    if not rungs:
        raise ValueError("a bucket lattice needs at least one rung >= 1")
    return tuple(rungs)


def bucket_for_lattice(n: int, lattice: Sequence[int]) -> int:
    """Smallest rung >= n; n beyond the top rung returns the top rung —
    the caller's cue to chunk (same contract as ``bucket_for``)."""
    top = lattice[0]
    for b in lattice:
        top = b
        if b >= n:
            return b
    return top


def grow_pow2(start: int, bound: int) -> int:
    """Smallest ``start * 2**k >= bound`` (k >= 0) — the ladder-growth
    primitive ``TuningPolicy.bucket_range`` used to hand-roll."""
    b = max(int(start), 1)
    while b < bound:
        b *= 2
    return b


def floor_pow2(x: float) -> int:
    """Largest power of two <= x (minimum 1) — the admission-bound
    sizing primitive."""
    if x < 2:
        return 1
    return 1 << (int(x).bit_length() - 1)


@dataclass(frozen=True)
class LatticeChoice:
    """The chooser's verdict: the lattice to use plus the predicted
    cost (seconds when a cost model backed the choice, padded rows
    under the linear proxy) both ways."""
    lattice: Tuple[int, ...]
    default: Tuple[int, ...]
    predicted_cost: float
    predicted_default_cost: float
    modeled: bool              # True: costs are model seconds
    reason: str

    def tuned(self) -> bool:
        return self.lattice != self.default

    def to_json(self) -> dict:
        return {"lattice": list(self.lattice),
                "default": list(self.default),
                "predictedCost": round(float(self.predicted_cost), 6),
                "predictedDefaultCost":
                    round(float(self.predicted_default_cost), 6),
                "modeled": self.modeled, "tuned": self.tuned(),
                "reason": self.reason}


def _fold_occupancy(occupancy: Dict[int, int],
                    max_bucket: int) -> Dict[int, int]:
    """Clamp the recorded rows-per-dispatch histogram onto the bucket
    range: sizes beyond ``max_bucket`` chunk (full top-rung pieces plus
    the remainder), sizes below 1 drop."""
    out: Dict[int, int] = {}
    for size, count in occupancy.items():
        s, c = int(size), int(count)
        if s < 1 or c < 1:
            continue
        if s > max_bucket:
            full, rem = divmod(s, max_bucket)
            out[max_bucket] = out.get(max_bucket, 0) + full * c
            if rem:
                out[rem] = out.get(rem, 0) + c
        else:
            out[s] = out.get(s, 0) + c
    return out


def lattice_cost(lattice: Sequence[int], occupancy: Dict[int, int],
                 exec_cost: Callable[[int], float],
                 compile_cost: Callable[[int], float]) -> float:
    """Predicted steady-state cost of serving ``occupancy`` on
    ``lattice``: per-dispatch execute at each size's rung, plus one
    compile per rung that actually serves traffic."""
    used: Dict[int, int] = {}
    total = 0.0
    for size, count in sorted(occupancy.items()):
        rung = bucket_for_lattice(size, lattice)
        used[rung] = used.get(rung, 0) + count
        total += count * float(exec_cost(rung))
    for rung in used:
        total += float(compile_cost(rung))
    return total


def choose_lattice(occupancy: Dict[int, int],
                   min_bucket: Optional[int] = None,
                   max_bucket: Optional[int] = None,
                   max_rungs: Optional[int] = None,
                   exec_cost: Optional[Callable[[int],
                                                Optional[float]]] = None,
                   compile_cost: Optional[Callable[[int],
                                                   Optional[float]]] = None
                   ) -> LatticeChoice:
    """Pick the bucket lattice for a plan from its recorded occupancy
    histogram and the cost model's per-bucket predictions.

    Candidate rungs are the observed (clamped) dispatch sizes — with a
    cost monotone in the padded row count, an optimal rung always sits
    exactly on an observed size — plus the forced ``max_bucket`` top
    rung. An interval-partition DP picks <= ``max_rungs`` rungs
    minimizing

        sum_sizes count(s) * exec_cost(rung(s))
        + sum_{rungs serving traffic} compile_cost(rung)

    When the model has no basis (``exec_cost=None``) the proxy is
    padded rows (``exec_cost = rung``, ``compile_cost = 0``) — i.e.
    minimize padding waste outright. The tuned lattice is returned only
    when strictly cheaper than the default power-of-two ladder under
    the SAME objective; otherwise (and on an empty histogram) the
    default ladder comes back unchanged."""
    lo = int(_TUNABLES["serving.min_bucket"]
             if min_bucket is None else min_bucket)
    hi = int(_TUNABLES["serving.max_bucket"]
             if max_bucket is None else max_bucket)
    lo = max(lo, 1)
    hi = max(hi, lo)
    cap = DEFAULT_LATTICE_MAX_RUNGS if max_rungs is None \
        else max(int(max_rungs), 1)
    dflt = default_lattice(lo, hi)

    occ = _fold_occupancy(occupancy or {}, hi)
    if not occ:
        return LatticeChoice(dflt, dflt, 0.0, 0.0, False,
                             "no recorded occupancy — default "
                             "power-of-two ladder")

    modeled = exec_cost is not None

    def _exec(b: int) -> float:
        if exec_cost is not None:
            v = exec_cost(b)
            if v is not None:
                return max(float(v), 0.0)
        return float(b)          # linear padded-rows proxy

    def _comp(b: int) -> float:
        if compile_cost is not None:
            v = compile_cost(b)
            if v is not None:
                return max(float(v), 0.0)
        return 0.0

    # candidate rungs: observed sizes clamped to >= min_bucket, plus
    # the forced top rung
    cands = sorted({max(min(s, hi), lo) for s in occ} | {hi})
    # per-candidate demand: every observed size maps to the smallest
    # candidate >= it (clamped sizes land exactly on a candidate)
    weight = [0] * len(cands)
    for size, count in occ.items():
        idx = next(i for i, c in enumerate(cands)
                   if c >= min(max(size, lo), hi))
        weight[idx] += count

    k = len(cands)
    inf = float("inf")
    # f[m][i]: min cost covering candidates 0..i with m rungs, rung m-1
    # at cands[i]; sizes between chosen rungs pay the NEXT rung up.
    exec_at = [_exec(c) for c in cands]
    comp_at = [_comp(c) for c in cands]
    prefix = [0] * (k + 1)
    for i in range(k):
        prefix[i + 1] = prefix[i] + weight[i]
    f = [[inf] * k for _ in range(min(cap, k) + 1)]
    parent: Dict[Tuple[int, int], int] = {}
    for i in range(k):
        f[1][i] = (prefix[i + 1] - prefix[0]) * exec_at[i] \
            + (comp_at[i] if prefix[i + 1] - prefix[0] else 0.0)
    for m in range(2, min(cap, k) + 1):
        for i in range(m - 1, k):
            for j in range(m - 2, i):
                if f[m - 1][j] == inf:
                    continue
                served = prefix[i + 1] - prefix[j + 1]
                cost = f[m - 1][j] + served * exec_at[i] \
                    + (comp_at[i] if served else 0.0)
                if cost < f[m][i]:
                    f[m][i] = cost
                    parent[(m, i)] = j
    best_m, best_cost = 0, inf
    for m in range(1, min(cap, k) + 1):
        if f[m][k - 1] < best_cost:
            best_m, best_cost = m, f[m][k - 1]
    rungs: List[int] = []
    m, i = best_m, k - 1
    while m >= 1:
        rungs.append(cands[i])
        i = parent.get((m, i), -1)
        m -= 1
    chosen = normalize_lattice(rungs)
    if chosen[-1] != hi:                 # top rung is structural
        chosen = normalize_lattice(chosen + (hi,))

    dflt_cost = lattice_cost(dflt, occ, _exec, _comp)
    tuned_cost = lattice_cost(chosen, occ, _exec, _comp)
    if chosen == dflt or not tuned_cost < dflt_cost:
        return LatticeChoice(
            dflt, dflt, dflt_cost, dflt_cost, modeled,
            "default power-of-two ladder already cost-optimal for the "
            "recorded occupancy")
    unit = "s predicted" if modeled else " padded rows"
    return LatticeChoice(
        chosen, dflt, tuned_cost, dflt_cost, modeled,
        f"{len(chosen)}-rung lattice from {len(occ)} recorded dispatch "
        f"shapes: {tuned_cost:.6g}{unit} vs {dflt_cost:.6g}{unit} on "
        f"the power-of-two ladder")
