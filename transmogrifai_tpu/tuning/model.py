"""The persisted cost model: recorded lookup + log-space interpolation
over the ProfileStore.

The store (observability/store.py -> ``BENCH_STATE.json`` ``profiles``)
accumulates per-(stage, family, bucket) wall/compile/execute seconds
across runs. :class:`CostModel` snapshots those records once at
construction and answers ``predict(key, bucket)`` with PER-CALL cost
estimates plus a confidence tag:

- ``recorded``     — the exact key exists with calls > 0; the estimate
                     is its measured mean,
- ``interpolated`` — no exact record, but sibling bucket records exist
                     for the same namespace: costs are interpolated
                     linearly in (log2 bucket, log seconds) space —
                     dispatch cost is close to power-law in the padded
                     row count, so log-log is where it is straightest
                     (the recorded-lookup seed of PAPERS.md "A Learned
                     Performance Model for TPUs"),
- ``default``      — the store knows nothing; the caller must fall
                     back to its static default (tuning/registry.py).

The model is a pure reader: it never writes the store and never
touches a device.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ..observability.store import ProfileStore

__all__ = ["CostModel", "CostEstimate",
           "RECORDED", "INTERPOLATED", "DEFAULT"]

RECORDED = "recorded"
INTERPOLATED = "interpolated"
DEFAULT = "default"

#: guards log() against exact-zero recorded costs
_EPS = 1e-9


@dataclass(frozen=True)
class CostEstimate:
    """Per-call cost prediction for one key (seconds)."""
    key: str
    wall: Optional[float]
    compile: Optional[float]
    execute: Optional[float]
    confidence: str            # recorded | interpolated | default
    calls: int = 0             # recorded calls backing the estimate

    def known(self) -> bool:
        return self.confidence != DEFAULT

    def to_json(self) -> dict:
        rnd = (lambda v: None if v is None else round(float(v), 6))
        return {"key": self.key, "wall": rnd(self.wall),
                "compile": rnd(self.compile),
                "execute": rnd(self.execute),
                "confidence": self.confidence, "calls": self.calls}


def _per_call(rec: dict) -> Optional[Tuple[float, float, float, int]]:
    calls = int(rec.get("calls", 0) or 0)
    if calls < 1:
        return None
    return (float(rec.get("wall_seconds", 0.0)) / calls,
            float(rec.get("compile_seconds", 0.0)) / calls,
            float(rec.get("execute_seconds", 0.0)) / calls,
            calls)


class CostModel:
    """Snapshot of the profile store, queryable by key or by
    (namespace, bucket)."""

    def __init__(self, profiles: Dict[str, dict]):
        self.records = {k: dict(v) for k, v in (profiles or {}).items()
                        if not k.startswith("_")}

    @classmethod
    def from_store(cls, path: Optional[str] = None) -> "CostModel":
        return cls(ProfileStore(path).profiles())

    def __len__(self) -> int:
        return len(self.records)

    # -- exact lookup ------------------------------------------------------
    def predict(self, key: str,
                bucket: Optional[int] = None) -> CostEstimate:
        """Per-call cost for ``key`` — or, with ``bucket``, for the
        bucketed key ``{key}:b{bucket}`` with interpolation across the
        namespace's recorded buckets when the exact one is missing."""
        if bucket is not None:
            return self._predict_bucket(key, int(bucket))
        rec = self.records.get(key)
        got = _per_call(rec) if rec else None
        if got is None:
            return CostEstimate(key, None, None, None, DEFAULT)
        wall, comp, execute, calls = got
        return CostEstimate(key, wall, comp, execute, RECORDED, calls)

    # -- bucketed lookup + log-space interpolation -------------------------
    def recorded_buckets(self, namespace: str = "score"
                         ) -> Dict[int, CostEstimate]:
        """Every recorded ``{namespace}:b<bucket>`` key with calls,
        as per-call estimates keyed by the integer bucket size."""
        prefix = f"{namespace}:b"
        out: Dict[int, CostEstimate] = {}
        for key, rec in self.records.items():
            if not key.startswith(prefix):
                continue
            tail = key[len(prefix):]
            if not tail.isdigit():
                continue
            got = _per_call(rec)
            if got is None:
                continue
            wall, comp, execute, calls = got
            out[int(tail)] = CostEstimate(key, wall, comp, execute,
                                          RECORDED, calls)
        return out

    def _predict_bucket(self, namespace: str, bucket: int
                        ) -> CostEstimate:
        key = f"{namespace}:b{bucket}"
        known = self.recorded_buckets(namespace)
        if bucket in known:
            return known[bucket]
        if not known:
            return CostEstimate(key, None, None, None, DEFAULT)
        pts = sorted(known.items())

        def interp(field: str) -> float:
            xs = [math.log2(b) for b, _ in pts]
            ys = [math.log(max(getattr(e, field), _EPS))
                  for _, e in pts]
            x = math.log2(max(bucket, 1))
            if len(xs) == 1:
                # one point: nearest-neighbor — no slope to fit
                return math.exp(ys[0])
            if x <= xs[0]:
                i = 0
            elif x >= xs[-1]:
                i = len(xs) - 2
            else:
                i = max(j for j in range(len(xs) - 1) if xs[j] <= x)
            t = (x - xs[i]) / (xs[i + 1] - xs[i])
            return math.exp(ys[i] + t * (ys[i + 1] - ys[i]))

        return CostEstimate(key, interp("wall"), interp("compile"),
                            interp("execute"), INTERPOLATED)

    # -- aggregates the policy consumes ------------------------------------
    def family_totals(self) -> Optional[CostEstimate]:
        """Mean per-call (one full-CV family dispatch) cost across
        every recorded ``family:*`` key — the compile-vs-execute split
        the racing-schedule decision keys on."""
        wall = comp = execute = 0.0
        calls = 0
        for key, rec in self.records.items():
            if not key.startswith("family:"):
                continue
            got = _per_call(rec)
            if got is None:
                continue
            wall += float(rec.get("wall_seconds", 0.0))
            comp += float(rec.get("compile_seconds", 0.0))
            execute += float(rec.get("execute_seconds", 0.0))
            calls += got[3]
        if calls < 1:
            return None
        return CostEstimate("family:*", wall / calls, comp / calls,
                            execute / calls, RECORDED, calls)

    def placement_records(self) -> Dict[Tuple[str, str], dict]:
        """Cross-run fit-placement records ``placement:<Class>:<where>``
        in the shape ``plans/placement.py`` accumulates process-locally
        ({seconds, compile, calls, rows}) — the seed for a fresh
        process's first decide_fit."""
        out: Dict[Tuple[str, str], dict] = {}
        for key, rec in self.records.items():
            parts = key.split(":")
            if len(parts) != 3 or parts[0] != "placement" \
                    or parts[2] not in ("host", "device"):
                continue
            if int(rec.get("calls", 0) or 0) < 1:
                continue
            out[(parts[1], parts[2])] = {
                "seconds": float(rec.get("wall_seconds", 0.0)),
                "compile": float(rec.get("compile_seconds", 0.0)),
                "calls": int(rec.get("calls", 0)),
                "rows": int(rec.get("rows", 0)),
            }
        return out
