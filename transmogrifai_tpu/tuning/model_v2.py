"""Learned cost model v2: a closed-form ridge fit in log space over
the ProfileStore's cost records × the plan auditor's IR features.

The v1 model (tuning/model.py) interpolates recorded per-bucket costs
in (log2 bucket, log seconds) space — it knows nothing about WHY a
bucket costs what it does. PR 16's ``tx audit`` left the explanatory
features on the same store rows (``ir``: op counts, fusion counts,
constant/parameter/output bytes, per lowered bucket program), and "A
Learned Performance Model for TPUs" (PAPERS.md) is the blueprint for
using them: regress log per-call cost on the program features plus the
bucket shape and recorded padding waste.

No SGD, no new deps: the fit is the closed-form ridge solution
``w = (XᵀX + λI)⁻¹ XᵀY`` over a handful of rows — deterministic for a
given store snapshot. The prediction ladder per (namespace, bucket):

- ``recorded``     — exact record exists: measured mean (unchanged),
- ``learned``      — the ridge fit is trained (>= 4 feature-complete
                     records) and confident (mean absolute log-space
                     training residual <= 0.35, i.e. ~40% relative):
                     features for the unseen bucket are synthesized
                     from the nearest recorded bucket with the
                     row-proportional byte features rescaled,
- ``interpolated`` — below the confidence floor: the v1 table,
- ``default``      — empty namespace: caller falls back to statics.

:func:`CostModelV2.prediction_error_report` computes the
leave-one-out error of each tier against the recorded truths — the
per-tier drift block every bench run persists into BENCH_STATE.json.
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from .model import (DEFAULT, INTERPOLATED, RECORDED, CostEstimate,
                    CostModel, _per_call)

__all__ = ["CostModelV2", "LEARNED", "prediction_error_report"]

LEARNED = "learned"

#: ridge regularizer — small enough not to bias the tiny fits, big
#: enough to keep near-collinear feature columns solvable
_RIDGE_LAMBDA = 1e-3
#: minimum feature-complete records before the fit activates
_MIN_TRAIN_RECORDS = 4
#: confidence floor: mean |log-residual| above this (≈40% relative
#: error on the training rows) falls back to the v1 interpolation
_RESIDUAL_FLOOR = 0.35

_EPS = 1e-9
_BUCKET_KEY = re.compile(r"^(?P<ns>.+):b(?P<bucket>\d+)$")

#: IR feature fields that scale with the padded row count (parameter
#: and output buffers are row-major over the batch axis); op/fusion
#: counts and constants are shape-independent facts about the program
_ROW_SCALED = ("parameter_bytes", "output_bytes")
_COPIED = ("ops", "fusions", "constant_bytes")


def _feature_row(bucket: int, ir: Dict[str, float],
                 waste: float) -> List[float]:
    """[1, log2 bucket, log1p ops, log1p fusions, log1p param bytes,
    log1p const bytes, log1p output bytes, log waste] — log space
    end-to-end so the power-law cost surface is near-linear."""
    return [1.0,
            math.log2(max(int(bucket), 1)),
            math.log1p(max(float(ir.get("ops", 0) or 0), 0.0)),
            math.log1p(max(float(ir.get("fusions", 0) or 0), 0.0)),
            math.log1p(max(float(ir.get("parameter_bytes", 0) or 0),
                           0.0)),
            math.log1p(max(float(ir.get("constant_bytes", 0) or 0),
                           0.0)),
            math.log1p(max(float(ir.get("output_bytes", 0) or 0), 0.0)),
            math.log(max(float(waste), 1.0))]


def _record_waste(bucket: int, rec: dict) -> float:
    """Recorded padding waste of one row: padded/real rows (1.0 when
    the store has no row accounting for the key)."""
    calls = int(rec.get("calls", 0) or 0)
    rows = int(rec.get("rows", 0) or 0)
    if calls < 1 or rows < 1:
        return 1.0
    return max(float(bucket) * calls / rows, 1.0)


class _Fit:
    """One namespace's trained ridge: weights + the recorded feature
    rows the unseen-bucket synthesis borrows from."""

    def __init__(self, weights: np.ndarray, residual: float,
                 by_bucket: Dict[int, Tuple[Dict[str, float], float]],
                 n: int):
        self.weights = weights          # (d, 3): wall, compile, execute
        self.residual = residual        # mean |log-residual| (execute)
        self.by_bucket = by_bucket      # bucket -> (ir, waste)
        self.n = n

    def confident(self) -> bool:
        return self.residual <= _RESIDUAL_FLOOR

    def predict(self, bucket: int) -> Tuple[float, float, float]:
        """Synthesize the unseen bucket's features from the nearest
        recorded bucket (row-proportional bytes rescaled), then apply
        the fit."""
        near = min(self.by_bucket,
                   key=lambda b: (abs(math.log2(max(bucket, 1))
                                      - math.log2(b)), b))
        ir, waste = self.by_bucket[near]
        scale = float(bucket) / float(near)
        synth = {f: ir.get(f, 0) for f in _COPIED}
        for f in _ROW_SCALED:
            synth[f] = float(ir.get(f, 0) or 0) * scale
        x = np.asarray(_feature_row(bucket, synth, waste))
        wall, comp, execute = (float(math.exp(v))
                               for v in x @ self.weights)
        return wall, comp, execute


class CostModelV2(CostModel):
    """The v1 snapshot reader plus the learned tier. Drop-in: every v1
    query keeps its answer for recorded keys; only the *unrecorded*
    bucket predictions upgrade from interpolation to the ridge fit
    (and only above the confidence floor)."""

    def __init__(self, profiles: Dict[str, dict]):
        super().__init__(profiles)
        self._fits: Dict[str, Optional[_Fit]] = {}

    # -- training ----------------------------------------------------------
    def _training_rows(self, namespace: str
                       ) -> List[Tuple[int, dict, dict]]:
        """(bucket, record, ir) for every feature-complete record of
        the namespace: measured calls AND audited IR features."""
        prefix = f"{namespace}:b"
        rows = []
        for key, rec in self.records.items():
            if not key.startswith(prefix):
                continue
            tail = key[len(prefix):]
            if not tail.isdigit():
                continue
            ir = rec.get("ir")
            if not isinstance(ir, dict) or _per_call(rec) is None:
                continue
            rows.append((int(tail), rec, ir))
        rows.sort(key=lambda r: r[0])
        return rows

    def fit_for(self, namespace: str) -> Optional[_Fit]:
        """Train (once per snapshot) the namespace's ridge; None below
        the record floor — the caller falls back to v1."""
        if namespace in self._fits:
            return self._fits[namespace]
        rows = self._training_rows(namespace)
        fit: Optional[_Fit] = None
        if len(rows) >= _MIN_TRAIN_RECORDS:
            X, Y, by_bucket = [], [], {}
            for bucket, rec, ir in rows:
                wall, comp, execute, _calls = _per_call(rec)
                waste = _record_waste(bucket, rec)
                X.append(_feature_row(bucket, ir, waste))
                Y.append([math.log(max(wall, _EPS)),
                          math.log(max(comp, _EPS)),
                          math.log(max(execute, _EPS))])
                by_bucket[bucket] = (dict(ir), waste)
            Xm = np.asarray(X, dtype=np.float64)
            Ym = np.asarray(Y, dtype=np.float64)
            d = Xm.shape[1]
            w = np.linalg.solve(Xm.T @ Xm + _RIDGE_LAMBDA * np.eye(d),
                                Xm.T @ Ym)
            resid = float(np.mean(np.abs(Xm @ w - Ym)[:, 2]))
            fit = _Fit(w, resid, by_bucket, len(rows))
        self._fits[namespace] = fit
        return fit

    # -- prediction (overrides the v1 bucket path) -------------------------
    def _predict_bucket(self, namespace: str, bucket: int
                        ) -> CostEstimate:
        known = self.recorded_buckets(namespace)
        if bucket in known:
            return known[bucket]
        fit = self.fit_for(namespace)
        if fit is not None and fit.confident():
            wall, comp, execute = fit.predict(int(bucket))
            return CostEstimate(f"{namespace}:b{bucket}", wall, comp,
                                execute, LEARNED)
        return super()._predict_bucket(namespace, bucket)

    def learned_namespaces(self) -> Dict[str, dict]:
        """Fit diagnostics per namespace that trained (tx tune
        --explain / bench surfaces)."""
        out: Dict[str, dict] = {}
        for ns in sorted({m.group("ns")
                          for m in (_BUCKET_KEY.match(k)
                                    for k in self.records)
                          if m}):
            fit = self.fit_for(ns)
            if fit is not None:
                out[ns] = {"records": fit.n,
                           "residual": round(fit.residual, 6),
                           "confident": fit.confident()}
        return out

    # -- drift accounting (the per-tier error block) -----------------------
    def prediction_error_report(self) -> dict:
        """Leave-one-out prediction error per confidence tier against
        the recorded per-call execute truths.

        Each recorded ``<ns>:b<bucket>`` row is held out in turn; a
        model built from the REMAINING rows predicts it through the v2
        ladder (error lands on whichever tier answered — learned,
        interpolated, or default when nothing else is known) and
        through the v1 interpolation alone (error lands on
        ``interpolated``), so every tier's drift is populated from the
        same truths. ``recorded`` is exact by construction (count =
        recorded rows, error 0)."""
        by_ns: Dict[str, Dict[int, dict]] = {}
        for key, rec in self.records.items():
            m = _BUCKET_KEY.match(key)
            if not m or _per_call(rec) is None:
                continue
            by_ns.setdefault(m.group("ns"), {})[
                int(m.group("bucket"))] = rec

        tiers: Dict[str, List[float]] = {RECORDED: [], INTERPOLATED: [],
                                         LEARNED: [], DEFAULT: []}
        for ns, buckets in sorted(by_ns.items()):
            for bucket, rec in sorted(buckets.items()):
                truth = _per_call(rec)[2]       # per-call execute
                tiers[RECORDED].append(0.0)
                rest = {k: v for k, v in self.records.items()
                        if k != f"{ns}:b{bucket}"}
                loo2 = CostModelV2(rest)
                loo1 = CostModel(rest)
                for model, pin in ((loo2, None), (loo1, INTERPOLATED)):
                    est = model.predict(ns, bucket=bucket)
                    tier = pin or est.confidence
                    if est.execute is None:
                        if pin is None:
                            tiers[DEFAULT].append(float("nan"))
                        continue
                    err = abs(est.execute - truth) / max(truth, _EPS)
                    tiers[tier].append(err)

        def _agg(errs: List[float]) -> dict:
            real = [e for e in errs if not math.isnan(e)]
            return {"count": len(errs),
                    "mean_abs_rel_err":
                        round(sum(real) / len(real), 6) if real
                        else None,
                    "max_abs_rel_err":
                        round(max(real), 6) if real else None}

        return {"schema": 1,
                "tiers": {t: _agg(v) for t, v in tiers.items()},
                "learned": self.learned_namespaces()}


def prediction_error_report(path: Optional[str] = None) -> dict:
    """Convenience: the per-tier LOO error block for one store."""
    return CostModelV2.from_store(path).prediction_error_report()
