"""TuningPolicy: turn cost-model predictions into knob decisions.

Every decision flows through one :class:`TuningDecision` record —
knob, chosen value, static default, predicted cost both ways,
confidence, source — consumed by three layers:

- **serving** (serving/server.py): the coalescer target when a plan has
  no local bucket profile yet, the ScoringPlan bucket range, and the
  pre-warm set compiled before traffic,
- **search** (selector/racing.py): the racing ``eta``/``min_fidelity``
  schedule, chosen so the rung ladder amortizes the recorded
  compile-vs-execute split (the final rung stays exact full CV — the
  exactness contract is structural, not a tuning outcome),
- **prepare** (plans/placement.py): the host-vs-device seed records and
  comparison margin, so a fresh process places its FIRST fit from
  cross-run history.

Cold-start safety is the contract: with an empty/absent store every
decision is bitwise the static default (``source="default"``), and
``TX_TUNE=off`` disables the whole layer (``source="disabled"``).
Operators inspect and pin decisions with ``tx tune`` (cli/tune.py);
pinned values live in the store's ``tuning.overrides`` block and win
over the model (``source="override"``).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..observability.store import ProfileStore, default_store_path
from .lattice import (choose_lattice, default_lattice, floor_pow2,
                      grow_pow2)
from .model import DEFAULT, CostModel
from .model_v2 import CostModelV2
from .registry import STATIC_DEFAULTS, knob as _knob_meta

__all__ = ["TuningDecision", "TuningPolicy", "tuning_enabled"]

_OFF_VALUES = ("off", "0", "false", "disabled", "no")

#: decision sources
SOURCE_MODEL = "model"
SOURCE_DEFAULT = "default"
SOURCE_OVERRIDE = "override"
SOURCE_DISABLED = "disabled"
SOURCE_CALLER = "caller"


def tuning_enabled() -> bool:
    """``TX_TUNE=off`` kills the whole autotuning layer."""
    return os.environ.get("TX_TUNE", "on").strip().lower() \
        not in _OFF_VALUES


@dataclass(frozen=True)
class TuningDecision:
    """One knob's resolution: what was chosen, what the static default
    would have been, and why."""
    knob: str
    chosen: Any
    default: Any
    #: model's cost estimate (seconds) under the chosen value / under
    #: the static default — None when the model has no basis
    predicted_chosen: Optional[float]
    predicted_default: Optional[float]
    confidence: str            # recorded | interpolated | default
    source: str                # model | default | override | disabled
    reason: str

    def tuned(self) -> bool:
        return self.chosen != self.default \
            and self.source in (SOURCE_MODEL, SOURCE_OVERRIDE)

    def to_json(self) -> dict:
        rnd = (lambda v: None if v is None else round(float(v), 6))
        chosen = (list(self.chosen)
                  if isinstance(self.chosen, tuple) else self.chosen)
        default = (list(self.default)
                   if isinstance(self.default, tuple) else self.default)
        return {"knob": self.knob, "chosen": chosen, "default": default,
                "predictedChosen": rnd(self.predicted_chosen),
                "predictedDefault": rnd(self.predicted_default),
                "confidence": self.confidence, "source": self.source,
                "tuned": self.tuned(), "reason": self.reason}


def _coerce(knob_name: str, value: Any) -> Any:
    """Normalize a persisted/CLI override to the knob's declared
    kind (overrides round-trip through JSON and argv strings)."""
    meta = _knob_meta(knob_name)
    kind = meta.kind if meta else "float"
    if kind == "int":
        return int(value)
    if kind == "float":
        return None if value is None else float(value)
    if kind == "str":
        return None if value is None else str(value)
    if kind == "int_tuple":
        if isinstance(value, str):
            value = [v for v in value.split(",") if v.strip()]
        return tuple(int(v) for v in value)
    return value


class TuningPolicy:
    """One store snapshot's worth of decisions. Construction reads the
    store once; consumers build a policy per long-lived object (server,
    validator, prepare plan) so a fresh process always honors freshly
    persisted overrides."""

    def __init__(self, path: Optional[str] = None,
                 enabled: Optional[bool] = None,
                 model: Optional[CostModel] = None):
        self.path = path or default_store_path()
        self.enabled = tuning_enabled() if enabled is None else \
            bool(enabled)
        self.store = ProfileStore(self.path)
        if self.enabled:
            self.model = model or CostModelV2.from_store(self.path)
            self.overrides = self.store.tuning_overrides()
        else:
            self.model = CostModel({})
            self.overrides = {}

    # -- shared resolution skeleton ----------------------------------------
    def _static(self, knob_name: str, reason: str) -> TuningDecision:
        default = STATIC_DEFAULTS[knob_name]
        return TuningDecision(
            knob=knob_name, chosen=default, default=default,
            predicted_chosen=None, predicted_default=None,
            confidence=DEFAULT,
            source=SOURCE_DISABLED if not self.enabled
            else SOURCE_DEFAULT,
            reason="TX_TUNE=off — autotuning disabled"
            if not self.enabled else reason)

    def _override(self, knob_name: str) -> Optional[Any]:
        if self.enabled and knob_name in self.overrides:
            return _coerce(knob_name, self.overrides[knob_name])
        return None

    # -- serving -----------------------------------------------------------
    def target_batch(self, max_wait_ms: float,
                     max_batch: int) -> TuningDecision:
        """The coalescer target for a plan with NO local bucket profile:
        the largest bucket whose PREDICTED per-dispatch execute cost
        fits inside the wait budget — the cross-run twin of
        ``ServingServer._target_batch``'s process-local rule."""
        name = "serving.target_batch"
        default = STATIC_DEFAULTS[name]
        ov = self._override(name)
        budget_s = float(max_wait_ms) / 1000.0
        if ov is not None:
            est = self.model.predict("score", bucket=int(ov))
            dflt = self.model.predict("score", bucket=default)
            return TuningDecision(
                name, int(ov), default, est.execute, dflt.execute,
                est.confidence, SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        known = self.model.recorded_buckets("score") if self.enabled \
            else {}
        if not known:
            return self._static(
                name, "no score:b* records in the store yet")
        best, best_est = 0, None
        cap = max(int(max_batch), 1)
        for b in default_lattice(
                int(STATIC_DEFAULTS["serving.min_bucket"]), cap):
            if b > cap:
                continue
            est = self.model.predict("score", bucket=b)
            if est.known() and est.execute is not None \
                    and est.execute <= budget_s and b > best:
                best, best_est = b, est
        dflt_est = self.model.predict("score", bucket=default)
        if not best:
            return self._static(
                name, f"no bucket's predicted dispatch cost fits the "
                      f"{max_wait_ms}ms budget")
        return TuningDecision(
            name, best, default, best_est.execute, dflt_est.execute,
            best_est.confidence, SOURCE_MODEL,
            f"largest bucket with predicted per-dispatch execute "
            f"{best_est.execute * 1e3:.3f}ms <= max_wait_ms budget "
            f"{max_wait_ms}ms ({len(known)} recorded buckets)")

    def bucket_range(self, max_batch: Optional[int] = None
                     ) -> Tuple[TuningDecision, TuningDecision]:
        """(min_bucket, max_bucket) decisions: clamp the ScoringPlan's
        bucket ladder onto the shapes the store has actually seen, so
        a fresh process compiles profiled programs instead of the full
        static ladder."""
        lo_name, hi_name = "serving.min_bucket", "serving.max_bucket"
        lo_d = int(STATIC_DEFAULTS[lo_name])
        hi_d = int(STATIC_DEFAULTS[hi_name])
        lo_ov, hi_ov = self._override(lo_name), self._override(hi_name)
        known = self.model.recorded_buckets("score") if self.enabled \
            else {}
        if known:
            lo_m, hi_m = min(known), max(known)
            if max_batch is not None:
                hi_m = grow_pow2(hi_m, min(int(max_batch), hi_d))
            source, conf = SOURCE_MODEL, "recorded"
            reason = (f"recorded dispatch shapes span b{lo_m}..b{hi_m} "
                      f"({len(known)} buckets)")
        else:
            lo_m, hi_m = lo_d, hi_d
            source, conf = (SOURCE_DISABLED if not self.enabled
                            else SOURCE_DEFAULT), DEFAULT
            reason = ("TX_TUNE=off — autotuning disabled"
                      if not self.enabled
                      else "no score:b* records in the store yet")
        lo = int(lo_ov) if lo_ov is not None else lo_m
        hi = int(hi_ov) if hi_ov is not None else hi_m
        hi = max(hi, lo)
        mk = (lambda nm, chosen, ov, dflt: TuningDecision(
            nm, chosen, dflt, None, None,
            conf if ov is None else "recorded",
            SOURCE_OVERRIDE if ov is not None else source,
            f"pinned by tx tune --set (store {self.path})"
            if ov is not None else reason))
        return (mk(lo_name, lo, lo_ov, lo_d),
                mk(hi_name, hi, hi_ov, hi_d))

    def prewarm_buckets(self, max_batch: Optional[int] = None
                        ) -> TuningDecision:
        """Buckets to pre-compile BEFORE traffic: every recorded
        dispatch shape within the serve cap. Predicted cost both ways
        is the same compile bill — tuned pays it behind the readiness
        gate, static pays it inside the first requests' latency."""
        name = "serving.prewarm"
        default = STATIC_DEFAULTS[name]
        ov = self._override(name)
        if ov is not None:
            chosen = tuple(sorted(set(int(b) for b in ov)))
            comp = sum((self.model.predict("score", bucket=b).compile
                        or 0.0) for b in chosen)
            return TuningDecision(
                name, chosen, default, comp, comp, "recorded",
                SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        known = self.model.recorded_buckets("score") if self.enabled \
            else {}
        chosen = tuple(sorted(
            b for b in known
            if max_batch is None or b <= int(max_batch)))
        if not chosen:
            return self._static(
                name, "no score:b* records in the store yet")
        comp = sum((known[b].compile or 0.0) for b in chosen)
        return TuningDecision(
            name, chosen, default, comp, comp, "recorded",
            SOURCE_MODEL,
            f"pre-compiling {len(chosen)} recorded buckets moves a "
            f"predicted {comp:.2f}s compile bill out of first-request "
            f"latency")

    def admission_queue_rows(self, max_batch: int = 256
                             ) -> TuningDecision:
        """Per-lane admission bound (rows) for the overload controller
        (serving/admission.py): the largest power of two whose backlog
        drains within ~250ms at the store's recorded dispatch rate, so
        the shed edge engages where queue wait would start dominating
        the SLO instead of at an arbitrary depth."""
        name = "serving.admission_queue_rows"
        default = int(STATIC_DEFAULTS[name])
        ov = self._override(name)
        if ov is not None:
            return TuningDecision(
                name, int(ov), default, None, None, "recorded",
                SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        known = self.model.recorded_buckets("score") if self.enabled \
            else {}
        rates = [(b / max(e.execute or e.wall or 0.0, 1e-9), b)
                 for b, e in known.items()
                 if b <= max(int(max_batch), 1)
                 and (e.execute or e.wall)]
        if not rates:
            return self._static(
                name, "no score:b* records in the store yet")
        rate, _bucket = max(rates)
        budget_s = 0.25
        rows = floor_pow2(rate * budget_s)
        chosen = max(min(rows, 4 * default), int(max_batch))
        return TuningDecision(
            name, chosen, default, chosen / rate, default / rate,
            "recorded", SOURCE_MODEL,
            f"recorded drain rate ~{rate:.0f} rows/s: a {chosen}-row "
            f"backlog clears in {chosen / rate * 1e3:.0f}ms "
            f"(~{budget_s * 1e3:.0f}ms budget; {len(known)} recorded "
            f"buckets)")

    def admission_quantum(self) -> TuningDecision:
        """DRR quantum for the admission dispatch-grant ring
        (override-only: the model keeps the static granularity)."""
        name = "serving.admission_quantum"
        ov = self._override(name)
        if ov is not None:
            return TuningDecision(
                name, int(ov), STATIC_DEFAULTS[name], None, None,
                "recorded", SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        return self._static(
            name, "model keeps the static fairness granularity")

    def lattice_max_rungs(self) -> TuningDecision:
        """Rung bound for tuned bucket lattices (override-only: the
        bound is a compile-budget policy, like the waste ceiling)."""
        name = "tuning.lattice_max_rungs"
        ov = self._override(name)
        if ov is not None:
            return TuningDecision(
                name, int(ov), STATIC_DEFAULTS[name], None, None,
                "recorded", SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        return self._static(
            name, "rung bound is a compile-budget policy choice")

    def bucket_lattice(self, min_bucket: Optional[int] = None,
                       max_bucket: Optional[int] = None
                       ) -> TuningDecision:
        """THE padding decision: the bucket lattice ScoringPlans
        dispatch on, chosen by the recorded occupancy histogram ×
        predicted per-bucket cost (tuning/lattice.py). Cold start
        (no occupancy) or TX_TUNE=off keeps the default power-of-two
        ladder bitwise."""
        name = "serving.bucket_lattice"
        lo = int(STATIC_DEFAULTS["serving.min_bucket"]
                 if min_bucket is None else min_bucket)
        hi = int(STATIC_DEFAULTS["serving.max_bucket"]
                 if max_bucket is None else max_bucket)
        dflt = default_lattice(lo, hi)
        if not self.enabled:
            return TuningDecision(
                name, dflt, dflt, None, None, DEFAULT, SOURCE_DISABLED,
                "TX_TUNE=off — autotuning disabled")
        occ = self.store.occupancy("score")
        if not occ:
            return TuningDecision(
                name, dflt, dflt, None, None, DEFAULT, SOURCE_DEFAULT,
                "no recorded occupancy histogram yet")
        known = self.model.recorded_buckets("score")
        exec_cost = compile_cost = None
        if known:
            exec_cost = (lambda b:
                         self.model.predict("score", bucket=b).execute)
            compile_cost = (lambda b:
                            self.model.predict("score",
                                               bucket=b).compile)
        choice = choose_lattice(
            occ, min_bucket=lo, max_bucket=hi,
            max_rungs=int(self.lattice_max_rungs().chosen),
            exec_cost=exec_cost, compile_cost=compile_cost)
        if not choice.tuned():
            return TuningDecision(
                name, dflt, dflt, choice.predicted_cost,
                choice.predicted_default_cost,
                "recorded" if known else DEFAULT, SOURCE_DEFAULT,
                choice.reason)
        conf = (self.model.predict(
            "score", bucket=choice.lattice[0]).confidence
            if known else DEFAULT)
        return TuningDecision(
            name, choice.lattice, dflt, choice.predicted_cost,
            choice.predicted_default_cost, conf, SOURCE_MODEL,
            choice.reason)

    def coalesce_policy(self, caller: Optional[str] = None,
                        lattice_tuned: bool = False) -> TuningDecision:
        """How the serving coalescer closes a batch. The model only
        moves off the fixed deadline-or-full rule when a tuned lattice
        is active AND it has recorded dispatch costs to predict
        marginal cost from — cold start stays bitwise on the old
        rule."""
        name = "serving.coalesce_policy"
        default = STATIC_DEFAULTS[name]
        valid = ("deadline_or_full", "predicted_cost")
        ov = self._override(name)
        if ov is not None:
            if ov not in valid:
                return TuningDecision(
                    name, default, default, None, None, DEFAULT,
                    SOURCE_DEFAULT,
                    f"override {ov!r} is not one of {valid} — "
                    f"keeping the default rule")
            return TuningDecision(
                name, str(ov), default, None, None, "recorded",
                SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        if caller is not None:
            chosen = caller if caller in valid else default
            return TuningDecision(
                name, chosen, default, None, None, DEFAULT,
                SOURCE_CALLER,
                f"requested by the serve config"
                if caller in valid else
                f"config value {caller!r} is not one of {valid} — "
                f"keeping the default rule")
        if self.enabled and lattice_tuned \
                and self.model.recorded_buckets("score"):
            return TuningDecision(
                name, "predicted_cost", default, None, None,
                "recorded", SOURCE_MODEL,
                "tuned lattice active — split batches against the "
                "model's predicted per-row marginal cost")
        return self._static(
            name, "fixed deadline-or-full rule (no tuned lattice)")

    # -- search ------------------------------------------------------------
    def _schedule_cost(self, eta: int, mf: float,
                       compile_s: float, execute_s: float) -> float:
        """Predicted per-family search cost of one racing ladder:
        every rung compiles one program (~family compile cost) and
        executes its budget fraction over the ~1/eta**r survivors.
        Full exact CV is ``compile_s + execute_s`` on this scale."""
        budgets: List[float] = []
        b = float(mf)
        while b < 1.0 - 1e-12:
            budgets.append(b)
            b *= eta
        budgets.append(1.0)
        cost = 0.0
        for r, budget in enumerate(budgets):
            cost += compile_s + execute_s * budget * (eta ** -r)
        return cost

    def racing_schedule(self) -> Tuple[int, float, List[TuningDecision]]:
        """(eta, min_fidelity, [eta decision, min_fidelity decision]).

        The model picks the ladder minimizing predicted per-family
        search cost from the recorded compile-vs-execute split of
        ``family:*`` records: compile-dominated workloads get a
        SHALLOWER ladder (fewer rung programs to compile),
        execute-dominated ones a DEEPER ladder (cheaper screening
        rungs). The final rung is full CV in every candidate —
        exactness is structural."""
        eta_name, mf_name = "search.eta", "search.min_fidelity"
        eta_d = int(STATIC_DEFAULTS[eta_name])
        mf_d = 1.0 / (eta_d * eta_d)
        eta_ov, mf_ov = self._override(eta_name), self._override(mf_name)
        fam = self.model.family_totals() if self.enabled else None

        chosen_eta, chosen_mf = eta_d, mf_d
        source, conf = SOURCE_DEFAULT, DEFAULT
        pred_c = pred_d = None
        reason = "no family:* records in the store yet"
        if not self.enabled:
            source, reason = SOURCE_DISABLED, \
                "TX_TUNE=off — autotuning disabled"
        elif fam is not None:
            c, e = fam.compile or 0.0, fam.execute or 0.0
            cands = [(eta, 1.0 / eta ** depth)
                     for eta in (3, 4) for depth in (1, 2, 3)]
            scored = sorted(
                cands,
                key=lambda p: (round(self._schedule_cost(
                    p[0], p[1], c, e), 9),
                    (p[0], p[1]) != (eta_d, mf_d), p[0], -p[1]))
            chosen_eta, chosen_mf = scored[0]
            pred_c = self._schedule_cost(chosen_eta, chosen_mf, c, e)
            pred_d = self._schedule_cost(eta_d, mf_d, c, e)
            source, conf = SOURCE_MODEL, fam.confidence
            share = c / max(c + e, 1e-12)
            reason = (f"recorded family cost is {share:.0%} compile "
                      f"({fam.calls} calls): ladder minimizing "
                      f"predicted per-family search cost "
                      f"{pred_c:.2f}s (static {pred_d:.2f}s)")
        decisions = []
        for nm, chosen, ov, dflt in (
                (eta_name, chosen_eta, eta_ov, eta_d),
                (mf_name, chosen_mf, mf_ov,
                 STATIC_DEFAULTS[mf_name])):
            if ov is not None:
                decisions.append(TuningDecision(
                    nm, ov, dflt, pred_c, pred_d, conf,
                    SOURCE_OVERRIDE,
                    f"pinned by tx tune --set (store {self.path})"))
            else:
                shown = chosen if nm == eta_name else (
                    dflt if source in (SOURCE_DEFAULT, SOURCE_DISABLED)
                    else chosen)
                decisions.append(TuningDecision(
                    nm, shown, dflt, pred_c, pred_d, conf, source,
                    reason))
        eta = int(eta_ov) if eta_ov is not None else chosen_eta
        mf = float(mf_ov) if mf_ov is not None else chosen_mf
        if eta_ov is not None and mf_ov is None \
                and source in (SOURCE_DEFAULT, SOURCE_DISABLED):
            mf = 1.0 / (eta * eta)
        return eta, mf, decisions

    # -- prepare -----------------------------------------------------------
    def placement_margin(self) -> TuningDecision:
        """Host-vs-device comparison margin (override-only: the model
        keeps the plain 1.0 comparison)."""
        name = "prepare.placement_margin"
        ov = self._override(name)
        if ov is not None:
            return TuningDecision(
                name, float(ov), STATIC_DEFAULTS[name], None, None,
                "recorded", SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        return self._static(
            name, "model keeps the plain steady-state comparison")

    def placement_seed(self) -> Tuple[Dict[Tuple[str, str], dict],
                                      TuningDecision]:
        """Cross-run (stage class, placement) fit records to seed a
        fresh process's PlacementPolicy, plus the decision record."""
        name = "prepare.placement_seed"
        seeds = self.model.placement_records() if self.enabled else {}
        if not seeds:
            decision = TuningDecision(
                name, (), (), None, None, DEFAULT,
                SOURCE_DISABLED if not self.enabled else SOURCE_DEFAULT,
                "TX_TUNE=off — autotuning disabled"
                if not self.enabled
                else "no placement:* records in the store yet")
            return {}, decision
        labels = tuple(sorted(f"{cls}:{where}"
                              for cls, where in seeds))
        total = sum(r["seconds"] for r in seeds.values())
        decision = TuningDecision(
            name, labels, (), None, total, "recorded", SOURCE_MODEL,
            f"seeding {len(seeds)} cross-run fit records so the first "
            f"decide_fit is data-driven instead of optimistic-device")
        return seeds, decision

    # -- audit -------------------------------------------------------------
    def waste_ceiling(self) -> TuningDecision:
        """TX-P04 padding-waste bound (override-only: the tolerable
        padded-rows-per-real-row ratio is a policy choice, not
        something the cost model can learn from timings)."""
        name = "audit.waste_ceiling"
        ov = self._override(name)
        if ov is not None:
            return TuningDecision(
                name, float(ov), STATIC_DEFAULTS[name], None, None,
                "recorded", SOURCE_OVERRIDE,
                f"pinned by tx tune --set (store {self.path})")
        return self._static(
            name, "waste tolerance is a policy choice, not learnable")

    # -- the full decision table (tx tune, bench) --------------------------
    def decisions(self, max_wait_ms: float = 5.0,
                  max_batch: int = 256) -> List[TuningDecision]:
        """Every knob's resolution under the given serving context —
        the table ``tx tune`` renders and ``TX_BENCH_MODE=autotune``
        persists."""
        out = [self.target_batch(max_wait_ms, max_batch)]
        out.extend(self.bucket_range(max_batch))
        out.append(self.prewarm_buckets(max_batch))
        out.append(self.admission_queue_rows(max_batch))
        out.append(self.admission_quantum())
        lattice = self.bucket_lattice()
        out.append(lattice)
        out.append(self.coalesce_policy(lattice_tuned=lattice.tuned()))
        out.append(self.lattice_max_rungs())
        _eta, _mf, racing = self.racing_schedule()
        out.extend(racing)
        out.append(self.placement_margin())
        out.append(self.placement_seed()[1])
        out.append(self.waste_ceiling())
        return out
