"""The tunable-knob registry: the SINGLE home of every performance
default the autotuner may move.

Every knob that shapes a hot path — the serving coalescer target, the
ScoringPlan bucket range, the racing ``eta``/``min_fidelity`` schedule,
the host-vs-device placement margin — is declared HERE, once, with its
static default. Consumers import the default from
:data:`STATIC_DEFAULTS` instead of re-stating the number; lint rule
TX-T01 (lint/rules_jax.py) enforces that a numeric literal default for
a registered knob outside ``tuning/`` is an error, so a knob can never
fork into two disagreeing copies the :class:`~.policy.TuningPolicy`
doesn't know about.

This module is a LEAF: stdlib only, no jax, no observability imports —
``plans/common.py`` and ``serving/server.py`` import it at module
scope, and the lint rules import the registered-name sets.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

__all__ = ["Knob", "KNOBS", "STATIC_DEFAULTS", "static_default",
           "TUNABLE_CONST_NAMES", "TUNABLE_PARAM_NAMES",
           "TUNABLE_PARAM_SCOPES"]


@dataclass(frozen=True)
class Knob:
    """One registered tunable: its identity, static default and the
    layer that consumes the decision."""
    name: str
    default: Any
    consumer: str
    kind: str          # int | float | str | int_pair | int_tuple
    description: str
    #: the constant / parameter spellings TX-T01 polices for this knob
    const_names: Tuple[str, ...] = ()
    param_names: Tuple[str, ...] = ()


#: the registry — ordering is the ``tx tune`` display order
KNOBS: Tuple[Knob, ...] = (
    Knob(name="serving.target_batch", default=64,
         consumer="serving/server.py ServingServer._target_batch",
         kind="int",
         description="coalescer target batch when the plan has no "
                     "local bucket profile yet (deadline-or-full's "
                     "'full')",
         const_names=("_DEFAULT_TARGET", "DEFAULT_TARGET_BATCH"),
         param_names=()),
    Knob(name="serving.min_bucket", default=8,
         consumer="plans/common.py bucket_for / serving ScoringPlan",
         kind="int",
         description="smallest padded power-of-two batch — "
                     "single-record requests share one program",
         const_names=("DEFAULT_MIN_BUCKET",),
         param_names=()),
    Knob(name="serving.max_bucket", default=8192,
         consumer="plans/common.py bucket_for / serving ScoringPlan",
         kind="int",
         description="largest padded batch — bigger inputs chunk so "
                     "compiles stay bounded at log2(max/min)+1 "
                     "programs per plan",
         const_names=("DEFAULT_MAX_BUCKET",),
         param_names=()),
    Knob(name="serving.prewarm", default=(),
         consumer="serving/server.py ServingServer.prewarm",
         kind="int_tuple",
         description="bucket sizes pre-compiled before traffic — "
                     "empty means no prewarm (today's behavior); the "
                     "policy fills it from the store's recorded "
                     "dispatch shapes",
         const_names=(), param_names=()),
    Knob(name="serving.admission_queue_rows", default=512,
         consumer="serving/admission.py AdmissionController",
         kind="int",
         description="per-(model, tenant) lane admission bound in "
                     "queued rows — arrivals beyond it are shed with "
                     "a retry_after_ms hint; the policy sizes it so "
                     "the worst-case backlog drains in ~250ms at the "
                     "recorded dispatch rate",
         const_names=("DEFAULT_ADMISSION_QUEUE_ROWS",),
         param_names=()),
    Knob(name="serving.admission_quantum", default=32,
         consumer="serving/admission.py AdmissionController",
         kind="int",
         description="deficit-round-robin quantum in rows credited "
                     "per tenant visit of the dispatch-grant ring — "
                     "larger favors batch throughput, smaller favors "
                     "fairness granularity",
         const_names=("DEFAULT_ADMISSION_QUANTUM",),
         param_names=()),
    Knob(name="serving.coalesce_policy", default="deadline_or_full",
         consumer="serving/server.py ServingServer._collect",
         kind="str",
         description="how the coalescer closes a batch: "
                     "'deadline_or_full' (the fixed rule — dispatch at "
                     "the wait deadline or the target fill) or "
                     "'predicted_cost' (additionally split the popped "
                     "batch at a lattice rung when the cost model's "
                     "predicted per-row marginal cost says the smaller "
                     "dispatch is cheaper)",
         const_names=("DEFAULT_COALESCE_POLICY",),
         param_names=()),
    Knob(name="tuning.lattice_max_rungs", default=12,
         consumer="tuning/lattice.py choose_lattice",
         kind="int",
         description="rung bound for tuned non-power-of-two bucket "
                     "lattices — caps per-plan compiles exactly like "
                     "the log2(max/min)+1 bound the default ladder "
                     "carries (11 rungs at 8..8192)",
         const_names=("DEFAULT_LATTICE_MAX_RUNGS",),
         param_names=()),
    Knob(name="search.eta", default=3,
         consumer="selector/racing.py RacingCrossValidation",
         kind="int",
         description="racing promotion ratio: each rung keeps the "
                     "top 1/eta",
         const_names=("DEFAULT_ETA",),
         param_names=("eta",)),
    Knob(name="search.min_fidelity", default=None,
         consumer="selector/racing.py RacingCrossValidation",
         kind="float",
         description="budget fraction of the first racing rung (None "
                     "derives the classic 1/eta**2 three-rung "
                     "ladder); the final rung is ALWAYS exact full "
                     "CV regardless",
         const_names=("DEFAULT_MIN_FIDELITY",),
         param_names=("min_fidelity",)),
    Knob(name="prepare.placement_margin", default=1.0,
         consumer="plans/placement.py PlacementPolicy.decide_fit",
         kind="float",
         description="host-vs-device comparison margin: the device "
                     "fit wins while steady-state device seconds <= "
                     "margin * host seconds (1.0 = plain comparison, "
                     "today's rule)",
         const_names=("DEFAULT_PLACEMENT_MARGIN",),
         param_names=("placement_margin",)),
    Knob(name="audit.waste_ceiling", default=16.0,
         consumer="analysis/rules.py TX-P04 padding-waste bound",
         kind="float",
         description="max tolerated padded_rows/real_rows ratio per "
                     "bucket (vs the ProfileStore's recorded "
                     "occupancy) before the plan auditor's TX-P04 "
                     "escalates to ERROR — 16.0 tolerates the "
                     "worst-case single-row-in-min-bucket shape while "
                     "catching systematically mis-sized ladders",
         const_names=("DEFAULT_WASTE_CEILING",),
         param_names=("waste_ceiling",)),
)

#: knob name -> static default; THE values consumers import. An entry
#: here is what "bitwise identical to static defaults" means for an
#: empty store / TX_TUNE=off.
STATIC_DEFAULTS: Dict[str, Any] = {k.name: k.default for k in KNOBS}

#: module-level constant spellings TX-T01 polices (a numeric literal
#: assigned to one of these outside tuning/ is a forked default)
TUNABLE_CONST_NAMES = frozenset(
    n for k in KNOBS for n in k.const_names)

#: function-parameter spellings TX-T01 polices (a numeric literal
#: default for one of these outside tuning/ bypasses the policy)
TUNABLE_PARAM_NAMES = frozenset(
    n for k in KNOBS for n in k.param_names)

#: param spelling -> the consumer packages where TX-T01 polices it.
#: Scope discipline: ``eta`` is ALSO a gradient-boosting learning rate
#: (models/trees.py) — only in the knob's own consumer layer does the
#: spelling mean the registered knob.
TUNABLE_PARAM_SCOPES: Dict[str, frozenset] = {}
for _k in KNOBS:
    _pkg = _k.consumer.split("/", 1)[0]
    for _n in _k.param_names:
        TUNABLE_PARAM_SCOPES[_n] = (
            TUNABLE_PARAM_SCOPES.get(_n, frozenset()) | {_pkg})
del _k, _pkg, _n


def knob(name: str) -> Optional[Knob]:
    for k in KNOBS:
        if k.name == name:
            return k
    return None


def static_default(name: str) -> Any:
    if name not in STATIC_DEFAULTS:
        raise KeyError(f"unknown tunable knob {name!r}; registered: "
                       f"{sorted(STATIC_DEFAULTS)}")
    return STATIC_DEFAULTS[name]
