"""Typed feature value system (45 concrete types).

Reference type hierarchy: features/src/main/scala/com/salesforce/op/features/types/.
"""
from .base import (Categorical, FeatureType, FeatureTypeError, Location,
                   MultiResponse, NonNullable, SingleResponse,
                   all_feature_types, feature_type_by_name,
                   register_feature_type)
from .numerics import (Binary, Currency, Date, DateTime, Integral, OPNumeric,
                       Percent, Real, RealNN)
from .text import (ID, URL, Base64, City, ComboBox, Country, Email, Phone,
                   PickList, PostalCode, State, Street, Text, TextArea)
from .collections import (DateList, DateTimeList, Geolocation, MultiPickList,
                          OPCollection, OPList, OPSet, OPVector, TextList)
from .maps import (Base64Map, BinaryMap, CityMap, ComboBoxMap, CountryMap,
                   CurrencyMap, DateMap, DateTimeMap, EmailMap,
                   GeolocationMap, IDMap, IntegralMap, MultiPickListMap,
                   NumericMap, OPMap, PercentMap, PhoneMap, PickListMap,
                   PostalCodeMap, Prediction, RealMap, StateMap, StreetMap,
                   TextAreaMap, TextMap, URLMap)

from .conversions import *  # noqa: F401,F403
from . import conversions as _conv

__all__ = _conv.__all__ + [  # noqa: F405
    # kernel
    "FeatureType", "FeatureTypeError", "NonNullable", "SingleResponse",
    "MultiResponse", "Categorical", "Location", "register_feature_type",
    "feature_type_by_name", "all_feature_types",
    # numerics
    "OPNumeric", "Real", "RealNN", "Binary", "Integral", "Percent",
    "Currency", "Date", "DateTime",
    # text
    "Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea", "PickList",
    "ComboBox", "Country", "State", "PostalCode", "City", "Street",
    # collections
    "OPCollection", "OPVector", "OPList", "TextList", "DateList",
    "DateTimeList", "OPSet", "MultiPickList", "Geolocation",
    # maps
    "OPMap", "TextMap", "EmailMap", "Base64Map", "PhoneMap", "IDMap",
    "URLMap", "TextAreaMap", "PickListMap", "ComboBoxMap", "BinaryMap",
    "IntegralMap", "NumericMap", "RealMap", "PercentMap", "CurrencyMap",
    "DateMap", "DateTimeMap", "MultiPickListMap", "CountryMap", "StateMap",
    "CityMap", "PostalCodeMap", "StreetMap", "GeolocationMap", "Prediction",
]
