"""Root of the typed feature value hierarchy.

TPU-native re-design of the reference feature type kernel
(reference: features/src/main/scala/com/salesforce/op/features/types/FeatureType.scala:44).
The reference models each cell as a boxed Scala object; here boxed values exist
only at the edges (row-level scoring, extract functions) while bulk data lives
in columnar numpy buffers (see transmogrifai_tpu.features.columns) that feed
JAX/XLA device arrays.

Marker traits from the reference (FeatureType.scala:122-176) are mixin classes:
``NonNullable``, ``SingleResponse``, ``MultiResponse``, ``Categorical``,
``Location``.
"""
from __future__ import annotations

from typing import Any, ClassVar, Iterator

__all__ = [
    "FeatureType", "NonNullable", "SingleResponse", "MultiResponse",
    "Categorical", "Location", "FeatureTypeError", "register_feature_type",
    "feature_type_by_name", "all_feature_types",
]


class FeatureTypeError(TypeError):
    """Raised when a raw value cannot be converted into a feature type."""


_REGISTRY: dict[str, type["FeatureType"]] = {}


def register_feature_type(cls: type["FeatureType"]) -> type["FeatureType"]:
    """Register a concrete feature type by simple name (typeName registry,
    reference FeatureType.scala:267)."""
    _REGISTRY[cls.__name__] = cls
    return cls


def feature_type_by_name(name: str) -> type["FeatureType"]:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise FeatureTypeError(f"Unknown feature type name: {name!r}") from None


def all_feature_types() -> list[type["FeatureType"]]:
    return list(_REGISTRY.values())


class FeatureType:
    """A typed, possibly-empty feature value.

    Subclasses define ``_convert`` to normalize/validate raw python values.
    ``value`` is the canonical payload; ``None`` encodes an empty value for
    nullable types.
    """

    __slots__ = ("_value",)

    #: nullable unless the NonNullable mixin is present
    is_nullable: ClassVar[bool] = True

    def __init__(self, value: Any = None):
        self._value = self._convert(value)
        if self._value is None and not self.is_nullable:
            raise FeatureTypeError(
                f"{type(self).__name__} cannot be empty (non-nullable)")

    # -- abstract-ish ------------------------------------------------------
    @classmethod
    def _convert(cls, value: Any) -> Any:
        return value

    # -- core API (FeatureType.scala:44-120) -------------------------------
    @property
    def value(self) -> Any:
        return self._value

    @property
    def v(self) -> Any:  # short alias, like the reference's `v`
        return self._value

    @property
    def is_empty(self) -> bool:
        v = self._value
        if v is None:
            return True
        if isinstance(v, (str, dict, list, tuple, set, frozenset)):
            return len(v) == 0
        return False

    @property
    def non_empty(self) -> bool:
        return not self.is_empty

    def exists(self, pred) -> bool:
        return self.non_empty and bool(pred(self._value))

    @classmethod
    def type_name(cls) -> str:
        return cls.__name__

    @classmethod
    def empty(cls) -> "FeatureType":
        """The default (empty) instance
        (reference FeatureTypeDefaults.scala)."""
        return cls(None)

    @classmethod
    def from_any(cls, value: Any) -> "FeatureType":
        """Runtime construction from an arbitrary python value
        (reference FeatureTypeFactory.scala)."""
        if isinstance(value, cls):
            return value
        if isinstance(value, FeatureType):
            value = value.value
        return cls(value)

    # -- dunder ------------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self._value == other._value

    def __hash__(self) -> int:
        v = self._value
        if isinstance(v, dict):
            v = tuple(sorted(v.items()))
        elif isinstance(v, list):
            v = tuple(v)
        return hash((type(self).__name__, v))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._value!r})"

    def __bool__(self) -> bool:
        return self.non_empty


class NonNullable:
    """Marker: the value can never be empty (FeatureType.scala:122)."""
    is_nullable: ClassVar[bool] = False

    @classmethod
    def empty(cls):  # pragma: no cover - misuse guard
        raise FeatureTypeError(
            f"{cls.__name__} is non-nullable and has no empty instance")


class SingleResponse:
    """Marker: usable as a single-response label (FeatureType.scala:145)."""


class MultiResponse:
    """Marker: usable as a multi-response label (FeatureType.scala:155)."""


class Categorical:
    """Marker: categorical feature (FeatureType.scala:176)."""


class Location:
    """Marker: location-valued feature (FeatureType.scala:140)."""
