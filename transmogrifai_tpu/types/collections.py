"""Collection feature types: vectors, lists, sets, geolocation.

Reference: features/src/main/scala/com/salesforce/op/features/types/
{OPVector.scala:41, Lists.scala:38-64, Sets.scala:38, Geolocation.scala:47,130,
OPCollection.scala:37}.

``OPVector`` wraps a 1-D numpy array instead of a Spark ml Vector; the batch
representation is a dense 2-D device matrix (see features/columns.py), so the
boxed form here is only used at row-level scoring edges.
"""
from __future__ import annotations

import math
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from .base import (Categorical, FeatureType, FeatureTypeError, Location,
                   MultiResponse, register_feature_type)

__all__ = ["OPCollection", "OPList", "OPSet", "OPVector", "TextList",
           "DateList", "DateTimeList", "MultiPickList", "Geolocation"]


class OPCollection(FeatureType):
    """Base for collection types (OPCollection.scala:37)."""
    __slots__ = ()


@register_feature_type
class OPVector(OPCollection):
    """Dense numeric vector (OPVector.scala:41). Empty = zero-length array."""
    __slots__ = ()

    @classmethod
    def _convert(cls, value: Any) -> np.ndarray:
        if value is None:
            return np.zeros((0,), dtype=np.float64)
        arr = np.asarray(value, dtype=np.float64)
        if arr.ndim != 1:
            raise FeatureTypeError(f"OPVector requires 1-D data, got {arr.ndim}-D")
        return arr

    @property
    def is_empty(self) -> bool:
        return self._value.size == 0

    def __eq__(self, other: Any) -> bool:
        return (type(self) is type(other)
                and np.array_equal(self._value, other._value))

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._value.tobytes()))

    def combine(self, *others: "OPVector") -> "OPVector":
        """Concatenate vectors (reference RichVectorFeature ``.combine``)."""
        return OPVector(np.concatenate([self._value] + [o._value for o in others]))


class OPList(OPCollection):
    """Base list type (OPList.scala:40)."""
    __slots__ = ()
    _element_convert = staticmethod(lambda x: x)

    @classmethod
    def _convert(cls, value: Any) -> tuple:
        if value is None:
            return ()
        if isinstance(value, (list, tuple)):
            return tuple(cls._element_convert(v) for v in value)
        raise FeatureTypeError(f"Cannot convert {value!r} to {cls.__name__}")

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


@register_feature_type
class TextList(OPList):
    """List of strings (Lists.scala:38)."""
    __slots__ = ()
    _element_convert = staticmethod(str)


@register_feature_type
class DateList(OPList):
    """List of epoch times (Lists.scala:51)."""
    __slots__ = ()
    _element_convert = staticmethod(int)


@register_feature_type
class DateTimeList(DateList):
    """List of epoch millis (Lists.scala:64)."""
    __slots__ = ()


class OPSet(OPCollection):
    """Base set type (OPSet.scala:39)."""
    __slots__ = ()

    @classmethod
    def _convert(cls, value: Any) -> frozenset:
        if value is None:
            return frozenset()
        if isinstance(value, (set, frozenset, list, tuple)):
            return frozenset(str(v) for v in value)
        raise FeatureTypeError(f"Cannot convert {value!r} to {cls.__name__}")

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


@register_feature_type
class MultiPickList(Categorical, MultiResponse, OPSet):
    """Multi-select categorical (Sets.scala:38)."""
    __slots__ = ()


@register_feature_type
class Geolocation(Location, OPList):
    """(lat, lon, accuracy) triple (Geolocation.scala:47).

    Accuracy is an integer code (reference GeolocationAccuracy enum,
    Geolocation.scala:130); 0 = unknown.
    """
    __slots__ = ()

    @classmethod
    def _convert(cls, value: Any) -> tuple:
        if value is None:
            return ()
        if isinstance(value, (list, tuple)):
            if len(value) == 0:
                return ()
            if len(value) != 3:
                raise FeatureTypeError(
                    f"Geolocation requires (lat, lon, accuracy), got {value!r}")
            lat, lon, acc = float(value[0]), float(value[1]), float(value[2])
            if math.isnan(lat) or math.isnan(lon):
                return ()
            if not (-90.0 <= lat <= 90.0):
                raise FeatureTypeError(f"Latitude out of range: {lat}")
            if not (-180.0 <= lon <= 180.0):
                raise FeatureTypeError(f"Longitude out of range: {lon}")
            return (lat, lon, acc)
        raise FeatureTypeError(f"Cannot convert {value!r} to Geolocation")

    @property
    def lat(self) -> Optional[float]:
        return self._value[0] if self._value else None

    @property
    def lon(self) -> Optional[float]:
        return self._value[1] if self._value else None

    @property
    def accuracy(self) -> Optional[float]:
        return self._value[2] if self._value else None

    def to_unit_sphere(self) -> Optional[tuple]:
        """(x, y, z) on the unit sphere — used for midpoint aggregation
        (reference Geolocation.scala midpoint via spatial3d)."""
        if not self._value:
            return None
        lat, lon = math.radians(self._value[0]), math.radians(self._value[1])
        return (math.cos(lat) * math.cos(lon),
                math.cos(lat) * math.sin(lon),
                math.sin(lat))

    @staticmethod
    def from_unit_sphere(x: float, y: float, z: float,
                         accuracy: float = 0.0) -> "Geolocation":
        lon = math.degrees(math.atan2(y, x))
        hyp = math.sqrt(x * x + y * y)
        lat = math.degrees(math.atan2(z, hyp))
        return Geolocation((lat, lon, accuracy))
