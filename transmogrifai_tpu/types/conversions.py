"""Value → feature-type conversion syntax.

Python equivalent of the reference's implicit enrichment package
(reference: features/src/main/scala/com/salesforce/op/features/types/package.scala:42-152),
whose ``"abc".toText`` / ``1.0.toReal`` / ``Some(2L).toIntegral`` forms
are used throughout extract functions. Here they are plain None-safe
functions — ``to_real(row.get("age"))`` — accepting either a raw value
or another feature-type instance (unwrapped first), so re-typing a
value is the same one call.
"""
from __future__ import annotations

import numbers
from typing import Any, Optional

import numpy as np

from .base import FeatureType, FeatureTypeError
from . import numerics as _n
from . import text as _t
from . import collections as _c

__all__ = [
    "to_text", "to_email", "to_base64", "to_phone", "to_id", "to_url",
    "to_text_area", "to_pick_list", "to_combo_box", "to_country",
    "to_state", "to_postal_code", "to_city", "to_street",
    "to_real", "to_real_nn", "to_currency", "to_percent", "to_integral",
    "to_date", "to_date_time", "to_binary",
    "to_multi_pick_list", "to_text_list", "to_date_list",
    "to_date_time_list", "to_geolocation", "to_op_vector",
]


def _raw(v: Any) -> Any:
    return v.value if isinstance(v, FeatureType) else v


def _make(cls, name: str):
    def convert(v: Any = None):
        return cls(_raw(v))
    convert.__name__ = name
    convert.__doc__ = f"Convert a raw value (or feature) to {cls.__name__}."
    return convert


# text family (StringConversions / OptStringConversions, package.scala:42-73)
to_text = _make(_t.Text, "to_text")
to_email = _make(_t.Email, "to_email")
to_base64 = _make(_t.Base64, "to_base64")
to_phone = _make(_t.Phone, "to_phone")
to_id = _make(_t.ID, "to_id")
to_url = _make(_t.URL, "to_url")
to_text_area = _make(_t.TextArea, "to_text_area")
to_pick_list = _make(_t.PickList, "to_pick_list")
to_combo_box = _make(_t.ComboBox, "to_combo_box")
to_country = _make(_t.Country, "to_country")
to_state = _make(_t.State, "to_state")
to_postal_code = _make(_t.PostalCode, "to_postal_code")
to_city = _make(_t.City, "to_city")
to_street = _make(_t.Street, "to_street")

# numerics (JDouble/JFloat/JInteger/JLong + Option variants, :76-127)
to_real = _make(_n.Real, "to_real")
to_currency = _make(_n.Currency, "to_currency")
to_percent = _make(_n.Percent, "to_percent")
to_integral = _make(_n.Integral, "to_integral")
to_date = _make(_n.Date, "to_date")
to_date_time = _make(_n.DateTime, "to_date_time")

# collections
to_multi_pick_list = _make(_c.MultiPickList, "to_multi_pick_list")
to_text_list = _make(_c.TextList, "to_text_list")
to_date_list = _make(_c.DateList, "to_date_list")
to_date_time_list = _make(_c.DateTimeList, "to_date_time_list")
to_geolocation = _make(_c.Geolocation, "to_geolocation")
to_op_vector = _make(_c.OPVector, "to_op_vector")


def to_real_nn(v: Any = None, default: Optional[float] = None) -> "_n.RealNN":
    """``Option[Double].toRealNN(default)`` (package.scala:103): RealNN
    is non-nullable, so an empty input needs a default (or raises)."""
    v = _raw(v)
    if v is None:
        if default is None:
            raise FeatureTypeError(
                "to_real_nn of an empty value requires a default")
        v = default
    return _n.RealNN(v)


def to_binary(v: Any = None) -> "_n.Binary":
    """Boolean passes through; numbers map to ``v != 0``
    (JDoubleConversions.toBinary, package.scala:106)."""
    v = _raw(v)
    if v is None or isinstance(v, (bool, np.bool_)):
        return _n.Binary(None if v is None else bool(v))
    if isinstance(v, numbers.Real):       # incl. numpy scalars
        return _n.Binary(bool(v != 0))
    return _n.Binary(v)
