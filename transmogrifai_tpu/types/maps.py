"""Map feature types (String -> V) and the universal ``Prediction`` output type.

Reference: features/src/main/scala/com/salesforce/op/features/types/Maps.scala:38-357.
22 map types keyed by string; ``Prediction`` (Maps.scala:302-357) is a
non-nullable RealMap holding ``prediction`` plus ``rawPrediction_i`` /
``probability_i`` keys — every model in the framework outputs it.
"""
from __future__ import annotations

import math
import numbers
from typing import Any, Dict, Optional

import numpy as np

from .base import (Categorical, FeatureType, FeatureTypeError, Location,
                   MultiResponse, NonNullable, SingleResponse,
                   register_feature_type)

__all__ = [
    "OPMap", "TextMap", "EmailMap", "Base64Map", "PhoneMap", "IDMap",
    "URLMap", "TextAreaMap", "PickListMap", "ComboBoxMap", "BinaryMap",
    "IntegralMap", "RealMap", "PercentMap", "CurrencyMap", "DateMap",
    "DateTimeMap", "MultiPickListMap", "CountryMap", "StateMap", "CityMap",
    "PostalCodeMap", "StreetMap", "GeolocationMap", "Prediction",
]


class OPMap(FeatureType):
    """Base map type (reference OPMap.scala:38). Value is a dict[str, V]."""
    __slots__ = ()
    _value_convert = staticmethod(lambda x: x)

    @classmethod
    def _convert(cls, value: Any) -> Dict[str, Any]:
        if value is None:
            return {}
        if isinstance(value, dict):
            out = {}
            for k, v in value.items():
                cv = cls._value_convert(v)
                if cv is not None:
                    out[str(k)] = cv
            return out
        raise FeatureTypeError(f"Cannot convert {value!r} to {cls.__name__}")

    def __len__(self) -> int:
        return len(self._value)

    def __contains__(self, k) -> bool:
        return k in self._value

    def __getitem__(self, k):
        return self._value[k]

    def get(self, k, default=None):
        return self._value.get(k, default)

    def keys(self):
        return self._value.keys()

    def items(self):
        return self._value.items()


def _to_str(v):
    if v is None:
        return None
    return str(v)


def _to_real(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, numbers.Real):
        f = float(v)
        return None if math.isnan(f) else f
    raise FeatureTypeError(f"Cannot convert map value {v!r} to float")


def _to_int(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, numbers.Integral):
        return int(v)
    if isinstance(v, float) and v.is_integer():
        return int(v)
    raise FeatureTypeError(f"Cannot convert map value {v!r} to int")


def _to_bool(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return v
    if isinstance(v, numbers.Real) and float(v) in (0.0, 1.0):
        return bool(v)
    raise FeatureTypeError(f"Cannot convert map value {v!r} to bool")


def _to_strset(v):
    if v is None:
        return None
    if isinstance(v, (set, frozenset, list, tuple)):
        return frozenset(str(x) for x in v)
    raise FeatureTypeError(f"Cannot convert map value {v!r} to set")


def _to_geo(v):
    from .collections import Geolocation
    if v is None:
        return None
    return Geolocation(v).value or None


@register_feature_type
class TextMap(OPMap):
    """Map of strings (Maps.scala:40)."""
    __slots__ = ()
    _value_convert = staticmethod(_to_str)


@register_feature_type
class EmailMap(TextMap):
    """(Maps.scala:51)"""
    __slots__ = ()


@register_feature_type
class Base64Map(TextMap):
    """(Maps.scala:62)"""
    __slots__ = ()


@register_feature_type
class PhoneMap(TextMap):
    """(Maps.scala:73)"""
    __slots__ = ()


@register_feature_type
class IDMap(TextMap):
    """(Maps.scala:84)"""
    __slots__ = ()


@register_feature_type
class URLMap(TextMap):
    """(Maps.scala:95)"""
    __slots__ = ()


@register_feature_type
class TextAreaMap(TextMap):
    """(Maps.scala:106)"""
    __slots__ = ()


@register_feature_type
class PickListMap(Categorical, TextMap):
    """(Maps.scala:117)"""
    __slots__ = ()


@register_feature_type
class ComboBoxMap(Categorical, TextMap):
    """(Maps.scala:128)"""
    __slots__ = ()


@register_feature_type
class BinaryMap(OPMap):
    """Map of booleans (Maps.scala:139)."""
    __slots__ = ()
    _value_convert = staticmethod(_to_bool)


@register_feature_type
class IntegralMap(OPMap):
    """Map of longs (Maps.scala:152)."""
    __slots__ = ()
    _value_convert = staticmethod(_to_int)


class NumericMap(OPMap):
    """Base for real-valued maps (Maps.scala:49 NumericMap trait)."""
    __slots__ = ()


@register_feature_type
class RealMap(NumericMap):
    """Map of doubles (Maps.scala:165)."""
    __slots__ = ()
    _value_convert = staticmethod(_to_real)


@register_feature_type
class PercentMap(RealMap):
    """(Maps.scala:178)"""
    __slots__ = ()


@register_feature_type
class CurrencyMap(RealMap):
    """(Maps.scala:189)"""
    __slots__ = ()


@register_feature_type
class DateMap(IntegralMap):
    """(Maps.scala:200)"""
    __slots__ = ()


@register_feature_type
class DateTimeMap(DateMap):
    """(Maps.scala:211)"""
    __slots__ = ()


@register_feature_type
class MultiPickListMap(Categorical, MultiResponse, OPMap):
    """Map of string sets (Maps.scala:222)."""
    __slots__ = ()
    _value_convert = staticmethod(_to_strset)


@register_feature_type
class CountryMap(Location, TextMap):
    """(Maps.scala:233)"""
    __slots__ = ()


@register_feature_type
class StateMap(Location, TextMap):
    """(Maps.scala:244)"""
    __slots__ = ()


@register_feature_type
class CityMap(Location, TextMap):
    """(Maps.scala:255)"""
    __slots__ = ()


@register_feature_type
class PostalCodeMap(Location, TextMap):
    """(Maps.scala:266)"""
    __slots__ = ()


@register_feature_type
class StreetMap(Location, TextMap):
    """(Maps.scala:277)"""
    __slots__ = ()


@register_feature_type
class GeolocationMap(Location, OPMap):
    """Map of (lat, lon, accuracy) triples (Maps.scala:288)."""
    __slots__ = ()
    _value_convert = staticmethod(_to_geo)


@register_feature_type
class Prediction(NonNullable, RealMap):
    """Universal model output (Maps.scala:302-357).

    Required key: ``prediction``. Optional vector keys ``rawPrediction_i``
    and ``probability_i``.
    """
    __slots__ = ()

    KEY_PREDICTION = "prediction"
    KEY_RAW = "rawPrediction"
    KEY_PROB = "probability"

    @classmethod
    def _convert(cls, value: Any) -> Dict[str, float]:
        out = super()._convert(value)
        if cls.KEY_PREDICTION not in out:
            raise FeatureTypeError(
                "Prediction must contain a 'prediction' key; got keys "
                f"{sorted(out)}")
        for k in out:
            if k == cls.KEY_PREDICTION:
                continue
            prefix, _, suffix = k.rpartition("_")
            if prefix not in (cls.KEY_RAW, cls.KEY_PROB) \
                    or not suffix.isdigit():
                raise FeatureTypeError(
                    f"Prediction contains invalid key {k!r}; allowed: "
                    "'prediction', 'rawPrediction_<i>', 'probability_<i>' "
                    "(reference Maps.scala:302-357)")
        return out

    @classmethod
    def build(cls, prediction: float, raw_prediction=None,
              probability=None) -> "Prediction":
        d = {cls.KEY_PREDICTION: float(prediction)}
        if raw_prediction is not None:
            for i, rv in enumerate(np.asarray(raw_prediction).ravel()):
                d[f"{cls.KEY_RAW}_{i}"] = float(rv)
        if probability is not None:
            for i, pv in enumerate(np.asarray(probability).ravel()):
                d[f"{cls.KEY_PROB}_{i}"] = float(pv)
        return cls(d)

    def _vector(self, prefix: str) -> np.ndarray:
        items = sorted(
            ((int(k.rsplit("_", 1)[1]), v) for k, v in self._value.items()
             if k.startswith(prefix + "_")),
            key=lambda kv: kv[0])
        return np.asarray([v for _, v in items], dtype=np.float64)

    @property
    def prediction(self) -> float:
        return self._value[self.KEY_PREDICTION]

    @property
    def raw_prediction(self) -> np.ndarray:
        return self._vector(self.KEY_RAW)

    @property
    def probability(self) -> np.ndarray:
        return self._vector(self.KEY_PROB)
