"""Numeric feature types.

Reference: features/src/main/scala/com/salesforce/op/features/types/Numerics.scala:40-147
and OPNumeric.scala:39. ``Date``/``DateTime`` are integral epoch values
(millis for DateTime, per reference convention).
"""
from __future__ import annotations

import math
import numbers
from typing import Any, Optional

from .base import (FeatureType, FeatureTypeError, NonNullable, SingleResponse,
                   register_feature_type)

__all__ = ["OPNumeric", "Real", "RealNN", "Binary", "Integral", "Percent",
           "Currency", "Date", "DateTime"]


class OPNumeric(FeatureType):
    """Base for numeric types (reference OPNumeric.scala:39)."""
    __slots__ = ()

    def to_double(self) -> Optional[float]:
        v = self.value
        return None if v is None else float(v)


@register_feature_type
class Real(OPNumeric):
    """Optional double (reference Numerics.scala:40)."""
    __slots__ = ()

    @classmethod
    def _convert(cls, value: Any) -> Optional[float]:
        if value is None:
            return None
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        if isinstance(value, numbers.Real):
            f = float(value)
            return None if math.isnan(f) else f
        raise FeatureTypeError(f"Cannot convert {value!r} to {cls.__name__}")


@register_feature_type
class RealNN(NonNullable, Real):
    """Non-nullable real — the canonical label type (Numerics.scala:59)."""
    __slots__ = ()


@register_feature_type
class Binary(SingleResponse, OPNumeric):
    """Optional boolean (Numerics.scala:73)."""
    __slots__ = ()

    @classmethod
    def _convert(cls, value: Any) -> Optional[bool]:
        if value is None:
            return None
        if isinstance(value, bool):
            return value
        if isinstance(value, numbers.Real):
            f = float(value)
            if math.isnan(f):
                return None
            if f in (0.0, 1.0):
                return bool(f)
        raise FeatureTypeError(f"Cannot convert {value!r} to {cls.__name__}")

    def to_double(self) -> Optional[float]:
        v = self.value
        return None if v is None else (1.0 if v else 0.0)


@register_feature_type
class Integral(OPNumeric):
    """Optional long (Numerics.scala:90)."""
    __slots__ = ()

    @classmethod
    def _convert(cls, value: Any) -> Optional[int]:
        if value is None:
            return None
        if isinstance(value, bool):
            return int(value)
        if isinstance(value, numbers.Integral):
            return int(value)
        if isinstance(value, float):
            if math.isnan(value):
                return None
            if value.is_integer():
                return int(value)
        raise FeatureTypeError(f"Cannot convert {value!r} to {cls.__name__}")


@register_feature_type
class Percent(Real):
    """Real subtype for percentages (Numerics.scala:105)."""
    __slots__ = ()


@register_feature_type
class Currency(Real):
    """Real subtype for currency (Numerics.scala:119)."""
    __slots__ = ()


@register_feature_type
class Date(Integral):
    """Epoch time value (Numerics.scala:133)."""
    __slots__ = ()


@register_feature_type
class DateTime(Date):
    """Epoch millis (Numerics.scala:147)."""
    __slots__ = ()
