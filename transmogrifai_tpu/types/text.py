"""Text feature types: ``Text`` plus 13 semantic subtypes.

Reference: features/src/main/scala/com/salesforce/op/features/types/Text.scala:48-305.
"""
from __future__ import annotations

import base64 as _b64
from typing import Any, Optional

from .base import (Categorical, FeatureType, FeatureTypeError, Location,
                   SingleResponse, register_feature_type)

__all__ = ["Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea",
           "PickList", "ComboBox", "Country", "State", "PostalCode", "City",
           "Street"]


@register_feature_type
class Text(FeatureType):
    """Optional string (reference Text.scala:48).

    Matching the reference, ``Text(Some(""))`` is *non-empty*: only ``None``
    encodes a missing value, so fill rates and null indicators treat the
    empty string as present.
    """
    __slots__ = ()

    @classmethod
    def _convert(cls, value: Any) -> Optional[str]:
        if value is None:
            return None
        if isinstance(value, str):
            return value
        raise FeatureTypeError(f"Cannot convert {value!r} to {cls.__name__}")

    @property
    def is_empty(self) -> bool:
        return self._value is None


class _CoerceNumeric:
    """Mixin for categorical text types: numeric category codes (e.g. CSV
    "pclass" 1/2/3) stringify, as the reference's .toPickList enrichment
    does. Semantic types (Email, URL, ...) stay strict."""

    @classmethod
    def _convert(cls, value: Any) -> Optional[str]:
        if isinstance(value, bool):
            raise FeatureTypeError(
                f"Cannot convert {value!r} to {cls.__name__}")
        if isinstance(value, (int, float)):
            if isinstance(value, float) and value.is_integer():
                return str(int(value))
            return str(value)
        return Text._convert.__func__(cls, value)


@register_feature_type
class Email(Text):
    """Email address (Text.scala:65); exposes prefix/domain accessors."""
    __slots__ = ()

    @property
    def prefix(self) -> Optional[str]:
        p = self._split()
        return p[0] if p else None

    @property
    def domain(self) -> Optional[str]:
        p = self._split()
        return p[1] if p else None

    def _split(self):
        v = self.value
        if not v or v.count("@") != 1:
            return None
        pre, dom = v.split("@")
        return (pre, dom) if pre and dom else None


@register_feature_type
class Base64(Text):
    """Base64-encoded binary (Text.scala:101)."""
    __slots__ = ()

    def as_bytes(self) -> Optional[bytes]:
        if self.is_empty:
            return None
        try:
            return _b64.b64decode(self.value)
        except Exception:
            return None

    def as_string(self) -> Optional[str]:
        b = self.as_bytes()
        if b is None:
            return None
        try:
            return b.decode("utf-8")
        except UnicodeDecodeError:
            return None


@register_feature_type
class Phone(Text):
    """Phone number (Text.scala:139)."""
    __slots__ = ()


@register_feature_type
class ID(_CoerceNumeric, Text):
    """Entity id (Text.scala:153)."""
    __slots__ = ()


@register_feature_type
class URL(Text):
    """URL (Text.scala:167); validity + protocol/domain accessors."""
    __slots__ = ()

    _PROTOCOLS = ("http", "https", "ftp")

    @property
    def is_valid(self) -> bool:
        from urllib.parse import urlparse
        if self.is_empty:
            return False
        try:
            p = urlparse(self.value)
        except ValueError:
            return False
        return p.scheme in self._PROTOCOLS and bool(p.hostname)

    @property
    def domain(self) -> Optional[str]:
        from urllib.parse import urlparse
        if not self.is_valid:
            return None
        return urlparse(self.value).hostname

    @property
    def protocol(self) -> Optional[str]:
        from urllib.parse import urlparse
        if not self.is_valid:
            return None
        return urlparse(self.value).scheme


@register_feature_type
class TextArea(Text):
    """Long free-form text (Text.scala:201)."""
    __slots__ = ()


@register_feature_type
class PickList(_CoerceNumeric, Categorical, SingleResponse, Text):
    """Single-select categorical (Text.scala:215)."""
    __slots__ = ()


@register_feature_type
class ComboBox(_CoerceNumeric, Categorical, Text):
    """Categorical with free-form entry allowed (Text.scala:228)."""
    __slots__ = ()


@register_feature_type
class Country(Location, Text):
    """Country name (Text.scala:242)."""
    __slots__ = ()


@register_feature_type
class State(Location, Text):
    """State name (Text.scala:256)."""
    __slots__ = ()


@register_feature_type
class PostalCode(Location, Text):
    """Postal code (Text.scala:270)."""
    __slots__ = ()


@register_feature_type
class City(Location, Text):
    """City name (Text.scala:284)."""
    __slots__ = ()


@register_feature_type
class Street(Location, Text):
    """Street address (Text.scala:298)."""
    __slots__ = ()
