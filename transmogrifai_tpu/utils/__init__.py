from .uid import uid, reset as reset_uids
from .vector_meta import (NULL_INDICATOR, OTHER_INDICATOR,
                          VectorColumnMetadata, VectorMetadata)

__all__ = ["uid", "reset_uids", "VectorColumnMetadata", "VectorMetadata",
           "NULL_INDICATOR", "OTHER_INDICATOR"]
