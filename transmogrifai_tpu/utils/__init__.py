from .listener import AppMetrics, StageMetric, WorkflowListener
from .table import Table
from .uid import uid, reset as reset_uids
from .vector_meta import (NULL_INDICATOR, OTHER_INDICATOR,
                          VectorColumnMetadata, VectorMetadata)
from .version import VersionInfo, version_info

__all__ = ["uid", "reset_uids", "VectorColumnMetadata", "VectorMetadata",
           "NULL_INDICATOR", "OTHER_INDICATOR", "Table",
           "WorkflowListener", "AppMetrics", "StageMetric",
           "VersionInfo", "version_info"]
