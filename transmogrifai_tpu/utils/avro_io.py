"""Avro object-container IO — stdlib-only encoder/decoder.

Host-side replacement for the reference's Avro stack
(utils/src/main/scala/com/salesforce/op/utils/io/avro/AvroInOut.scala,
readers/.../AvroReaders.scala, CSVToAvro in utils/.../io/csv/): the
environment ships no avro library, so the object container file format
(magic ``Obj\\x01`` + metadata map + sync-marker framed blocks) and the
binary encoding (zigzag varints, length-prefixed bytes/strings, blocked
arrays/maps, union indices) are implemented directly. Supported codecs:
``null`` and ``deflate`` (zlib). Schema support covers what tabular
pipelines use: records of primitives, nullable unions, enums, arrays,
maps, and nested records.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterator, List, Optional, Tuple

__all__ = ["read_avro", "write_avro", "iter_avro", "infer_avro_schema",
           "AvroError"]

_MAGIC = b"Obj\x01"


class AvroError(ValueError):
    pass


# ---------------------------------------------------------------------------
# binary primitives
# ---------------------------------------------------------------------------

def _read_long(buf: io.BytesIO) -> int:
    """Zigzag-encoded variable-length long."""
    shift, acc = 0, 0
    while True:
        b = buf.read(1)
        if not b:
            raise AvroError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_long(out: io.BytesIO, v: int) -> None:
    v = (v << 1) ^ (v >> 63) if v >= 0 else ((-v - 1) << 1 | 1)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            break


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise AvroError("truncated bytes")
    return data


def _write_bytes(out: io.BytesIO, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


# ---------------------------------------------------------------------------
# schema-driven (de)coding
# ---------------------------------------------------------------------------

def _decode(schema, buf: io.BytesIO, names: Dict[str, Any]):
    if isinstance(schema, str):
        if schema in names:                      # named-type reference
            return _decode(names[schema], buf, names)
        t = schema
        if t == "null":
            return None
        if t == "boolean":
            return buf.read(1) != b"\x00"
        if t in ("int", "long"):
            return _read_long(buf)
        if t == "float":
            return struct.unpack("<f", buf.read(4))[0]
        if t == "double":
            return struct.unpack("<d", buf.read(8))[0]
        if t == "bytes":
            return _read_bytes(buf)
        if t == "string":
            return _read_bytes(buf).decode("utf-8")
        raise AvroError(f"unsupported avro type {t!r}")
    if isinstance(schema, list):                 # union: index then value
        idx = _read_long(buf)
        if not 0 <= idx < len(schema):
            raise AvroError(f"union index {idx} out of range")
        return _decode(schema[idx], buf, names)
    t = schema["type"]
    if t == "record":
        names[schema["name"]] = schema
        return {f["name"]: _decode(f["type"], buf, names)
                for f in schema["fields"]}
    if t == "enum":
        names[schema["name"]] = schema
        return schema["symbols"][_read_long(buf)]
    if t == "array":
        out = []
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:                        # block with byte size
                count = -count
                _read_long(buf)
            for _ in range(count):
                out.append(_decode(schema["items"], buf, names))
        return out
    if t == "map":
        out = {}
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:
                count = -count
                _read_long(buf)
            for _ in range(count):
                k = _read_bytes(buf).decode("utf-8")
                out[k] = _decode(schema["values"], buf, names)
        return out
    if t == "fixed":
        names[schema["name"]] = schema
        return buf.read(schema["size"])
    return _decode(t, buf, names)                # {"type": "string"} form


def _encode(schema, v, out: io.BytesIO, names: Dict[str, Any]) -> None:
    if isinstance(schema, str):
        if schema in names:
            return _encode(names[schema], v, out, names)
        t = schema
        if t == "null":
            return
        if t == "boolean":
            out.write(b"\x01" if v else b"\x00")
        elif t in ("int", "long"):
            _write_long(out, int(v))
        elif t == "float":
            out.write(struct.pack("<f", float(v)))
        elif t == "double":
            out.write(struct.pack("<d", float(v)))
        elif t == "bytes":
            _write_bytes(out, bytes(v))
        elif t == "string":
            _write_bytes(out, str(v).encode("utf-8"))
        else:
            raise AvroError(f"unsupported avro type {t!r}")
        return
    if isinstance(schema, list):
        for i, branch in enumerate(schema):
            bt = branch if isinstance(branch, str) else branch["type"]
            if (v is None) == (bt == "null"):
                if v is None or _matches(branch, v):
                    _write_long(out, i)
                    return _encode(branch, v, out, names)
        raise AvroError(f"no union branch for {v!r} in {schema}")
    t = schema["type"]
    if t == "record":
        names[schema["name"]] = schema
        for f in schema["fields"]:
            _encode(f["type"], (v or {}).get(f["name"]), out, names)
    elif t == "enum":
        _write_long(out, schema["symbols"].index(v))
    elif t == "array":
        if v:
            _write_long(out, len(v))
            for item in v:
                _encode(schema["items"], item, out, names)
        _write_long(out, 0)
    elif t == "map":
        if v:
            _write_long(out, len(v))
            for k, item in v.items():
                _write_bytes(out, str(k).encode("utf-8"))
                _encode(schema["values"], item, out, names)
        _write_long(out, 0)
    elif t == "fixed":
        out.write(bytes(v))
    else:
        _encode(t, v, out, names)


def _matches(branch, v) -> bool:
    t = branch if isinstance(branch, str) else branch.get("type")
    if t == "boolean":
        return isinstance(v, bool)
    if t in ("int", "long"):
        return isinstance(v, int) and not isinstance(v, bool)
    if t in ("float", "double"):
        return isinstance(v, (int, float)) and not isinstance(v, bool)
    if t == "string":
        return isinstance(v, str)
    if t == "bytes":
        return isinstance(v, bytes)
    if t == "array":
        return isinstance(v, (list, tuple))
    if t in ("map", "record"):
        return isinstance(v, dict)
    return True


# ---------------------------------------------------------------------------
# container files
# ---------------------------------------------------------------------------

def iter_avro(path: str) -> Iterator[dict]:
    """Stream records from an Avro object container file. Reads the
    sync-framed blocks incrementally off the file handle (the binary
    primitives above only need ``.read``), so peak memory is one block
    — the property the streaming readers rely on."""
    with open(path, "rb") as fh:
        if fh.read(4) != _MAGIC:
            raise AvroError(f"{path}: not an Avro container file")
        meta: Dict[str, bytes] = {}
        while True:
            count = _read_long(fh)
            if count == 0:
                break
            if count < 0:
                count = -count
                _read_long(fh)
            for _ in range(count):
                k = _read_bytes(fh).decode("utf-8")
                meta[k] = _read_bytes(fh)
        sync = fh.read(16)
        schema = json.loads(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise AvroError(f"unsupported codec {codec!r}")
        names: Dict[str, Any] = {}
        while True:
            try:
                n_records = _read_long(fh)
            except AvroError:
                break                              # clean EOF
            size = _read_long(fh)
            block = fh.read(size)
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            bbuf = io.BytesIO(block)
            for _ in range(n_records):
                yield _decode(schema, bbuf, names)
            if fh.read(16) != sync:
                raise AvroError("sync marker mismatch")


def read_avro(path: str) -> List[dict]:
    """All records of an Avro container file (reference AvroInOut.read)."""
    return list(iter_avro(path))


def infer_avro_schema(records: List[dict], name: str = "Row") -> dict:
    """Nullable-union record schema from sample dicts (the role of
    CSVToAvro's schema application / CSVAutoReaders inference)."""
    #: type-widening lattice: null < boolean|long < double < string
    _RANK = {"null": 0, "boolean": 1, "long": 1, "double": 2, "string": 3}
    types: Dict[str, str] = {}

    def widen(k: str, t: str) -> None:
        cur = types.setdefault(k, "null")
        if _RANK[t] > _RANK[cur]:
            types[k] = t
        elif _RANK[t] == _RANK[cur] and t != cur:
            types[k] = "string"   # boolean vs long — no numeric widening

    for r in records:
        for k, v in (r or {}).items():
            if v is None:
                widen(k, "null")
            elif isinstance(v, bool):
                widen(k, "boolean")
            elif isinstance(v, int):
                widen(k, "long")
            elif isinstance(v, float):
                widen(k, "double")
            else:
                widen(k, "string")
    fields = [{"name": k,
               "type": ["null", t] if t != "null" else ["null", "string"],
               "default": None}
              for k, t in sorted(types.items())]
    return {"type": "record", "name": name, "fields": fields}


def write_avro(path: str, records: List[dict],
               schema: Optional[dict] = None, codec: str = "null",
               sync: bytes = b"\x00" * 16) -> dict:
    """Write records as an Avro object container file; returns the
    schema used (inferred when not given)."""
    schema = schema or infer_avro_schema(records)
    if codec not in ("null", "deflate"):
        raise AvroError(f"unsupported codec {codec!r}")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    names: Dict[str, Any] = {}
    body = io.BytesIO()
    for r in records:
        _encode(schema, r, body, names)
    block = body.getvalue()
    if codec == "deflate":
        co = zlib.compressobj(9, zlib.DEFLATED, -15)
        block = co.compress(block) + co.flush()
    with open(path, "wb") as fh:
        fh.write(_MAGIC)
        meta = io.BytesIO()
        _write_long(meta, 2)
        _write_bytes(meta, b"avro.schema")
        _write_bytes(meta, json.dumps(schema).encode("utf-8"))
        _write_bytes(meta, b"avro.codec")
        _write_bytes(meta, codec.encode())
        _write_long(meta, 0)
        fh.write(meta.getvalue())
        fh.write(sync)
        out = io.BytesIO()
        _write_long(out, len(records))
        _write_long(out, len(block))
        fh.write(out.getvalue())
        fh.write(block)
        fh.write(sync)
    return schema
