"""Process-wide XLA compile-time accounting via ``jax.monitoring``.

CPU benchmark runs are frequently COMPILE-bound (tracing + XLA
compilation dominates the wall clock) while accelerator runs are
compute-bound — a single per-stage wall-time number cannot tell the two
apart. JAX publishes internal event durations (``.../backend_compile``
and friends) through ``jax.monitoring``; this module installs one
listener that accumulates them

- globally (``compile_seconds()``), snapshotted around each workflow
  stage so ``StageMetric.compile_seconds`` splits first-call compile
  time from steady-state execute time,
- per thread NAME (``compile_seconds_by_thread()``): the validator
  renames its dispatch workers ``tx-family-<Name>``
  (selector/validator.py), so a model-selection search attributes its
  compile bill family by family, and
- per SECTION label (``section()`` / ``seconds_by_section()``): the
  compiled prepare plan (plans/prepare.py) runs many stages inside ONE
  fused program, so thread- and stage-wall attribution alone would
  lose the per-stage compile/execute split that the telemetry-
  autotuning roadmap item consumes. A section is a labelled span
  (``with section("prepare:seg0"): ...``) on a per-thread stack;
  monitoring events observed inside attribute to EVERY open label, so
  a segment's total includes its per-stage sub-sections. Each label
  also records wall seconds and call count, giving callers the
  ``execute = wall - compile`` split per label.

Installation is lazy and idempotent; on a JAX without the monitoring
API everything degrades to zeros (callers must treat 0.0 as "unknown",
not "free").
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict

__all__ = ["install", "compile_seconds", "compile_seconds_by_thread",
           "section", "seconds_by_section", "reset_sections",
           "set_section_observer"]

_LOCK = threading.Lock()
_TOTAL = {"seconds": 0.0}
_BY_THREAD: Dict[str, float] = defaultdict(float)
#: label -> {"seconds": wall, "compile": event seconds, "calls": n}
_SECTIONS: Dict[str, Dict[str, float]] = {}
_STATE = {"installed": False, "available": False}
_SECTION_STACK = threading.local()
#: optional callback ``(label, wall_seconds, compile_seconds)`` fired
#: as each section CLOSES — how the span tracer
#: (observability/trace.py) attaches a section's compile/execute split
#: to the enclosing span. None (the default) costs nothing.
_SECTION_OBSERVER = {"fn": None}


def set_section_observer(fn) -> None:
    """Register (or clear, with None) the section-close observer."""
    _SECTION_OBSERVER["fn"] = fn


def _stack():
    st = getattr(_SECTION_STACK, "stack", None)
    if st is None:
        st = _SECTION_STACK.stack = []
    return st


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    # '/jax/core/compile/backend_compile_duration' and the pjit
    # trace/lower events all carry 'compile' or 'trace' in the key;
    # anything else (transfer, execution) is not compile cost
    if "compile" not in event and "trace" not in event and \
            "lower" not in event:
        return
    open_labels = list(_stack())
    with _LOCK:
        _TOTAL["seconds"] += duration
        _BY_THREAD[threading.current_thread().name] += duration
        for label in open_labels:
            rec = _SECTIONS.setdefault(
                label, {"seconds": 0.0, "compile": 0.0, "calls": 0})
            rec["compile"] += duration


def install() -> bool:
    """Register the listener once; True when the monitoring API exists."""
    if _STATE["installed"]:
        return _STATE["available"]
    _STATE["installed"] = True
    try:
        import jax.monitoring as monitoring
        monitoring.register_event_duration_secs_listener(
            _on_event_duration)
        _STATE["available"] = True
    except Exception:  # pragma: no cover - older jax without the API
        _STATE["available"] = False
    return _STATE["available"]


def compile_seconds() -> float:
    """Total compile/trace seconds observed so far in this process."""
    with _LOCK:
        return _TOTAL["seconds"]


def compile_seconds_by_thread(prefix: str = "") -> Dict[str, float]:
    """Snapshot of compile seconds keyed by the OBSERVING thread's name
    at event time (filtered to names starting with ``prefix``)."""
    with _LOCK:
        return {k: v for k, v in _BY_THREAD.items()
                if k.startswith(prefix)}


@contextmanager
def section(label: str):
    """Attribute wall + compile seconds inside this span to ``label``
    (nested sections attribute compile events to every open label).
    Works inside a jit trace too: the body of a traced function runs
    exactly once per trace, so a per-stage section there measures that
    stage's TRACE cost — the per-stage half of the plan-section
    telemetry (docs/prepare.md)."""
    install()
    st = _stack()
    st.append(label)
    observer = _SECTION_OBSERVER["fn"]
    if observer is not None:
        with _LOCK:
            prev = _SECTIONS.get(label)
            compile_before = prev["compile"] if prev else 0.0
    t0 = time.perf_counter()
    try:
        yield
    finally:
        st.pop()
        wall = time.perf_counter() - t0
        with _LOCK:
            rec = _SECTIONS.setdefault(
                label, {"seconds": 0.0, "compile": 0.0, "calls": 0})
            rec["seconds"] += wall
            rec["calls"] += 1
            compile_after = rec["compile"]
        if observer is not None:
            # per-invocation compile share: this label's event seconds
            # accumulated while the span was open (approximate under
            # concurrent same-label sections; exact single-threaded)
            observer(label, wall, max(compile_after - compile_before,
                                      0.0))


def seconds_by_section(prefix: str = "") -> Dict[str, Dict[str, float]]:
    """Snapshot of ``{label: {"seconds", "compile", "calls"}}`` for
    labels starting with ``prefix``. ``seconds`` is wall time inside
    the span, ``compile`` the monitoring-event (trace/lower/compile)
    seconds observed while it was open; ``seconds - compile`` is the
    steady-state execute estimate for the label."""
    with _LOCK:
        return {k: dict(v) for k, v in _SECTIONS.items()
                if k.startswith(prefix)}


def reset_sections(prefix: str = "") -> None:
    """Drop section records (filtered by prefix; "" drops all) — test
    and bench isolation."""
    with _LOCK:
        for k in [k for k in _SECTIONS if k.startswith(prefix)]:
            del _SECTIONS[k]
