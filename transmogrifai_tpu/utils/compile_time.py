"""Process-wide XLA compile-time accounting via ``jax.monitoring``.

CPU benchmark runs are frequently COMPILE-bound (tracing + XLA
compilation dominates the wall clock) while accelerator runs are
compute-bound — a single per-stage wall-time number cannot tell the two
apart. JAX publishes internal event durations (``.../backend_compile``
and friends) through ``jax.monitoring``; this module installs one
listener that accumulates them

- globally (``compile_seconds()``), snapshotted around each workflow
  stage so ``StageMetric.compile_seconds`` splits first-call compile
  time from steady-state execute time, and
- per thread NAME (``compile_seconds_by_thread()``): the validator
  renames its dispatch workers ``tx-family-<Name>``
  (selector/validator.py), so a model-selection search attributes its
  compile bill family by family.

Installation is lazy and idempotent; on a JAX without the monitoring
API everything degrades to zeros (callers must treat 0.0 as "unknown",
not "free").
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict

__all__ = ["install", "compile_seconds", "compile_seconds_by_thread"]

_LOCK = threading.Lock()
_TOTAL = {"seconds": 0.0}
_BY_THREAD: Dict[str, float] = defaultdict(float)
_STATE = {"installed": False, "available": False}


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    # '/jax/core/compile/backend_compile_duration' and the pjit
    # trace/lower events all carry 'compile' or 'trace' in the key;
    # anything else (transfer, execution) is not compile cost
    if "compile" not in event and "trace" not in event and \
            "lower" not in event:
        return
    with _LOCK:
        _TOTAL["seconds"] += duration
        _BY_THREAD[threading.current_thread().name] += duration


def install() -> bool:
    """Register the listener once; True when the monitoring API exists."""
    if _STATE["installed"]:
        return _STATE["available"]
    _STATE["installed"] = True
    try:
        import jax.monitoring as monitoring
        monitoring.register_event_duration_secs_listener(
            _on_event_duration)
        _STATE["available"] = True
    except Exception:  # pragma: no cover - older jax without the API
        _STATE["available"] = False
    return _STATE["available"]


def compile_seconds() -> float:
    """Total compile/trace seconds observed so far in this process."""
    with _LOCK:
        return _TOTAL["seconds"]


def compile_seconds_by_thread(prefix: str = "") -> Dict[str, float]:
    """Snapshot of compile seconds keyed by the OBSERVING thread's name
    at event time (filtered to names starting with ``prefix``)."""
    with _LOCK:
        return {k: v for k, v in _BY_THREAD.items()
                if k.startswith(prefix)}
