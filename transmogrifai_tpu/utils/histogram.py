"""Streaming (Ben-Haim/Tom-Tov) histogram.

TPU-native equivalent of the reference's single Java source file
(utils/src/main/java/com/salesforce/op/utils/stats/StreamingHistogram.java:36),
used by RawFeatureFilter for numeric feature distributions. This numpy
implementation batches inserts (sort + merge) instead of the one-point-at-a-
time Java loop.
"""
from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple

import numpy as np

__all__ = ["StreamingHistogram"]


def _native_merge():
    """Lazily-loaded C++ merge kernel (native/streaming_histogram.cpp);
    None -> numpy fallback."""
    global _NATIVE
    if _NATIVE == "unset":
        from ..native import histogram_merge_kernel
        _NATIVE = histogram_merge_kernel()
    return _NATIVE


_NATIVE = "unset"


class StreamingHistogram:
    """Fixed-size histogram of (centroid, count) bins supporting merge and
    interpolated sum/quantile queries."""

    def __init__(self, max_bins: int = 100):
        if max_bins < 2:
            raise ValueError("max_bins must be >= 2")
        self.max_bins = max_bins
        self.centroids = np.zeros(0, dtype=np.float64)
        self.counts = np.zeros(0, dtype=np.float64)

    # -- updates -----------------------------------------------------------
    def update(self, points: Iterable[float],
               counts: Optional[Iterable[float]] = None
               ) -> "StreamingHistogram":
        pts = np.asarray(list(points) if not isinstance(points, np.ndarray)
                         else points, dtype=np.float64)
        cts = np.ones_like(pts) if counts is None else \
            np.asarray(list(counts), dtype=np.float64)
        if cts.shape != pts.shape:
            raise ValueError(
                f"counts shape {cts.shape} != points shape {pts.shape}")
        keep = ~np.isnan(pts)  # drop NaN points and their counts together
        pts, cts = pts[keep], cts[keep]
        if pts.size == 0:
            return self
        # presort and collapse duplicates, then merge with existing bins
        order = np.argsort(pts)
        pts, cts = pts[order], cts[order]
        uniq, inv = np.unique(pts, return_inverse=True)
        agg = np.zeros_like(uniq)
        np.add.at(agg, inv, cts)
        self._merge_arrays(uniq, agg)
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Merge another histogram into this one (used to combine per-shard
        histograms — the distributed reduction point)."""
        self._merge_arrays(other.centroids, other.counts)
        return self

    def _merge_arrays(self, cents: np.ndarray, cnts: np.ndarray) -> None:
        c = np.concatenate([self.centroids, cents])
        n = np.concatenate([self.counts, cnts])
        order = np.argsort(c)
        c, n = np.ascontiguousarray(c[order]), np.ascontiguousarray(n[order])
        if c.size > self.max_bins:
            kernel = _native_merge()
            if kernel is not None:
                # O(k log k) heap merge in C++ (native/
                # streaming_histogram.cpp); same closest-pair semantics
                import ctypes
                size = kernel(
                    c.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    n.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    c.size, self.max_bins)
                c, n = c[:size].copy(), n[:size].copy()
            else:
                # numpy fallback: rescan for the closest pair each round
                while c.size > self.max_bins:
                    gaps = np.diff(c)
                    i = int(np.argmin(gaps))
                    tot = n[i] + n[i + 1]
                    c[i] = (c[i] * n[i] + c[i + 1] * n[i + 1]) / tot
                    n[i] = tot
                    c = np.delete(c, i + 1)
                    n = np.delete(n, i + 1)
        self.centroids, self.counts = c, n

    # -- queries -----------------------------------------------------------
    @property
    def total(self) -> float:
        return float(self.counts.sum())

    def bins(self) -> Tuple[np.ndarray, np.ndarray]:
        return self.centroids.copy(), self.counts.copy()

    def sum_upto(self, b: float) -> float:
        """Estimated number of points <= b (StreamingHistogram.java sum())."""
        c, n = self.centroids, self.counts
        if c.size == 0:
            return 0.0
        if b >= c[-1]:
            return float(n.sum())
        if b < c[0]:
            return 0.0
        i = int(np.searchsorted(c, b, side="right")) - 1
        if c.size == 1 or i == c.size - 1:
            return float(n[:i].sum() + n[i] / 2.0)
        # trapezoid interpolation between centroid i and i+1
        ci, ci1, ni, ni1 = c[i], c[i + 1], n[i], n[i + 1]
        frac = (b - ci) / (ci1 - ci) if ci1 > ci else 0.0
        mb = ni + (ni1 - ni) * frac
        s = (ni + mb) * frac / 2.0
        return float(n[:i].sum() + ni / 2.0 + s)

    def density(self, breakpoints: Sequence[float]) -> np.ndarray:
        """Estimated counts falling in intervals defined by breakpoints."""
        sums = np.asarray([self.sum_upto(b) for b in breakpoints])
        return np.diff(np.concatenate([[0.0], sums, [self.total]]))

    def quantile(self, q: float) -> float:
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        c, n = self.centroids, self.counts
        if c.size == 0:
            return float("nan")
        target = q * n.sum()
        cum = np.cumsum(n) - n / 2.0
        i = int(np.searchsorted(cum, target))
        if i == 0:
            return float(c[0])
        if i >= c.size:
            return float(c[-1])
        frac = (target - cum[i - 1]) / (cum[i] - cum[i - 1])
        return float(c[i - 1] + (c[i] - c[i - 1]) * frac)

    def to_json(self) -> dict:
        return {"maxBins": self.max_bins,
                "centroids": self.centroids.tolist(),
                "counts": self.counts.tolist()}

    @staticmethod
    def from_json(d: dict) -> "StreamingHistogram":
        h = StreamingHistogram(d["maxBins"])
        h.centroids = np.asarray(d["centroids"], dtype=np.float64)
        h.counts = np.asarray(d["counts"], dtype=np.float64)
        return h
