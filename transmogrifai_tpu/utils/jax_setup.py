"""Process-level JAX configuration helpers.

The selector's hyperparameter grids span several static shapes (tree
depth, forest size, fold sizes), each costing an XLA compile. The
persistent compilation cache amortizes those compiles across processes
— the same mechanism production JAX training jobs use. Call
:func:`enable_compilation_cache` once at program start (bench.py and
the examples do).
"""
from __future__ import annotations

import os

__all__ = ["enable_compilation_cache"]

_DEFAULT_CACHE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".jax_cache")


def enable_compilation_cache(path: str = None) -> str:
    """Turn on JAX's persistent compilation cache at ``path`` (defaults
    to ``<repo>/.jax_cache``). Safe to call multiple times."""
    import jax
    path = path or os.environ.get("TX_JAX_CACHE_DIR", _DEFAULT_CACHE)
    os.makedirs(path, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except AttributeError:  # older jax without the knob
        pass
    return path
