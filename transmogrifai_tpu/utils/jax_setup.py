"""Process-level JAX configuration helpers.

The selector's hyperparameter grids span several static shapes (tree
depth, forest size, fold sizes), each costing an XLA compile. The
persistent compilation cache amortizes those compiles across processes
— the same mechanism production JAX training jobs use. Call
:func:`enable_compilation_cache` once at program start (bench.py and
the examples do).
"""
from __future__ import annotations

import os

__all__ = ["enable_compilation_cache", "device_trace",
           "pin_platform_from_env", "shard_map"]

_SHARD_MAP = None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """Version-portable ``shard_map``: jax>=0.5 exposes
    ``jax.shard_map(..., check_vma=)``, while 0.4.x only has
    ``jax.experimental.shard_map.shard_map(..., check_rep=)``. The
    AttributeError from probing the wrong one classifies as a BUG under
    runtime.errors (it IS one at a direct call site), so resolve once
    here instead of per-kernel."""
    global _SHARD_MAP
    if _SHARD_MAP is None:
        import inspect

        import jax
        try:
            fn = jax.shard_map
        except AttributeError:
            from jax.experimental.shard_map import shard_map as fn
        rep_kw = ("check_vma" if "check_vma" in
                  inspect.signature(fn).parameters else "check_rep")
        _SHARD_MAP = (fn, rep_kw)
    fn, rep_kw = _SHARD_MAP
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{rep_kw: check_vma})


def device_trace(log_dir: str):
    """Context manager around ``jax.profiler`` tracing: per-op device
    timelines viewable in TensorBoard/Perfetto — the accelerator-level
    profile the reference leaves to the Spark UI (aux SURVEY §5.5).

    >>> with device_trace("/tmp/trace"):
    ...     model = workflow.train()
    """
    import contextlib

    import jax

    @contextlib.contextmanager
    def _trace():
        jax.profiler.start_trace(log_dir)
        try:
            yield log_dir
        finally:
            jax.profiler.stop_trace()
    return _trace()

_DEFAULT_CACHE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".jax_cache")


def _machine_fingerprint() -> str:
    """Cache namespace per CPU capability set: XLA:CPU AOT artifacts are
    machine-feature-specific, and loading one compiled for a different
    microarchitecture can SIGILL (cpu_aot_loader warns exactly this)."""
    import hashlib
    import platform
    ident = platform.machine()
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    ident += ",".join(sorted(line.split(":")[1].split()))
                    break
    except OSError:
        pass
    return hashlib.sha1(ident.encode()).hexdigest()[:12]


def enable_compilation_cache(path: str = None) -> str:
    """Turn on JAX's persistent compilation cache at ``path`` (defaults
    to ``<repo>/.jax_cache/<machine-fingerprint>``). Safe to call
    multiple times."""
    import jax
    path = path or os.environ.get("TX_JAX_CACHE_DIR", _DEFAULT_CACHE)
    path = os.path.join(path, _machine_fingerprint())
    os.makedirs(path, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except AttributeError:  # older jax without the knob
        pass
    return path


def pin_platform_from_env() -> None:
    """Honor a JAX_PLATFORMS env request via jax.config.

    In this environment the env var alone is NOT enough: a
    sitecustomize imports jax at interpreter start and the remote-TPU
    (axon) plugin can dial its tunnel during backend discovery even
    when the env filter says cpu — hanging indefinitely if the tunnel
    is down. ``jax.config.update("jax_platforms", ...)`` after import
    reliably avoids the dial, so entry points (examples, benches) call
    this once before first device use.
    """
    import os

    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
