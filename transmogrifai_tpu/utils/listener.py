"""Workflow execution metrics: per-stage timing/row collection.

TPU-native port of the reference OpSparkListener
(utils/src/main/scala/com/salesforce/op/utils/spark/
OpSparkListener.scala:56,136,164): where the reference hooks Spark's
stage-completed events to collect executor runtime / IO bytes, here
the workflow executor reports each stage's fit/transform wall time and
row count to an attached listener; ``AppMetrics`` aggregates per run
and serializes next to outputs (OpWorkflowRunner:145 behavior).
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

_log = logging.getLogger(__name__)

__all__ = ["StageMetric", "AppMetrics", "WorkflowListener"]


@dataclass
class StageMetric:
    """(reference StageMetrics, OpSparkListener.scala:164)

    ``compile_seconds`` is the XLA trace+lower+compile time observed
    while the stage ran (utils/compile_time.py) — first-call cost that
    a warm process never pays again. ``execute_seconds`` is the
    steady-state remainder; a compile-bound CPU run and a compute-bound
    accelerator run are indistinguishable without the split."""
    stage_name: str
    stage_uid: str
    phase: str             # "fit" | "transform"
    seconds: float
    n_rows: int
    compile_seconds: float = 0.0

    @property
    def execute_seconds(self) -> float:
        return max(0.0, self.seconds - self.compile_seconds)

    def to_json(self) -> dict:
        return {"stageName": self.stage_name, "stageUid": self.stage_uid,
                "phase": self.phase, "seconds": round(self.seconds, 6),
                "compileSeconds": round(self.compile_seconds, 6),
                "executeSeconds": round(self.execute_seconds, 6),
                "nRows": self.n_rows}


@dataclass
class AppMetrics:
    """(reference AppMetrics, OpSparkListener.scala:136)"""
    app_name: str = "transmogrifai_tpu"
    custom_tag_name: Optional[str] = None
    custom_tag_value: Optional[str] = None
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    stage_metrics: List[StageMetric] = field(default_factory=list)
    #: fault-runtime events observed during this run (retries,
    #: quarantines, journal resumes, plan fallbacks — runtime/
    #: telemetry.py). Empty — and absent from the JSON — on a
    #: fault-free run.
    fault_events: List[Dict] = field(default_factory=list)

    @property
    def app_duration(self) -> float:
        end = self.end_time if self.end_time is not None else time.time()
        return end - self.start_time

    def to_json(self) -> dict:
        out = {"appName": self.app_name,
               "customTagName": self.custom_tag_name,
               "customTagValue": self.custom_tag_value,
               "appDurationSeconds": round(self.app_duration, 3),
               "stageMetrics": [m.to_json() for m in self.stage_metrics]}
        if self.fault_events:
            out["faultEvents"] = self.fault_events
        return out

    def profile_pretty(self, top: int = 0) -> str:
        """Human per-stage profile, slowest first — the role of the
        reference's Spark-UI stage table (aux SURVEY §5.5); rendered
        with the same Table util summaryPretty uses."""
        from .table import Table
        rows = sorted(self.stage_metrics, key=lambda m: -m.seconds)
        if top:
            rows = rows[:top]
        total = sum(m.seconds for m in self.stage_metrics) or 1.0
        t = Table(
            ["stage", "phase", "seconds", "compile", "execute",
             "% of total", "rows"],
            [[m.stage_name, m.phase, f"{m.seconds:.3f}",
              f"{m.compile_seconds:.3f}", f"{m.execute_seconds:.3f}",
              f"{100.0 * m.seconds / total:.1f}%", m.n_rows]
             for m in rows],
            name=f"Stage profile ({self.app_name}, "
                 f"{self.app_duration:.2f}s wall)")
        return t.pretty()


class WorkflowListener:
    """Attach via ``Workflow.with_listener`` to collect per-stage metrics
    (reference collectStageMetrics / logStageMetrics, OpParams.scala:94)."""

    def __init__(self, log_stage_metrics: bool = False,
                 collect_stage_metrics: bool = True,
                 app_name: str = "transmogrifai_tpu"):
        self.log_stage_metrics = log_stage_metrics
        self.collect_stage_metrics = collect_stage_metrics
        self.metrics = AppMetrics(app_name=app_name)
        self._end_handlers: List[Callable[[AppMetrics], None]] = []
        # fault-runtime events after this mark belong to this run
        from ..runtime import telemetry as _rt
        self._fault_mark = _rt.events_mark()

    def on_stage_completed(self, stage, phase: str, seconds: float,
                           n_rows: int,
                           compile_seconds: float = 0.0) -> None:
        m = StageMetric(stage_name=stage.stage_name(), stage_uid=stage.uid,
                        phase=phase, seconds=seconds, n_rows=n_rows,
                        compile_seconds=min(compile_seconds, seconds))
        if self.collect_stage_metrics:
            self.metrics.stage_metrics.append(m)
        if self.log_stage_metrics:
            _log.info("stage %s %s: %.3fs over %d rows",
                      m.stage_name, phase, seconds, n_rows)

    def add_application_end_handler(
            self, fn: Callable[[AppMetrics], None]) -> None:
        """(reference OpWorkflowRunner.addApplicationEndHandler:145)"""
        self._end_handlers.append(fn)

    def on_application_end(self) -> None:
        self.metrics.end_time = time.time()
        # snapshot the fault-runtime events (retries/quarantines/
        # journal resumes) that happened during this run next to its
        # stage profile
        from ..runtime import telemetry as _rt
        self.metrics.fault_events = _rt.events_since(self._fault_mark)
        for fn in self._end_handlers:
            try:
                fn(self.metrics)
            except Exception:  # handlers must not break the run
                _log.exception("application-end handler failed")
