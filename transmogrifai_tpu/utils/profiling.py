"""Device-trace post-processing: per-op timing from a jax.profiler run.

`jax.profiler.start_trace` writes a Chrome-trace JSON
(``plugins/profile/<ts>/<host>.trace.json.gz``) whose DEVICE lanes carry
one complete event per XLA op execution — the accelerator-level
profile the reference delegates to the Spark UI (SURVEY §5.5 aux).
:func:`summarize_device_trace` reduces it to the top time-sink ops and a
device-busy figure so benchmarks can report utilization, not just
wall-clock (VERDICT r4 next-round #1).

On the CPU backend the trace contains only host python frames (no
device lanes) — callers fall back to the workflow listener's per-stage
profile there.
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["summarize_device_trace", "trace_and_summarize"]


def _newest_trace(log_dir: str) -> Optional[str]:
    paths = glob.glob(os.path.join(log_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    return max(paths, key=os.path.getmtime) if paths else None


def summarize_device_trace(log_dir: str, top: int = 5) -> Optional[Dict]:
    """Aggregate the newest trace under ``log_dir``.

    Returns ``{"top_ops": [(name, total_ms), ...], "device_busy_ms",
    "device_span_ms", "device_busy_pct", "device_lanes"}`` or None when
    the trace has no device lanes (CPU backend) or no trace exists."""
    path = _newest_trace(log_dir)
    if path is None:
        return None
    data = json.loads(gzip.open(path).read())
    events = data.get("traceEvents", [])
    # pid -> process name metadata; device lanes are "/device:..." (TPU)
    proc_names = {e.get("pid"): (e.get("args") or {}).get("name", "")
                  for e in events
                  if e.get("ph") == "M" and e.get("name") == "process_name"}
    device_pids = {pid for pid, name in proc_names.items()
                   if "/device:" in name and "CPU" not in name}
    if not device_pids:
        return None
    # a device pid carries OVERLAPPING thread lanes (module-level spans,
    # per-op events, step markers); summing them all double-counts — so
    # per pid keep the per-op lanes: every thread named "XLA Ops" or
    # "Stream ..." (genuinely concurrent lanes all count), falling back
    # to the single busiest lane when nothing is named
    thread_names: Dict[Tuple, str] = {
        (e.get("pid"), e.get("tid")): (e.get("args") or {}).get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"}
    lane_busy: collections.Counter = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in device_pids:
            lane_busy[(e.get("pid"), e.get("tid"))] += float(
                e.get("dur", 0.0))
    keep_lanes = set()
    for pid in device_pids:
        lanes = [k for k in lane_busy if k[0] == pid]
        if not lanes:
            continue
        named = [k for k in lanes
                 if any(t in thread_names.get(k, "").lower()
                        for t in ("xla ops", "stream"))]
        keep_lanes.update(named if named
                          else [max(lanes, key=lane_busy.__getitem__)])
    agg: collections.Counter = collections.Counter()
    t_min, t_max = float("inf"), 0.0
    busy = 0.0
    for e in events:
        if e.get("ph") != "X" or \
                (e.get("pid"), e.get("tid")) not in keep_lanes:
            continue
        dur = float(e.get("dur", 0.0))          # microseconds
        agg[e.get("name", "?")] += dur
        busy += dur
        ts = float(e.get("ts", 0.0))
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
    span = max(t_max - t_min, 1e-9)
    return {
        "top_ops": [(name, round(dur / 1000.0, 3))
                    for name, dur in agg.most_common(top)],
        "device_busy_ms": round(busy / 1000.0, 3),
        "device_span_ms": round(span / 1000.0, 3),
        # busy sums the kept per-op lanes of every DEVICE; dividing by
        # span x device count makes an 8-chip mesh at full tilt read
        # ~100 (it can exceed 100 only through real intra-device lane
        # concurrency, e.g. overlapped GPU streams)
        "device_busy_pct": round(
            100.0 * busy / (span * len(device_pids)), 2),
        "device_lanes": sorted(proc_names[p] for p in device_pids),
    }


def trace_and_summarize(fn, log_dir: str, top: int = 5
                        ) -> Tuple[object, Optional[Dict]]:
    """Run ``fn()`` under a device trace rooted at a FRESH subdirectory
    of ``log_dir`` and summarize it. Returns (fn result,
    summary-or-None). The per-run subdirectory guarantees a run that
    writes no trace reports None instead of silently summarizing a
    previous run's files."""
    import tempfile

    import jax
    os.makedirs(log_dir, exist_ok=True)
    run_dir = tempfile.mkdtemp(prefix="run_", dir=log_dir)
    jax.profiler.start_trace(run_dir)
    try:
        out = fn()
    finally:
        jax.profiler.stop_trace()
    return out, summarize_device_trace(run_dir, top=top)
