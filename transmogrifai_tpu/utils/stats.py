"""Statistics kernels used by SanityChecker / ModelInsights.

TPU-native port of the reference ``OpStatistics``
(utils/src/main/scala/com/salesforce/op/utils/stats/OpStatistics.scala:39-346):
Cramér's V, chi-squared, pointwise/plain mutual information, association-rule
max confidence + support, plus weighted column stats and label correlation
computed as XLA matrix ops (the reference used Spark's colStats + a
RowMatrix correlation — on TPU one fused matmul pass does it).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["ColStats", "col_stats", "correlation_with_label",
           "correlation_matrix", "ContingencyStats", "contingency_stats",
           "chi_square", "cramers_v"]


@dataclass
class ColStats:
    """Per-column moments (reference: Spark MultivariateStatisticalSummary
    usage in SanityChecker.fitFn:535)."""
    count: int
    mean: np.ndarray
    variance: np.ndarray
    min: np.ndarray
    max: np.ndarray
    num_nonzeros: np.ndarray


def col_stats(X, w: Optional[np.ndarray] = None) -> ColStats:
    """Weighted column statistics in one device pass."""
    X = jnp.asarray(X)
    n = X.shape[0]
    if w is None:
        w = jnp.ones((n,), X.dtype)
    else:
        w = jnp.asarray(w, X.dtype)
    wsum = jnp.sum(w)
    mean = (w @ X) / wsum
    var = (w @ (X - mean) ** 2) / jnp.maximum(wsum - 1.0, 1.0)
    live = w > 0
    big = jnp.where(live[:, None], X, jnp.inf)
    small = jnp.where(live[:, None], X, -jnp.inf)
    mn = jnp.min(big, axis=0)
    mx = jnp.max(small, axis=0)
    nnz = jnp.sum((X != 0) & live[:, None], axis=0)
    return ColStats(count=int(jnp.sum(live)), mean=np.asarray(mean),
                    variance=np.asarray(var), min=np.asarray(mn),
                    max=np.asarray(mx), num_nonzeros=np.asarray(nnz))


def correlation_matrix(X, w: Optional[np.ndarray] = None) -> np.ndarray:
    """Weighted Pearson correlation matrix via one gram matmul (MXU)."""
    X = jnp.asarray(X, jnp.float64 if X.dtype == np.float64 else jnp.float32)
    n = X.shape[0]
    w = jnp.ones((n,), X.dtype) if w is None else jnp.asarray(w, X.dtype)
    wsum = jnp.sum(w)
    mean = (w @ X) / wsum
    Xc = (X - mean) * jnp.sqrt(w)[:, None]
    # population normalization; the 1/wsum factor cancels in corr = cov/sd²,
    # so this matches col_stats' sample variance convention for correlations
    cov = (Xc.T @ Xc) / wsum
    sd = jnp.sqrt(jnp.diag(cov))
    denom = jnp.outer(sd, sd)
    corr = jnp.where(denom > 0, cov / jnp.where(denom > 0, denom, 1.0),
                     jnp.nan)
    return np.asarray(corr)


def correlation_with_label(X, y, w: Optional[np.ndarray] = None
                           ) -> np.ndarray:
    """Pearson correlation of each feature column with the label
    (the reference appends the label to the matrix and takes the last
    correlation row, SanityChecker.scala:535).

    Computed DIRECTLY per column — O(n·d) — with the same weighted
    population normalization as :func:`correlation_matrix`. The former
    append-and-gram implementation built the full (d+1)² correlation
    matrix to read one row: O(n·d²), the dominant SanityChecker fit
    cost on wide matrices (last-ulp differences vs the gram path are
    possible; only this column of it was ever consumed)."""
    # canonicalize first (as the former gram path did): under x64-off
    # this lands on f32 without requesting — and warning about — f64
    X = jnp.asarray(X)
    y = jnp.asarray(y, X.dtype).reshape(-1)
    n = X.shape[0]
    w = jnp.ones((n,), X.dtype) if w is None else jnp.asarray(w, X.dtype)
    wsum = jnp.sum(w)
    sw = jnp.sqrt(w)
    Xc = (X - (w @ X) / wsum) * sw[:, None]
    yc = (y - jnp.sum(w * y) / wsum) * sw
    cov = (yc @ Xc) / wsum
    sd = jnp.sqrt((jnp.sum(Xc * Xc, axis=0) / wsum)
                  * (jnp.sum(yc * yc) / wsum))
    corr = jnp.where(sd > 0, cov / jnp.where(sd > 0, sd, 1.0), jnp.nan)
    return np.asarray(corr)


@dataclass
class ContingencyStats:
    """Results of contingency-table analysis for one categorical group
    (reference OpStatistics.contingencyStats:117)."""
    chi2: float
    p_value: float
    cramers_v: float
    mutual_info: float
    pointwise_mutual_info: np.ndarray  # shape (n_levels, n_labels)
    max_rule_confidences: np.ndarray   # per categorical level
    supports: np.ndarray               # per categorical level


def chi_square(table: np.ndarray) -> Tuple[float, float, int]:
    """Pearson chi-squared statistic, p-value, dof for a contingency table."""
    t = np.asarray(table, dtype=np.float64)
    rows = t.sum(axis=1, keepdims=True)
    cols = t.sum(axis=0, keepdims=True)
    total = t.sum()
    if total <= 0:
        return 0.0, 1.0, 0
    keep_r = rows.ravel() > 0
    keep_c = cols.ravel() > 0
    t = t[keep_r][:, keep_c]
    rows, cols = rows[keep_r], cols[:, keep_c]
    expected = rows * cols / total
    with np.errstate(divide="ignore", invalid="ignore"):
        stat = float(np.nansum((t - expected) ** 2 / expected))
    dof = max((t.shape[0] - 1) * (t.shape[1] - 1), 0)
    if dof == 0:
        return stat, 1.0, 0
    from scipy.stats import chi2 as _chi2  # scipy ships with sklearn image
    p = float(_chi2.sf(stat, dof))
    return stat, p, dof


def cramers_v(table: np.ndarray) -> float:
    """Cramér's V (reference OpStatistics.cramersV:300, no bias correction
    beyond min-dimension normalization)."""
    t = np.asarray(table, dtype=np.float64)
    t = t[t.sum(axis=1) > 0][:, t.sum(axis=0) > 0]
    if t.size == 0:
        return float("nan")
    stat, _, _ = chi_square(t)
    n = t.sum()
    k = min(t.shape[0] - 1, t.shape[1] - 1)
    if n <= 0 or k <= 0:
        return float("nan")
    return float(np.sqrt(stat / (n * k)))


def contingency_stats(table: np.ndarray) -> ContingencyStats:
    """All association stats for one (categorical level x label) table
    (reference OpStatistics.contingencyStats:117-133)."""
    t = np.asarray(table, dtype=np.float64)
    total = t.sum()
    stat, p, _ = chi_square(t)
    cv = cramers_v(t)
    # mutual information (natural log base 2, matching reference log2 usage)
    with np.errstate(divide="ignore", invalid="ignore"):
        pxy = t / total if total > 0 else t
        px = pxy.sum(axis=1, keepdims=True)
        py = pxy.sum(axis=0, keepdims=True)
        pmi = np.log2(pxy / (px * py))
        pmi[~np.isfinite(pmi)] = 0.0
        mi = float(np.nansum(np.where(pxy > 0, pxy * pmi, 0.0)))
    row_tot = t.sum(axis=1)
    with np.errstate(divide="ignore", invalid="ignore"):
        conf = np.where(row_tot[:, None] > 0, t / row_tot[:, None], 0.0)
    max_conf = conf.max(axis=1) if t.size else np.zeros(0)
    support = row_tot / total if total > 0 else row_tot
    return ContingencyStats(chi2=stat, p_value=p, cramers_v=cv,
                            mutual_info=mi, pointwise_mutual_info=pmi,
                            max_rule_confidences=max_conf, supports=support)
