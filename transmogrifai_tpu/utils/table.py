"""ASCII table pretty-printer.

TPU-native port of the reference Table
(utils/src/main/scala/com/salesforce/op/utils/table/Table.scala), used
by ``summary_pretty`` reports.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

__all__ = ["Table"]


class Table:
    def __init__(self, columns: Sequence[str],
                 rows: Sequence[Sequence[Any]], name: str = ""):
        if not columns:
            raise ValueError("Table requires at least one column")
        for r in rows:
            if len(r) != len(columns):
                raise ValueError(
                    f"Row {r!r} has {len(r)} cells; expected {len(columns)}")
        self.columns = [str(c) for c in columns]
        self.rows = [[_fmt(c) for c in r] for r in rows]
        self.name = name

    def pretty(self) -> str:
        widths = [max(len(self.columns[j]),
                      *(len(r[j]) for r in self.rows)) if self.rows
                  else len(self.columns[j])
                  for j in range(len(self.columns))]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"

        def line(cells: Sequence[str]) -> str:
            return "|" + "|".join(
                f" {c:<{w}} " for c, w in zip(cells, widths)) + "|"

        out: List[str] = []
        if self.name:
            total = len(sep)
            out.append("=" * total)
            out.append(f"|{self.name:^{total - 2}}|")
        out.append(sep)
        out.append(line(self.columns))
        out.append(sep)
        for r in self.rows:
            out.append(line(r))
        out.append(sep)
        return "\n".join(out)

    def __str__(self) -> str:
        return self.pretty()


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)
