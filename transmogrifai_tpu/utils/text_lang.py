"""Language identification: Unicode-script routing + character n-gram
profiles.

Replaces the r3 stopword-vote heuristic with the standard two-stage
design real detectors use (the reference ships Optimaize language
detection, core/build.gradle — an n-gram profile model):

1. **Script routing.** A Unicode-block histogram decides the script;
   single-script languages resolve immediately (Hangul -> ko, kana ->
   ja, Han without kana -> zh, Greek -> el, Arabic -> ar, Hebrew -> he,
   Devanagari -> hi, Thai -> th). This is what makes non-Latin text
   work at all — the old Latin-only regex discarded it wholesale.
2. **Cavnar–Trenkle rank-order n-gram profiles** for languages sharing
   a script (Latin: en/fr/de/es/it/pt/nl; Cyrillic: ru/uk): character
   1–3-gram frequency ranks of the input are compared to per-language
   profiles by out-of-place distance. Profiles are built at import from
   embedded seed text (ordinary prose composed for this table — small,
   but rank-order matching is robust to profile size by design).

Host-side, pure Python: language detection runs in the pre-device text
pipeline (SURVEY §2.9 — JVM analyzers map to host equivalents).
"""
from __future__ import annotations

import re
import unicodedata
from collections import Counter
from typing import Dict, List, Optional, Tuple

__all__ = ["detect_language", "dominant_script", "ngram_profile",
           "profile_distance"]

# ---------------------------------------------------------------------------
# script routing
# ---------------------------------------------------------------------------

_SCRIPT_RANGES = (
    ("han", 0x4E00, 0x9FFF), ("han", 0x3400, 0x4DBF),
    ("hiragana", 0x3040, 0x309F), ("katakana", 0x30A0, 0x30FF),
    ("hangul", 0xAC00, 0xD7AF), ("hangul", 0x1100, 0x11FF),
    ("cyrillic", 0x0400, 0x04FF),
    ("greek", 0x0370, 0x03FF),
    ("arabic", 0x0600, 0x06FF), ("arabic", 0x0750, 0x077F),
    ("hebrew", 0x0590, 0x05FF),
    ("devanagari", 0x0900, 0x097F),
    ("thai", 0x0E00, 0x0E7F),
    ("latin", 0x0041, 0x024F),
)


def _char_script(ch: str) -> Optional[str]:
    cp = ord(ch)
    for name, lo, hi in _SCRIPT_RANGES:
        if lo <= cp <= hi:
            return name
    return None


def dominant_script(text: str) -> Optional[str]:
    """Most frequent script among letter characters; None if no letters."""
    counts: Counter = Counter()
    for ch in text:
        if ch.isalpha():
            s = _char_script(ch)
            if s:
                counts[s] += 1
    if not counts:
        return None
    return counts.most_common(1)[0][0]


#: scripts that identify a language on their own (the ambiguity left —
#: e.g. Han covers zh AND ja kanji — is resolved below)
_SCRIPT_LANG = {"hangul": "ko", "greek": "el", "arabic": "ar",
                "hebrew": "he", "devanagari": "hi", "thai": "th"}

# ---------------------------------------------------------------------------
# Cavnar–Trenkle profiles
# ---------------------------------------------------------------------------

#: embedded seed prose per Latin/Cyrillic language (ordinary sentences
#: composed for this table; everyday vocabulary so the character
#: statistics are representative)
_SEED_TEXT = {
    "en": ("the quick brown fox jumps over the lazy dog. she said that "
           "they would come to the house in the morning and bring with "
           "them all the things that we had asked for. it is not what "
           "you know but who you know. there are many people who think "
           "that the world would be better with more kindness and this "
           "is something we can all agree with. when the weather is "
           "good the children play outside until the evening."),
    "fr": ("le chien et le chat sont dans le jardin de la maison. elle a "
           "dit qu'ils viendraient demain matin avec toutes les choses "
           "que nous avions demandées. ce n'est pas ce que vous savez "
           "mais qui vous connaissez. il y a beaucoup de gens qui "
           "pensent que le monde serait meilleur avec plus de "
           "gentillesse et c'est quelque chose que nous pouvons tous "
           "accepter. quand il fait beau les enfants jouent dehors "
           "jusqu'au soir."),
    "de": ("der schnelle braune fuchs springt über den faulen hund. sie "
           "sagte dass sie morgen früh kommen würden und alle dinge "
           "mitbringen die wir verlangt hatten. es ist nicht was du "
           "weißt sondern wen du kennst. es gibt viele menschen die "
           "denken dass die welt mit mehr freundlichkeit besser wäre "
           "und dem können wir alle zustimmen. wenn das wetter schön "
           "ist spielen die kinder draußen bis zum abend. guten morgen "
           "und guten abend sagen die leute hier jeden tag. ich habe "
           "heute keine zeit aber vielleicht können wir nächste woche "
           "zusammen essen gehen. das buch liegt auf dem tisch neben "
           "dem fenster und gehört meinem bruder."),
    "es": ("el perro y el gato están en el jardín de la casa. ella dijo "
           "que vendrían mañana por la mañana y traerían todas las "
           "cosas que habíamos pedido. no es lo que sabes sino a quién "
           "conoces. hay mucha gente que piensa que el mundo sería "
           "mejor con más amabilidad y es algo con lo que todos podemos "
           "estar de acuerdo. cuando hace buen tiempo los niños juegan "
           "afuera hasta la noche."),
    "it": ("il cane e il gatto sono nel giardino della casa. ha detto "
           "che sarebbero venuti domani mattina e avrebbero portato "
           "tutte le cose che avevamo chiesto. non è quello che sai ma "
           "chi conosci. ci sono molte persone che pensano che il mondo "
           "sarebbe migliore con più gentilezza e questo è qualcosa su "
           "cui tutti possiamo essere d'accordo. quando il tempo è "
           "bello i bambini giocano fuori fino a sera."),
    "pt": ("o cão e o gato estão no jardim da casa. ela disse que "
           "viriam amanhã de manhã e trariam todas as coisas que "
           "tínhamos pedido. não é o que você sabe mas quem você "
           "conhece. há muitas pessoas que pensam que o mundo seria "
           "melhor com mais gentileza e isso é algo com que todos "
           "podemos concordar. quando o tempo está bom as crianças "
           "brincam lá fora até a noite."),
    "nl": ("de snelle bruine vos springt over de luie hond. ze zei dat "
           "ze morgenochtend zouden komen en alle dingen meebrengen "
           "waar we om hadden gevraagd. het is niet wat je weet maar "
           "wie je kent. er zijn veel mensen die denken dat de wereld "
           "beter zou zijn met meer vriendelijkheid en daar kunnen we "
           "het allemaal mee eens zijn. als het weer mooi is spelen de "
           "kinderen buiten tot de avond. goedemorgen en goedenavond "
           "zeggen de mensen hier elke dag. ik heb vandaag geen tijd "
           "maar misschien kunnen we volgende week samen uit eten "
           "gaan. het boek ligt op de tafel naast het raam en is van "
           "mijn broer."),
    "ru": ("быстрая коричневая лиса прыгает через ленивую собаку. она "
           "сказала что они придут завтра утром и принесут все вещи "
           "которые мы просили. важно не то что ты знаешь а кого ты "
           "знаешь. есть много людей которые думают что мир был бы "
           "лучше с большей добротой и с этим мы все можем "
           "согласиться. когда погода хорошая дети играют на улице до "
           "вечера."),
    "uk": ("швидка коричнева лисиця стрибає через ледачого пса. вона "
           "сказала що вони прийдуть завтра вранці і принесуть усі "
           "речі які ми просили. важливо не те що ти знаєш а кого ти "
           "знаєш. є багато людей які думають що світ був би кращим з "
           "більшою добротою і з цим ми всі можемо погодитися. коли "
           "погода гарна діти граються надворі до вечора."),
}

_PROFILE_SIZE = 300
_SCRIPT_LANGS = {
    "latin": ("en", "fr", "de", "es", "it", "pt", "nl"),
    "cyrillic": ("ru", "uk"),
}


def _normalize(text: str) -> str:
    text = unicodedata.normalize("NFC", text.lower())
    return re.sub(r"[^\w\s']|\d", " ", text)


def ngram_profile(text: str, max_n: int = 3,
                  size: Optional[int] = _PROFILE_SIZE) -> List[str]:
    """Character 1..max_n-grams ranked by frequency (Cavnar–Trenkle);
    word-boundary padded with spaces as the original formulation."""
    counts: Counter = Counter()
    for word in _normalize(text).split():
        padded = f" {word} "
        for n in range(1, max_n + 1):
            for i in range(len(padded) - n + 1):
                g = padded[i:i + n]
                if not g.isspace():
                    counts[g] += 1
    ranked = [g for g, _ in counts.most_common(size)]
    return ranked


def profile_distance(doc_profile: List[str],
                     lang_profile: List[str]) -> int:
    """Out-of-place distance: sum over document n-grams of the rank
    difference vs the language profile (missing = max penalty)."""
    pos = {g: i for i, g in enumerate(lang_profile)}
    max_pen = len(lang_profile)
    return sum(abs(pos.get(g, max_pen) - i)
               for i, g in enumerate(doc_profile))


_PROFILES: Dict[str, List[str]] = {
    lang: ngram_profile(seed) for lang, seed in _SEED_TEXT.items()}


def detect_language(text: Optional[str],
                    default: str = "unknown") -> Tuple[str, float]:
    """(language code, confidence in [0, 1]). Script-routed, n-gram
    resolved; ``default`` when the text carries no signal."""
    if not text or not text.strip():
        return default, 0.0
    script = dominant_script(text)
    if script is None:
        return default, 0.0
    if script in _SCRIPT_LANG:
        return _SCRIPT_LANG[script], 1.0
    if script in ("hiragana", "katakana"):
        return "ja", 1.0
    if script == "han":
        # Han + any kana = Japanese; pure Han = Chinese
        if any(_char_script(c) in ("hiragana", "katakana") for c in text):
            return "ja", 1.0
        return "zh", 0.9
    langs = _SCRIPT_LANGS.get(script)
    if not langs:
        return default, 0.0
    doc = ngram_profile(text, size=_PROFILE_SIZE)
    if not doc:
        return default, 0.0
    dists = {lang: profile_distance(doc, _PROFILES[lang])
             for lang in langs}
    ranked = sorted(dists.items(), key=lambda kv: kv[1])
    best, best_d = ranked[0]
    worst_d = max(len(doc) * _PROFILE_SIZE, 1)
    margin = ((ranked[1][1] - best_d) / max(ranked[1][1], 1)
              if len(ranked) > 1 else 1.0)
    confidence = max(0.0, min(1.0, 1.0 - best_d / worst_d)) * 0.5 \
        + min(1.0, margin * 5.0) * 0.5
    # a couple of words is weak evidence for same-script languages
    # (closely related pairs like de/nl need statistics to separate) —
    # damp the confidence so min_confidence gates can actually act on
    # short inputs instead of confidently-wrong labels
    confidence *= min(1.0, len(doc) / 80.0)
    return best, confidence
