"""Name-entity tagging — host-side heuristic tagger.

TPU-native stand-in for the reference's OpenNLP statistical NER
(utils/.../text/NameEntityTagger.scala:71-86 NameEntityType enum,
core/.../utils/text/OpenNLPNameEntityTagger.scala): the image ships no
OpenNLP-style maxent models, so tagging is rule/gazetteer-based —
honorific-introduced capitalized spans tag Person, corporate suffixes
Organization, a compact country/city gazetteer Location, and
month/clock/currency/percent patterns Date/Time/Money/Percentage.
Deterministic, dependency-free, and (like the reference's text stack,
SURVEY §2.9) strictly a pre-device host pass.
"""
from __future__ import annotations

import re
from typing import Dict, List, Sequence, Set, Tuple

__all__ = ["NameEntityType", "HeuristicNameEntityTagger",
           "split_sentences"]


class NameEntityType:
    """(reference NameEntityTagger.scala:71-86)"""
    Date = "Date"
    Location = "Location"
    Money = "Money"
    Organization = "Organization"
    Percentage = "Percentage"
    Person = "Person"
    Time = "Time"
    Misc = "Misc"
    Other = "Other"
    values = (Date, Location, Money, Organization, Percentage, Person,
              Time, Misc, Other)


_HONORIFICS = {"mr", "mr.", "mrs", "mrs.", "ms", "ms.", "dr", "dr.",
               "prof", "prof.", "sir", "madam", "president", "senator",
               "judge", "captain", "governor", "mayor", "chancellor",
               "minister", "ceo", "st", "st."}
_ORG_SUFFIXES = {"inc", "inc.", "corp", "corp.", "co", "co.", "ltd",
                 "ltd.", "llc", "plc", "gmbh", "ag", "sa", "nv", "oy",
                 "company", "corporation", "university", "institute",
                 "bank", "group", "holdings", "industries", "systems",
                 "technologies", "laboratories", "labs", "partners",
                 "foundation", "association", "agency", "ministry",
                 "department", "committee", "council"}
_LOCATIONS = {
    # countries / regions
    "usa", "u.s.", "u.s.a.", "uk", "u.k.", "france", "germany", "spain",
    "italy", "china", "japan", "india", "canada", "australia", "brazil",
    "mexico", "russia", "england", "scotland", "wales", "ireland",
    "america", "europe", "asia", "africa", "netherlands", "belgium",
    "switzerland", "austria", "sweden", "norway", "denmark", "finland",
    "poland", "portugal", "greece", "turkey", "egypt", "israel",
    "argentina", "chile", "colombia", "peru", "korea", "vietnam",
    "thailand", "indonesia", "malaysia", "singapore", "philippines",
    "nigeria", "kenya", "morocco", "ukraine",
    # cities
    "paris", "london", "tokyo", "berlin", "madrid", "rome", "moscow",
    "beijing", "shanghai", "sydney", "melbourne", "toronto", "vancouver",
    "montreal", "chicago", "boston", "seattle", "francisco", "york",
    "angeles", "dallas", "houston", "miami", "atlanta", "denver",
    "phoenix", "philadelphia", "amsterdam", "brussels", "vienna",
    "zurich", "geneva", "munich", "hamburg", "frankfurt", "barcelona",
    "lisbon", "dublin", "stockholm", "oslo", "copenhagen", "helsinki",
    "warsaw", "prague", "budapest", "athens", "istanbul", "cairo",
    "mumbai", "delhi", "bangalore", "seoul", "osaka", "taipei",
    "jakarta", "bangkok", "manila", "lagos", "nairobi",
    # US states
    "california", "texas", "washington", "florida", "oregon", "arizona",
    "nevada", "colorado", "georgia", "virginia", "ohio", "michigan",
    "illinois", "massachusetts", "pennsylvania", "carolina", "alaska",
    "hawaii", "utah", "montana", "idaho", "kansas", "iowa", "missouri",
}
#: common given names — the gazetteer backbone of the Person tag (the
#: OpenNLP maxent model's role; a name list + context cues is the
#: classic statistical-NER fallback)
_GIVEN_NAMES = {
    "james", "john", "robert", "michael", "william", "david", "richard",
    "joseph", "thomas", "charles", "christopher", "daniel", "matthew",
    "anthony", "mark", "donald", "steven", "paul", "andrew", "joshua",
    "kenneth", "kevin", "brian", "george", "edward", "ronald", "timothy",
    "jason", "jeffrey", "ryan", "jacob", "gary", "nicholas", "eric",
    "jonathan", "stephen", "larry", "justin", "scott", "brandon",
    "benjamin", "samuel", "gregory", "frank", "alexander", "raymond",
    "patrick", "jack", "dennis", "jerry", "peter", "henry", "adam",
    "mary", "patricia", "jennifer", "linda", "elizabeth", "barbara",
    "susan", "jessica", "sarah", "karen", "nancy", "lisa", "betty",
    "margaret", "sandra", "ashley", "kimberly", "emily", "donna",
    "michelle", "dorothy", "carol", "amanda", "melissa", "deborah",
    "stephanie", "rebecca", "sharon", "laura", "cynthia", "kathleen",
    "amy", "angela", "shirley", "anna", "brenda", "pamela", "emma",
    "nicole", "helen", "samantha", "katherine", "christine", "debra",
    "rachel", "catherine", "carolyn", "janet", "ruth", "maria",
    "heather", "diane", "virginia", "julie", "joyce", "victoria",
    "olivia", "kelly", "christina", "alice", "julia", "grace", "sofia",
    "ahmed", "mohammed", "ali", "omar", "hassan", "fatima", "aisha",
    "wei", "jing", "li", "chen", "yuki", "hiroshi", "kenji", "sakura",
    "raj", "priya", "arjun", "ananya", "ivan", "dmitri", "olga",
    "natasha", "pierre", "marie", "jean", "sophie", "hans", "klaus",
    "ingrid", "carlos", "jose", "juan", "ana", "lucia", "marco",
    "giulia", "lars", "erik", "astrid",
}
#: verbs/cues whose capitalized neighbor is very likely a Person
_PERSON_CUE_AFTER = {"said", "says", "told", "met", "asked", "replied",
                     "wrote", "argued", "announced", "stated", "noted",
                     "added", "explained", "warned"}
#: prepositions whose capitalized object is very likely a Location
_LOC_PREPS = {"in", "at", "from", "near", "to", "toward", "towards"}
#: connectors allowed INSIDE a multi-token proper-noun span
_SPAN_CONNECTORS = {"of", "the", "de", "da", "del", "della", "van",
                    "von", "bin", "al", "el", "la", "le"}
_MONTHS = {"january", "february", "march", "april", "may", "june", "july",
           "august", "september", "october", "november", "december",
           "jan", "feb", "mar", "apr", "jun", "jul", "aug", "sep", "sept",
           "oct", "nov", "dec"}
_WEEKDAYS = {"monday", "tuesday", "wednesday", "thursday", "friday",
             "saturday", "sunday"}

_TIME_RE = re.compile(r"^\d{1,2}:\d{2}(:\d{2})?([ap]m)?$", re.IGNORECASE)
_MONEY_RE = re.compile(r"^[$€£¥]\d[\d,.]*[kmb]?$", re.IGNORECASE)
_PCT_RE = re.compile(r"^\d[\d,.]*%$")
_YEAR_RE = re.compile(r"^(1[89]|20)\d\d$")
_SENT_RE = re.compile(r"(?<=[.!?])\s+(?=[A-Z0-9\"'(])")
_TOKEN_RE = re.compile(r"[^\s]+")


def split_sentences(text: str) -> List[str]:
    """Sentence split on terminal punctuation followed by a capital,
    with abbreviation/honorific periods rejoined ("Dr. Alice" is not a
    boundary) — the reference OpenNLPSentenceSplitter's role."""
    text = (text or "").strip()
    if not text:
        return []
    parts = [s for s in _SENT_RE.split(text) if s]
    merged: List[str] = []
    no_break = _HONORIFICS | _ORG_SUFFIXES | {"no.", "vs.", "etc.", "e.g.",
                                              "i.e.", "jr.", "sr."}
    for part in parts:
        if merged and merged[-1].rsplit(None, 1)[-1].lower() in no_break:
            merged[-1] += " " + part
        else:
            merged.append(part)
    return merged


def _strip(tok: str) -> str:
    return tok.strip(".,;:!?\"'()[]{}")


def _is_cap(tok: str) -> bool:
    return bool(tok) and (tok[:1].isupper() and not tok.isupper()
                          or (tok.isupper() and len(tok) > 1))


class HeuristicNameEntityTagger:
    """tag(sentence) -> {token: {entity types}}
    (reference NameEntityTagger.tag returning TaggerResult.tokenTags).

    Gazetteer + context tagger (the r4 upgrade of the r3 45-line
    heuristic): capitalized spans are assembled first (connector words
    like "of"/"van" allowed inside), then each span is classified by —
    in priority order — corporate suffix (Organization), location
    gazetteer (Location), honorific / given-name gazetteer / reporting-
    verb context (Person), locative preposition (Location). Numeric
    patterns (time/money/percent/date) tag independently per token."""

    def tag(self, sentence: str,
            entities: Sequence[str] = NameEntityType.values
            ) -> Dict[str, Set[str]]:
        raw = _TOKEN_RE.findall(sentence or "")
        toks = [_strip(t) for t in raw]
        lows = [t.lower() for t in toks]
        tags: Dict[str, Set[str]] = {}
        want = set(entities)

        def add(token: str, ent: str) -> None:
            if ent in want and token:
                tags.setdefault(token, set()).add(ent)

        # numeric / calendar patterns, token-local
        for tok, low in zip(toks, lows):
            if _TIME_RE.match(tok):
                add(tok, NameEntityType.Time)
            if _MONEY_RE.match(tok):
                add(tok, NameEntityType.Money)
            if _PCT_RE.match(tok):
                add(tok, NameEntityType.Percentage)
            if low in _MONTHS or low in _WEEKDAYS or _YEAR_RE.match(tok):
                add(tok, NameEntityType.Date)

        # assemble capitalized spans (connectors allowed inside)
        spans: List[Tuple[int, int]] = []      # [start, end) token idx
        i = 0
        n = len(toks)
        while i < n:
            if _is_cap(toks[i]) and lows[i] not in _HONORIFICS:
                j = i + 1
                while j < n and (
                        _is_cap(toks[j])
                        or (lows[j] in _SPAN_CONNECTORS and j + 1 < n
                            and _is_cap(toks[j + 1]))):
                    j += 1
                spans.append((i, j))
                i = j
            else:
                i += 1

        worklist = list(spans)
        while worklist:
            start, end = worklist.pop(0)
            span_lows = lows[start:end]
            span_toks = [t for t in toks[start:end] if t]
            prev = lows[start - 1] if start else ""
            is_sent_start = start == 0

            def add_span(ent: str, skip_connectors: bool = True,
                         _s=start, _e=end) -> None:
                for t, lo in zip(toks[_s:_e], lows[_s:_e]):
                    # only LOWERCASE connectors are glue ("Jean de la
                    # Fontaine"); a capitalized homograph is part of
                    # the name itself ("Al Gore", "La Paz")
                    if (skip_connectors and lo in _SPAN_CONNECTORS
                            and not _is_cap(t)):
                        continue
                    add(t, ent)

            # 0. a connector-bridged span opening with PERSON evidence
            #    ("Dr. Alice Smith of Acme Corp") splits at the first
            #    connector: the head is the person, the tail re-enters
            #    classification on its own
            if prev in _HONORIFICS or span_lows[0] in _GIVEN_NAMES:
                split = next((c for c, lo in enumerate(span_lows)
                              if lo in _SPAN_CONNECTORS
                              and not _is_cap(toks[start + c])), None)
                if split is not None:
                    for t in toks[start:start + split]:
                        add(t, NameEntityType.Person)
                    worklist.insert(0, (start + split + 1, end))
                    continue

            # 1. corporate suffix anywhere in span -> Organization
            if any(lo in _ORG_SUFFIXES for lo in span_lows):
                add_span(NameEntityType.Organization,
                         skip_connectors=False)
                continue
            # 2. location gazetteer hit -> Location
            if any(lo in _LOCATIONS for lo in span_lows):
                add_span(NameEntityType.Location)
                continue
            # 3. Person evidence: honorific before, given-name first
            #    token, or a reporting verb adjacent
            nxt = lows[end] if end < n else ""
            if (prev in _HONORIFICS
                    or span_lows[0] in _GIVEN_NAMES
                    or nxt in _PERSON_CUE_AFTER
                    or prev in _PERSON_CUE_AFTER):
                add_span(NameEntityType.Person)
                continue
            # 4. locative preposition before a non-sentence-initial span
            if prev in _LOC_PREPS and not is_sent_start:
                add_span(NameEntityType.Location)
                continue
            # 5. multi-token capitalized span mid-sentence, no other
            #    evidence -> likely Person (OpenNLP's majority case)
            if not is_sent_start and len(span_toks) >= 2:
                add_span(NameEntityType.Person)
        return tags
