"""Name-entity tagging — host-side heuristic tagger.

TPU-native stand-in for the reference's OpenNLP statistical NER
(utils/.../text/NameEntityTagger.scala:71-86 NameEntityType enum,
core/.../utils/text/OpenNLPNameEntityTagger.scala): the image ships no
OpenNLP-style maxent models, so tagging is rule/gazetteer-based —
honorific-introduced capitalized spans tag Person, corporate suffixes
Organization, a compact country/city gazetteer Location, and
month/clock/currency/percent patterns Date/Time/Money/Percentage.
Deterministic, dependency-free, and (like the reference's text stack,
SURVEY §2.9) strictly a pre-device host pass.
"""
from __future__ import annotations

import re
from typing import Dict, List, Sequence, Set, Tuple

__all__ = ["NameEntityType", "HeuristicNameEntityTagger",
           "split_sentences"]


class NameEntityType:
    """(reference NameEntityTagger.scala:71-86)"""
    Date = "Date"
    Location = "Location"
    Money = "Money"
    Organization = "Organization"
    Percentage = "Percentage"
    Person = "Person"
    Time = "Time"
    Misc = "Misc"
    Other = "Other"
    values = (Date, Location, Money, Organization, Percentage, Person,
              Time, Misc, Other)


_HONORIFICS = {"mr", "mr.", "mrs", "mrs.", "ms", "ms.", "dr", "dr.",
               "prof", "prof.", "sir", "president", "senator", "judge",
               "captain", "st", "st."}
_ORG_SUFFIXES = {"inc", "inc.", "corp", "corp.", "co", "co.", "ltd",
                 "ltd.", "llc", "plc", "gmbh", "ag", "company",
                 "corporation", "university", "institute", "bank"}
_LOCATIONS = {
    "paris", "london", "tokyo", "berlin", "madrid", "rome", "moscow",
    "beijing", "sydney", "toronto", "chicago", "boston", "seattle",
    "francisco", "york", "angeles", "usa", "u.s.", "uk", "france",
    "germany", "spain", "italy", "china", "japan", "india", "canada",
    "australia", "brazil", "mexico", "russia", "england", "america",
    "europe", "asia", "africa", "california", "texas", "washington",
}
_MONTHS = {"january", "february", "march", "april", "may", "june", "july",
           "august", "september", "october", "november", "december",
           "jan", "feb", "mar", "apr", "jun", "jul", "aug", "sep", "sept",
           "oct", "nov", "dec"}
_WEEKDAYS = {"monday", "tuesday", "wednesday", "thursday", "friday",
             "saturday", "sunday"}

_TIME_RE = re.compile(r"^\d{1,2}:\d{2}(:\d{2})?([ap]m)?$", re.IGNORECASE)
_MONEY_RE = re.compile(r"^[$€£¥]\d[\d,.]*[kmb]?$", re.IGNORECASE)
_PCT_RE = re.compile(r"^\d[\d,.]*%$")
_YEAR_RE = re.compile(r"^(1[89]|20)\d\d$")
_SENT_RE = re.compile(r"(?<=[.!?])\s+(?=[A-Z0-9\"'(])")
_TOKEN_RE = re.compile(r"[^\s]+")


def split_sentences(text: str) -> List[str]:
    """Sentence split on terminal punctuation followed by a capital,
    with abbreviation/honorific periods rejoined ("Dr. Alice" is not a
    boundary) — the reference OpenNLPSentenceSplitter's role."""
    text = (text or "").strip()
    if not text:
        return []
    parts = [s for s in _SENT_RE.split(text) if s]
    merged: List[str] = []
    no_break = _HONORIFICS | _ORG_SUFFIXES | {"no.", "vs.", "etc.", "e.g.",
                                              "i.e.", "jr.", "sr."}
    for part in parts:
        if merged and merged[-1].rsplit(None, 1)[-1].lower() in no_break:
            merged[-1] += " " + part
        else:
            merged.append(part)
    return merged


def _strip(tok: str) -> str:
    return tok.strip(".,;:!?\"'()[]{}")


class HeuristicNameEntityTagger:
    """tag(sentence) -> {token: {entity types}}
    (reference NameEntityTagger.tag returning TaggerResult.tokenTags)."""

    def tag(self, sentence: str,
            entities: Sequence[str] = NameEntityType.values
            ) -> Dict[str, Set[str]]:
        raw = _TOKEN_RE.findall(sentence or "")
        toks = [_strip(t) for t in raw]
        tags: Dict[str, Set[str]] = {}
        want = set(entities)

        def add(token: str, ent: str) -> None:
            if ent in want and token:
                tags.setdefault(token, set()).add(ent)

        for i, (rtok, tok) in enumerate(zip(raw, toks)):
            low = tok.lower()
            if _TIME_RE.match(tok):
                add(tok, NameEntityType.Time)
            if _MONEY_RE.match(tok):
                add(tok, NameEntityType.Money)
            if _PCT_RE.match(tok):
                add(tok, NameEntityType.Percentage)
            if low in _MONTHS or low in _WEEKDAYS or _YEAR_RE.match(tok):
                add(tok, NameEntityType.Date)
            if low in _LOCATIONS and tok[:1].isupper():
                add(tok, NameEntityType.Location)
            cap = tok[:1].isupper() and not tok.isupper() or \
                (tok.isupper() and len(tok) > 1)
            if not cap or low in _HONORIFICS:
                continue
            prev = toks[i - 1].lower() if i else ""
            nxt = toks[i + 1].lower() if i + 1 < len(toks) else ""
            # corporate suffix tags the capitalized span before it
            if nxt in _ORG_SUFFIXES or low in _ORG_SUFFIXES and i:
                add(tok, NameEntityType.Organization)
                if low in _ORG_SUFFIXES:
                    add(toks[i - 1], NameEntityType.Organization)
                continue
            # honorific-introduced or capitalized-bigram mid-sentence span
            if prev in _HONORIFICS:
                add(tok, NameEntityType.Person)
                if i + 1 < len(toks) and toks[i + 1][:1].isupper():
                    add(toks[i + 1], NameEntityType.Person)
                continue
            prev_cap = i > 0 and toks[i - 1][:1].isupper() \
                and toks[i - 1].lower() not in _HONORIFICS
            if i > 0 and prev_cap and tags.get(toks[i - 1]) \
                    and NameEntityType.Person in tags[toks[i - 1]]:
                add(tok, NameEntityType.Person)
            elif i > 0 and not prev_cap and i + 1 < len(toks) \
                    and toks[i + 1][:1].isupper() \
                    and _strip(toks[i + 1]).lower() not in _ORG_SUFFIXES \
                    and low not in _LOCATIONS:
                # mid-sentence capitalized bigram start -> likely Person
                add(tok, NameEntityType.Person)
                add(toks[i + 1], NameEntityType.Person)
        return tags
