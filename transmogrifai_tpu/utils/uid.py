"""Unique-ID generation for stages and features.

TPU-native re-design of the reference's class-prefixed 12-hex UIDs
(reference: utils/src/main/scala/com/salesforce/op/utils/spark/UID.scala:42).
Deterministic per-process counter mode is supported for reproducible tests
(the reference resets UIDs via ``UID.reset()``).
"""
from __future__ import annotations

import secrets
import threading

_lock = threading.Lock()
_counters: dict[str, int] = {}
_deterministic = False


def uid(prefix: str | type) -> str:
    """Generate a UID of form ``<ClassName>_<12 hex>``."""
    name = prefix if isinstance(prefix, str) else prefix.__name__
    global _deterministic
    with _lock:
        if _deterministic:
            c = _counters.get(name, 0)
            _counters[name] = c + 1
            return f"{name}_{c:012x}"
        return f"{name}_{secrets.token_hex(6)}"


def reset(deterministic: bool = True) -> None:
    """Reset counters; if ``deterministic``, subsequent UIDs are sequential."""
    global _deterministic
    with _lock:
        _counters.clear()
        _deterministic = deterministic


def from_string(s: str) -> tuple[str, str]:
    """Split ``Prefix_hex`` into (prefix, id). Raises ValueError if malformed."""
    if "_" not in s:
        raise ValueError(f"Invalid UID: {s!r}")
    prefix, _, rest = s.rpartition("_")
    if not prefix or not rest:
        raise ValueError(f"Invalid UID: {s!r}")
    return prefix, rest
