"""Vector column provenance metadata — the framework's metadata spine.

TPU-native port of the reference's ``OpVectorMetadata`` /
``OpVectorColumnMetadata`` (features/src/main/scala/com/salesforce/op/utils/
spark/OpVectorMetadata.scala:49, OpVectorColumnMetadata.scala). Every
vectorizer records, per output column: the parent raw feature, its type,
optional grouping (e.g. map key or categorical group), optional indicator
value (one-hot level) and descriptor value (e.g. "sin(HourOfDay)").
SanityChecker, ModelInsights and LOCO all key off this record.

Unlike the reference, metadata travels attached to the in-memory
``FeatureColumn`` rather than hidden in Spark column metadata.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Iterable, Optional, Sequence

__all__ = ["VectorColumnMetadata", "VectorMetadata", "NULL_INDICATOR",
           "OTHER_INDICATOR"]

#: indicator value used for null-tracking columns
NULL_INDICATOR = "NullIndicatorValue"
#: indicator value used for the one-hot "other" bucket
OTHER_INDICATOR = "OTHER"


@dataclass(frozen=True)
class VectorColumnMetadata:
    """Provenance of a single column in a feature vector."""
    parent_feature_name: str
    parent_feature_type: str
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    descriptor_value: Optional[str] = None
    index: int = 0

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_INDICATOR

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_INDICATOR

    def grouping_key(self) -> tuple:
        """Key identifying the indicator group this column belongs to
        (reference OpVectorColumnMetadata.grouping semantics): one-hot
        columns of the same parent+grouping form one categorical group."""
        return (self.parent_feature_name, self.grouping)

    def column_name(self, vector_name: str) -> str:
        parts = [self.parent_feature_name]
        if self.grouping is not None:
            parts.append(self.grouping)
        if self.descriptor_value is not None:
            parts.append(self.descriptor_value)
        if self.indicator_value is not None:
            parts.append(self.indicator_value)
        return "_".join(parts) + f"_{self.index}"

    def to_json(self) -> dict:
        return {
            "parentFeatureName": self.parent_feature_name,
            "parentFeatureType": self.parent_feature_type,
            "grouping": self.grouping,
            "indicatorValue": self.indicator_value,
            "descriptorValue": self.descriptor_value,
            "index": self.index,
        }

    @staticmethod
    def from_json(d: dict) -> "VectorColumnMetadata":
        return VectorColumnMetadata(
            parent_feature_name=d["parentFeatureName"],
            parent_feature_type=d["parentFeatureType"],
            grouping=d.get("grouping"),
            indicator_value=d.get("indicatorValue"),
            descriptor_value=d.get("descriptorValue"),
            index=d.get("index", 0),
        )


@dataclass(frozen=True)
class VectorMetadata:
    """Metadata for a whole feature vector (OpVectorMetadata.scala:49)."""
    name: str
    columns: tuple[VectorColumnMetadata, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "columns", tuple(
            replace(c, index=i) for i, c in enumerate(self.columns)))

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> list[str]:
        return [c.column_name(self.name) for c in self.columns]

    def indicator_groups(self) -> dict[tuple, list[int]]:
        """Group column indices by (parent feature, grouping) for columns that
        are categorical indicators — used by SanityChecker's Cramér's V and
        group-aware pruning (reference OpVectorMetadata.getColumnHistory:120)."""
        groups: dict[tuple, list[int]] = {}
        for c in self.columns:
            if c.indicator_value is not None:
                groups.setdefault(c.grouping_key(), []).append(c.index)
        return groups

    def parent_groups(self) -> dict[str, list[int]]:
        """Column indices grouped by parent raw feature name."""
        groups: dict[str, list[int]] = {}
        for c in self.columns:
            groups.setdefault(c.parent_feature_name, []).append(c.index)
        return groups

    def select(self, indices: Sequence[int], name: Optional[str] = None
               ) -> "VectorMetadata":
        """Metadata for a column subset (vector surgery / pruning)."""
        return VectorMetadata(
            name=name or self.name,
            columns=tuple(self.columns[i] for i in indices))

    @staticmethod
    def flatten(name: str, metas: Iterable["VectorMetadata"]
                ) -> "VectorMetadata":
        """Concatenate vector metadatas (OpVectorMetadata.flatten:242)."""
        cols: list[VectorColumnMetadata] = []
        for m in metas:
            cols.extend(m.columns)
        return VectorMetadata(name=name, columns=tuple(cols))

    def to_json(self) -> dict:
        return {"name": self.name,
                "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: dict) -> "VectorMetadata":
        return VectorMetadata(
            name=d["name"],
            columns=tuple(VectorColumnMetadata.from_json(c)
                          for c in d["columns"]))
