"""Build/version info embedded in saved artifacts.

TPU-native port of the reference VersionInfo
(utils/src/main/scala/com/salesforce/op/utils/version/VersionInfo.scala)
which bakes the git sha into the jar; here it is resolved lazily from
the repository (or an env override) and attached to model JSON.
"""
from __future__ import annotations

import os
import subprocess
from dataclasses import dataclass
from typing import Optional

__all__ = ["VersionInfo", "version_info"]

VERSION = "0.1.0"


@dataclass(frozen=True)
class VersionInfo:
    version: str
    git_sha: Optional[str] = None
    git_branch: Optional[str] = None

    def to_json(self) -> dict:
        return {"version": self.version, "gitSha": self.git_sha,
                "gitBranch": self.git_branch}


def _git(*args: str) -> Optional[str]:
    try:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        out = subprocess.run(["git", *args], cwd=repo, capture_output=True,
                             text=True, timeout=5)
        return out.stdout.strip() or None
    except Exception:
        return None


def version_info() -> VersionInfo:
    sha = os.environ.get("TX_GIT_SHA") or _git("rev-parse", "HEAD")
    branch = _git("rev-parse", "--abbrev-ref", "HEAD")
    return VersionInfo(version=VERSION, git_sha=sha, git_branch=branch)
