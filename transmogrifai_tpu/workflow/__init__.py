"""Workflow engine (SURVEY §2.4; core/.../OpWorkflow.scala:332)."""
from .persistence import load_model, save_model
from .runner import OpParams, RunResult, RunType, WorkflowRunner
from .workflow import Workflow, WorkflowModel

__all__ = ["Workflow", "WorkflowModel", "save_model", "load_model",
           "OpParams", "WorkflowRunner", "RunType", "RunResult"]
