"""Workflow engine (SURVEY §2.4; core/.../OpWorkflow.scala:332)."""
from .workflow import Workflow, WorkflowModel

__all__ = ["Workflow", "WorkflowModel"]
